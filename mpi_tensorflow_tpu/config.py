"""Run configuration.

The reference has no config system — four module-level constants at
mpipy.py:18-21 (``iteration = 2``, ``image_size = 28``, ``batch_size = 64``,
``num_channel = 10`` — the last is the class count, misnamed) and zero CLI
flags.  Zero-flag invocation of our CLI must reproduce those defaults
(BASELINE.json: "Keep the script's original CLI"), so every default below
matches the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class Config:
    # --- reference knobs (mpipy.py:18-21) ---
    epochs: int = 2               # ``iteration`` at mpipy.py:18
    image_size: int = 28          # mpipy.py:19
    batch_size: int = 64          # global batch; per-shard = batch_size in the
                                  # reference (each rank steps its own batch of
                                  # 64, mpipy.py:80-82). ``scale_batch`` below
                                  # controls which semantics we reproduce.
    num_classes: int = 10         # ``num_channel`` at mpipy.py:21 (misnamed)

    # --- optimizer / schedule (mpipy.py:55-66) ---
    base_lr: float = 0.01
    lr_decay: float = 0.95
    momentum: float = 0.9
    weight_decay: float = 5e-4    # L2 on fc params only (mpipy.py:57-58)

    # --- loop / reporting (mpipy.py:87-90) ---
    log_every: int = 50           # 50-step console cadence
    eval_every: int = 50          # reference evaluates EVERY step
                                  # (mpipy.py:86) — an accidental cost; we
                                  # evaluate on the log cadence and keep it off
                                  # the timed path (BASELINE.md measurement rule)
    early_stop_patience: int = 0  # >0: stop when validation error hasn't
                                  # improved for N trace points.  The
                                  # reference scatters validation shards and
                                  # never reads them (mpipy.py:236-241, dead
                                  # data); 0 keeps that faithful default,
                                  # >0 puts the split to work

    # --- parallelism ---
    sync: str = "psum"            # "psum": per-step gradient summation (the
                                  # north-star semantics) | "avg50": periodic
                                  # parameter averaging, the reference's
                                  # strategy (mpipy.py:95-153) with the rank-0-
                                  # only bug fixed (all ranks receive the mean)
    fused_steps: int = 1          # steps executed per device dispatch in the
                                  # psum loop (lax.scan over staged batches,
                                  # train/step.py make_multi_train_step).
                                  # 1 = one dispatch per step (the
                                  # reference's execution shape); the CLI
                                  # defaults to the 50-step trace cadence on
                                  # TPU, where dispatch latency dominates
                                  # tiny steps
    remat: bool = False           # transformer-layer rematerialization
                                  # (jax.checkpoint): recompute activations
                                  # in the backward pass to cut peak HBM
    text_file: Optional[str] = None  # real-text corpus for the LM families
                                  # (data/corpus.py); None = synthetic
    vocab_file: Optional[str] = None  # WordPiece vocab (one token/line,
                                  # BERT vocab.txt layout) for --text-file
                                  # runs: real-vocab training exercises the
                                  # packed/chunked MLM head at flagship
                                  # vocab size; None = byte-level (261)
    param_sharding: str = "replicated"  # transformer-family state layout:
                                  # "replicated" (pure DP/TP/PP rules),
                                  # "fsdp" (params+moments data-sharded,
                                  # ZeRO-3-style via GSPMD), or "zero1"
                                  # (params keep their layout, moments
                                  # data-sharded — composes with PP)
    prefetch: str = "auto"        # window-assembly prefetch for the fused
                                  # loop: "auto" (native C++ worker when
                                  # built, else Python thread), "native",
                                  # "thread", "off" (inline assembly)
    pp_schedule: str = "gpipe"    # pipeline-parallel training schedule:
                                  # "gpipe" (scanned fwd pipeline, autodiff
                                  # backward), "1f1b" (one-forward-one-
                                  # backward — same bubble, O(P) stash), or
                                  # "1f1b_interleaved" (v virtual chunks
                                  # per device: bubble / v, 2P-deep rings)
    virtual_stages: int = 2       # chunks/device for "1f1b_interleaved"
    grad_accum: int = 1           # microbatches per step: grads accumulate
                                  # on-device (lax.scan) before the single
                                  # allreduce+update — same semantics, 1/A
                                  # the activation memory
    scale_batch: bool = True      # True: per-device batch = batch_size, i.e.
                                  # global batch grows with the mesh — the
                                  # reference's behavior (each rank independently
                                  # slices 64 rows, mpipy.py:80-82)
    mesh_shape: Optional[dict] = None  # e.g. {"data": 8}; None = all devices
                                       # on one "data" axis

    # --- serving (continuous-batching decode engine, serving/) ---
    serve_pool_blocks: int = 128  # paged-KV pool size in blocks (block 0
                                  # reserved as the null/scratch block);
                                  # HBM cost = blocks * block_size * 2KV
                                  # * heads * head_dim * layers * dtype
    serve_block_size: int = 16    # cache entries per pool block
    serve_max_slots: int = 8      # concurrent sequences (decode batch cap)
    serve_max_seq_len: int = 512  # per-request prompt+output cap; also
                                  # sizes the per-sequence block table
    serve_kernel: str = "auto"    # paged-attention lowering: auto (fused
                                  # Pallas kernel on TPU when the compile
                                  # probe passes, else XLA gather), xla
                                  # (force the exact gather fallback),
                                  # pallas (force the kernel; interpret
                                  # mode off TPU — the test path)
    serve_kv_dtype: str = "fp32"  # paged-pool storage format: "fp32"
                                  # (blocks in the model compute dtype —
                                  # byte-for-byte the pre-quantization
                                  # behavior) | "int8" (symmetric-absmax
                                  # codes + per-(block, head, slot) fp32
                                  # row scales: ~4x effective KV
                                  # capacity, dequantized inside the
                                  # attention consume paths; greedy
                                  # outputs track fp32 at a token-match-
                                  # rate gate, not token identity) |
                                  # "int4" (two nibble-packed codes per
                                  # byte + per-group fp32 scales along
                                  # head_dim + a KIVI fp-residual self
                                  # lane: the next capacity rung, same
                                  # token-match-rate gate)
    serve_kv_group: int = 32      # int4 scale-group size along head_dim
                                  # (one fp32 scale per group; clamped
                                  # to head_dim on tiny heads, must
                                  # divide it).  Consumed only under
                                  # serve_kv_dtype=int4
    serve_kv_tier: str = "off"    # host-RAM block tier: "host" demotes
                                  # cold prefix-cache blocks to host
                                  # memory on eviction and promotes
                                  # them back into fresh device blocks
                                  # when a later prompt matches their
                                  # trie path (multi-turn sessions stop
                                  # re-paying prefill); requires
                                  # serve_prefix_cache=on; "off" is
                                  # byte-for-byte untiered
    serve_prefix_cache: str = "off"  # radix prefix cache: "on" shares
                                  # already-cached full prompt blocks
                                  # across requests (refcounted, copy-
                                  # on-write, LRU trie eviction under
                                  # pool pressure); "off" preserves the
                                  # unshared behavior byte-for-byte
    serve_prefix_gen: str = "off"  # prefix cache v2 extensions: "on"
                                  # additionally caches a finished
                                  # request's GENERATED full blocks in
                                  # the trie (follow-up turns that embed
                                  # the prior answer hit them) and
                                  # shares partial tail blocks via a
                                  # one-compile row-prefix copy; "off"
                                  # keeps prefix_cache=on behavior
                                  # byte-for-byte; requires
                                  # serve_prefix_cache=on
    serve_prefix_route: str = "off"  # prefix-aware fleet routing: "on"
                                  # biases sessionless placement toward
                                  # the replica whose trie caches the
                                  # prompt's leading full block (load-
                                  # bounded, never overrides the health
                                  # gate, never changes tokens); "off"
                                  # keeps affinity+least-load routing;
                                  # requires serve_prefix_cache=on
    serve_speculative: str = "off"  # speculative decoding: "ngram"
                                  # (n-gram self-draft, zero extra
                                  # model), "draft-model" (tiny-model
                                  # drafter over its own paged pool);
                                  # drafts verify in ONE batched
                                  # forward and only the argmax-
                                  # matching prefix is emitted, so
                                  # greedy outputs are token-identical
                                  # to "off" (which preserves the one-
                                  # token decode loop byte-for-byte)
    serve_draft_k: int = 4        # draft window: tokens proposed per
                                  # verify forward (dispatch width is
                                  # draft_k + 1)
    serve_draft_auto: str = "off"  # auto-tune the draft window: "on"
                                  # adapts the effective k to an EWMA
                                  # of the observed accepted length,
                                  # clamped to [1, serve_draft_k] (the
                                  # dispatch width never changes, so
                                  # no recompiles); "off" drafts the
                                  # configured k every step
    serve_mixed_batch: str = "off"  # stall-free mixed batching: "on"
                                  # fuses budget-capped prefill chunks
                                  # from MULTIPLE mid-prefill sequences
                                  # into the decode dispatch, so every
                                  # step is ONE forward (chunked-prefill
                                  # math; decode is the chunk=1 case)
                                  # — lower dispatches per emitted
                                  # token and lower TTFT under bursty
                                  # admission; "off" preserves the
                                  # two-dispatch prefill-then-decode
                                  # loop byte-for-byte
    serve_prefill_budget: int = 64  # mixed batching: max prefill
                                  # tokens fused into one step across
                                  # all mid-prefill sequences; bounds
                                  # the decode-latency tax a step can
                                  # pay for prompt ingestion (consumed
                                  # only with serve_mixed_batch=on)
    serve_tp: int = 1             # tensor-parallel shards for the
                                  # decode engine: >1 partitions the
                                  # paged pool's head axis, the QKV/O
                                  # projections, and the MLP over a
                                  # ``tp`` mesh axis (serving/tp) with
                                  # one psum per row-parallel output;
                                  # must divide the model's heads and
                                  # mlp dims and fit the device count
    serve_replicas: int = 1       # data-parallel engine replicas
                                  # fronted by serving/router: each has
                                  # its own pool/scheduler; requests
                                  # place by session affinity then
                                  # least-load (queue depth, occupancy,
                                  # shed rate).  1 = no router layer
    # fault-tolerance policy (serving/engine.ServeConfig; None = off)
    serve_deadline_ms: Optional[float] = None  # default per-request TTL
                                  # from arrival; expired work fails
                                  # with deadline_exceeded instead of
                                  # occupying a slot
    serve_queue_depth: Optional[int] = None    # bound on the waiting
                                  # queue; a full queue load-sheds the
                                  # newest submit (backpressure)
    serve_max_evictions: Optional[int] = None  # preemption-livelock
                                  # guard: a request evicted more than
                                  # this many times fails with
                                  # evicted_too_often
    serve_drain_ms: Optional[float] = None     # graceful-drain budget
                                  # after SIGTERM; in-flight work past
                                  # it is cut with status `drained`
                                  # (None = finish all in-flight)
    serve_failover_backoff_ms: float = 50.0    # replica circuit
                                  # breaker (serving/router): base
                                  # probe backoff after a transient
                                  # replica fault, doubled per
                                  # consecutive fault and capped at
                                  # 64x before the replica is rebuilt
                                  # and probed back into rotation
    serve_workload: str = "poisson"  # synthetic trace shape for bench
                                  # --mode serving (serving/loadgen):
                                  # poisson | bursty | multi-tenant |
                                  # diurnal; poisson replays the
                                  # historical trace byte-for-byte
    serve_slo_ms: Optional[float] = None       # per-request latency
                                  # budget stamped as Request.deadline;
                                  # the goodput metric (tokens/sec
                                  # within budget) keys on it (None =
                                  # no SLO)
    serve_trace: str = "off"      # request-lifecycle + step-phase
                                  # tracing (serving/tracing): off | on.
                                  # off = byte-for-byte untraced
                                  # behavior; on adds host-side span
                                  # stamps (zero device syncs) and the
                                  # `breakdown` block in bench detail
    serve_trace_out: Optional[str] = None      # Chrome trace-event JSON
                                  # path (open in Perfetto or
                                  # chrome://tracing); requires
                                  # serve_trace=on

    # --- checkpointing (absent from the reference; SURVEY.md §5) ---
    checkpoint_dir: Optional[str] = None   # None = checkpointing off
    resume: bool = False                   # resume from latest in the dir

    # --- metrics sink (SURVEY.md §5 metrics row; the reference has only
    #     the stdout trace, mpipy.py:88) ---
    metrics_dir: Optional[str] = None      # TensorBoard events + JSONL here

    # --- precision (TPU-first: bf16 on the MXU, fp32 master params) ---
    precision: str = "fp32"       # "fp32" | "bf16": compute dtype for the
                                  # forward/backward matmuls+convs; parameters,
                                  # optimizer state and loss stay float32.
                                  # fp32 default keeps bit-level comparability
                                  # with the reference (mpipy.py is float32
                                  # throughout)

    optimizer: str = "adamw"      # transformer-family optimizer: "adamw"
                                  # | "lamb" (layer-wise trust ratios, the
                                  # large-batch BERT recipe — You et al.
                                  # 2019).  The image families keep the
                                  # reference's momentum SGD (mpipy.py:65)

    # --- misc ---
    prng_impl: str = "threefry"   # PRNG for the training rng stream
                                  # (dropout masks): "threefry" (JAX default,
                                  # splittable, bit-reproducible across
                                  # backends) | "rbg" | "unsafe_rbg" (XLA
                                  # RngBitGenerator — far cheaper mask
                                  # generation on TPU; rbg keys also shard
                                  # cleanly under GSPMD).  A BERT train step
                                  # runs 25 (B,S,E) mask generations, so the
                                  # generator choice is a first-order
                                  # throughput knob (scripts/bert_diagnose.py
                                  # measures the delta); parameter INIT always
                                  # uses threefry so init is bit-identical
                                  # across prng arms
    seed: int = 1                 # the reference seeds everything with 1
                                  # (mpipy.py:40, 43, 48, 52, 166)
    dropout_rate: float = 0.5     # mpipy.py:166
    data_dir: str = "./data"      # mpipy.py:187
    model: str = "mnist_cnn"      # flagship families: mnist_cnn, resnet20,
                                  # resnet50, bert_base, moe_bert
    dataset: str = "mnist"

    @property
    def num_channels(self) -> int:
        """Input channels (1 for MNIST)."""
        return 1

    def make_train_key(self, seed: int):
        """Training rng stream keyed per ``prng_impl``.  The impl travels
        with the key through every ``fold_in`` inside the jitted step, so
        this one call site decides the dropout-mask generator."""
        import jax

        impl = {"threefry": "threefry2x32"}.get(self.prng_impl,
                                                self.prng_impl)
        return jax.random.key(seed, impl=impl)

    @property
    def compute_dtype(self):
        """The jnp dtype the forward/backward matmuls run in."""
        import jax.numpy as jnp

        if self.precision == "bf16":
            return jnp.bfloat16
        if self.precision == "fp32":
            return jnp.float32
        raise ValueError(f"unknown precision {self.precision!r}")
