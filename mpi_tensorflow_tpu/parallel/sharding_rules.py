"""Logical-axis sharding rules: how parameters and activations map to the mesh.

Models annotate every parameter with *logical* axis names (``("embed",
"mlp")`` etc.); a rule table maps logical names to mesh axes.  Swapping the
rule table re-lays-out the same model for a different mesh (pure DP, DP+TP,
DP+TP+SP) without touching model code — the TPU-native replacement for the
reference's hard-wired single-strategy replication (SURVEY.md §2 checklist:
TP/SP absent from the reference; required by the framework goal).

Default rules implement the Megatron layout: attention heads and MLP hidden
sharded over ``model`` (column-parallel in, row-parallel out), batch over
``data``, sequence over ``seq`` for ring attention.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# logical axis -> mesh axis (None = replicate)
DEFAULT_RULES: dict[str, Optional[str]] = {
    "batch": "data",
    "seq": "seq",
    "embed": None,       # hidden/residual stream replicated
    "heads": "model",    # attention heads tensor-parallel
    "head_dim": None,
    "mlp": "model",      # MLP hidden tensor-parallel
    "vocab": "model",    # embedding/LM-head vocab-parallel
    "pos": None,
    "classes": None,
    "expert": "expert",  # MoE expert stacks expert-parallel (models/moe.py)
    "expert_classes": None,   # router output dim (small) replicated
    "capacity": None,    # per-expert token buffer dim (models/moe.py)
    "stage": "pipe",     # pipeline-stage stacks (parallel/pipeline.py)
    "layer": None,       # within-stage layer dim (models/bert_pipeline.py)
    "vchunk": None,      # interleaved virtual-chunk dim (1f1b_interleaved)
}

# Serving tensor-parallel rules (serving/tp): ONLY the head- and
# mlp-sharded dims map to the ``tp`` axis — the Megatron column/row split
# of attention and MLP.  embed/vocab/pos stay replicated so after the two
# per-layer psum points (attention out-proj, MLP down-proj) every shard
# holds the identical residual stream and computes identical logits; the
# paged KV pool follows ``heads`` (its axis 1), which is why a block
# table that indexes BLOCKS, not heads, replicates cleanly.
SERVING_TP_RULES: dict[str, Optional[str]] = {
    "heads": "tp",
    "mlp": "tp",
}


def spec_for(logical_axes: tuple, rules: Mapping[str, Optional[str]],
             mesh: Mesh) -> PartitionSpec:
    """PartitionSpec for one tensor: map each logical axis through the rules,
    dropping mesh axes the mesh doesn't have (or that are size 1)."""
    out = []
    for ax in logical_axes:
        mesh_ax = rules.get(ax)
        if mesh_ax is not None and mesh.shape.get(mesh_ax, 1) > 1:
            out.append(mesh_ax)
        else:
            out.append(None)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def tree_specs(logical_tree: Any, mesh: Mesh,
               rules: Optional[Mapping[str, Optional[str]]] = None) -> Any:
    """Pytree of logical-axis tuples -> pytree of PartitionSpecs."""
    rules = rules if rules is not None else DEFAULT_RULES
    return jax.tree.map(lambda axes: spec_for(axes, rules, mesh),
                        logical_tree,
                        is_leaf=lambda x: isinstance(x, tuple))


def shard_tree(tree: Any, logical_tree: Any, mesh: Mesh,
               rules: Optional[Mapping[str, Optional[str]]] = None) -> Any:
    """Place a pytree of arrays onto the mesh per the rules."""
    specs = tree_specs(logical_tree, mesh, rules)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        tree, specs)


def constrain(x, logical_axes: tuple, mesh: Mesh,
              rules: Optional[Mapping[str, Optional[str]]] = None):
    """``with_sharding_constraint`` by logical axes, inside jit."""
    rules = rules if rules is not None else DEFAULT_RULES
    spec = spec_for(logical_axes, rules, mesh)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
