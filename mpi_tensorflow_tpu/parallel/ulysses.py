"""Ulysses (all-to-all) sequence parallelism — the second SP strategy.

Ring attention (parallel/ring.py) keeps K/V moving around a ring of
neighbors; DeepSpeed-Ulysses-style attention instead *re-shards* with two
all-to-alls: heads are exchanged for sequence so every shard holds the FULL
sequence for ``H / n`` heads, runs an ordinary (or flash) attention locally,
and the output is re-sharded back to sequence-parallel layout.

Trade-offs vs ring (why the framework offers both):
- communication is 2 all-to-alls of the activations per attention call,
  independent of sequence length — cheaper than the ring's ``n-1`` K/V hops
  when heads are plentiful and ICI all-to-all bandwidth is good;
- the local attention sees the full (S, S) score matrix for its heads, so
  per-shard memory is O(S^2 / n) score rows with a plain kernel (the ring
  stays O(S_local^2)) — pair it with the flash kernel for long S;
- requires ``num_heads % n == 0``; the ring has no such constraint.

No counterpart exists in the reference (no attention at all — SURVEY.md §2
parallelism checklist); first-class long-context support is a framework goal.

Call ``ulysses_attention`` inside ``shard_map`` with the ``seq`` axis in
scope, exactly like ``ring.ring_attention`` (equivalence with dense attention
on the gathered sequence is pinned in tests/test_ulysses.py).
"""

from __future__ import annotations

from typing import Callable, Optional

from jax import lax

from mpi_tensorflow_tpu.parallel import ring


def ulysses_attention(q, k, v, axis_name: str = "seq", *,
                      causal: bool = False, scale: Optional[float] = None,
                      inner: Optional[Callable] = None):
    """All-to-all sequence-parallel attention.

    q, k, v: (B, H, S_local, D) per shard, sequence-sharded over
    ``axis_name``.  Requires ``H`` divisible by the axis size.  ``inner``
    overrides the local attention kernel (default: ``ring.dense_attention``;
    pass a flash kernel for long sequences).
    """
    n = lax.axis_size(axis_name)
    H = q.shape[1]
    if H % n != 0:
        raise ValueError(
            f"ulysses needs num_heads ({H}) divisible by the '{axis_name}' "
            f"axis size ({n}); use ring attention otherwise")
    if n == 1:
        attn = inner if inner is not None else ring.dense_attention
        return attn(q, k, v, causal=causal, scale=scale)

    # reshard: split heads, gather sequence -> (B, H/n, S_global, D).
    # shard i holds sequence block i, so the tiled concat along axis 2
    # reassembles blocks in global order.
    qh = lax.all_to_all(q, axis_name, 1, 2, tiled=True)
    kh = lax.all_to_all(k, axis_name, 1, 2, tiled=True)
    vh = lax.all_to_all(v, axis_name, 1, 2, tiled=True)

    attn = inner if inner is not None else ring.dense_attention
    o = attn(qh, kh, vh, causal=causal, scale=scale)

    # reshard back: split sequence, gather heads -> (B, H, S_local, D)
    return lax.all_to_all(o, axis_name, 2, 1, tiled=True)
