"""Device mesh and process bootstrap — the communication-backend layer.

Replaces the reference's MPI world setup (``MPI.COMM_WORLD`` +
``Get_rank``/``Get_size``, mpipy.py:208-210) with the TPU-native equivalent:
``jax.distributed.initialize()`` for multi-host process setup over DCN, and a
``jax.sharding.Mesh`` whose named axes carry the parallelism strategy.  On a
mesh, collectives ride ICI and are inserted by XLA — there is no explicit
rank-indexed message passing to write.

Default topology is a 1-D ``('data',)`` mesh over all devices (pure DP, the
reference's only strategy).  Multi-axis meshes (``data`` x ``model`` x
``seq``) drive TP/SP for the transformer families.
"""

from __future__ import annotations

import os
from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def _distributed_client_active() -> bool:
    """Whether ``jax.distributed.initialize`` has already run.

    Must NOT call ``jax.process_count()``: that initializes the XLA
    backend, after which ``jax.distributed.initialize`` is a hard error —
    the old guard made every real (non-monkeypatched) multi-process
    bring-up fail.  Found by the 2-process bring-up test
    (tests/test_distributed_bringup.py)."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:        # private-API drift: fall back, accept the cost
        return jax.process_count() > 1


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (the ``mpiexec`` equivalent).

    On TPU pods the arguments are auto-detected from the environment; calling
    with no arguments is correct there.  Safe no-op for single-process runs
    and when already initialized.
    """
    if _distributed_client_active():
        return  # already initialized
    explicit = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    auto_env = any(v in os.environ for v in
                   ("TPU_WORKER_HOSTNAMES", "CLOUD_TPU_TASK_ID"))
    if explicit or (auto_env and os.environ.get("TPU_WORKER_HOSTNAMES") != "localhost"):
        try:
            jax.distributed.initialize(coordinator_address, num_processes,
                                       process_id)
        except (RuntimeError, ValueError) as e:
            # A pod that was configured for multi-host but failed to
            # initialize must NOT silently degrade to single-process
            # training (it would train on 1/N of the data at 1/N scale
            # with no error) — the mpiexec equivalent of a rank failing
            # to join COMM_WORLD is a launch failure.
            raise RuntimeError(
                "distributed initialization failed for an explicitly "
                f"configured multi-host launch (coordinator="
                f"{explicit or 'auto-detected env'}): {e}") from e


def make_mesh(shape: Optional[Mapping[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build the device mesh.

    ``shape`` maps axis name -> size, e.g. ``{"data": 4, "model": 2}``.
    ``None`` puts every device on one ``data`` axis.  An axis sized -1 absorbs
    the remaining devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = {"data": len(devices)}
    names = tuple(shape.keys())
    sizes = list(shape.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {len(devices)}")
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, names)


def data_axis_size(mesh: Mesh) -> int:
    return mesh.shape.get("data", 1)


def process_index() -> int:
    """The ``comm.Get_rank()`` analogue, but per host (mpipy.py:209)."""
    return jax.process_index()


def process_count() -> int:
    """The ``comm.Get_size()`` analogue, but per host (mpipy.py:210)."""
    return jax.process_count()


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Leading-dim sharding over the data axis — how input batches live."""
    return NamedSharding(mesh, PartitionSpec(axis))
