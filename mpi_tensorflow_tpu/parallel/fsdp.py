"""ZeRO/FSDP-style fully-sharded parameters and optimizer state.

The reference has nothing of the kind: its optimizer state is per-rank and
never communicated (SURVEY.md §2 parallelism checklist, "ZeRO/FSDP-style
sharded optimizer state: Absent"; mpipy.py:65-66), and every rank holds a
full replica of the model (mpipy.py:38-53).  On TPU the idiomatic
equivalent is *compiler-side* FSDP: store each parameter (and therefore its
optimizer moments, which inherit the placement) sharded along the ``data``
mesh axis, and let XLA GSPMD insert the all-gather at each use site in the
forward/backward and a reduce-scatter for the gradients.  No hand-written
gather/scatter schedule — the sharding annotation IS the strategy.

Composition with tensor parallelism is free: ``augment_spec`` only claims
dimensions the logical sharding rules left unsharded, so a Megatron-TP
weight sharded over ``model`` additionally shards a second dimension over
``data`` (the standard 2-D "FSDP x TP" layout).
"""

from __future__ import annotations

import math
from typing import Any, Mapping, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from mpi_tensorflow_tpu.parallel import sharding_rules as rules_lib

# Parameters smaller than this stay replicated: the all-gather latency would
# cost more than the HBM the shard saves (biases, layernorm scales, ...).
DEFAULT_MIN_SIZE = 1024


def augment_spec(spec: PartitionSpec, shape: tuple, mesh: Mesh,
                 axis: str = "data",
                 min_size: int = DEFAULT_MIN_SIZE) -> PartitionSpec:
    """Add ``axis`` to one tensor's PartitionSpec, FSDP-style.

    Shards the largest dimension that (a) the existing spec leaves
    unsharded and (b) is divisible by the mesh-axis size.  Returns the spec
    unchanged when the tensor is too small, the axis is already used, or no
    dimension divides evenly (an uneven shard would force XLA padding).
    """
    n = mesh.shape.get(axis, 1)
    if n <= 1 or math.prod(shape) < min_size:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    used = set()
    for e in entries:
        if e is None:
            continue
        used.update(e if isinstance(e, tuple) else (e,))
    if axis in used:
        return spec
    best = -1
    for d, dim in enumerate(shape):
        if entries[d] is None and dim % n == 0 and dim >= n:
            if best < 0 or dim > shape[best]:
                best = d
    if best < 0:
        return spec
    entries[best] = axis
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def fsdp_tree_specs(params: Any, mesh: Mesh,
                    logical_tree: Optional[Any] = None,
                    rules: Optional[Mapping[str, Optional[str]]] = None,
                    axis: str = "data",
                    min_size: int = DEFAULT_MIN_SIZE) -> Any:
    """PartitionSpec pytree for FSDP placement.

    Starts from the logical-axis rules when the model provides them (so TP
    axes are preserved) and replication otherwise, then augments every
    parameter with the ``data`` axis.
    """
    if logical_tree is not None:
        base = rules_lib.tree_specs(logical_tree, mesh, rules)
    else:
        base = jax.tree.map(lambda x: PartitionSpec(), params)
    return jax.tree.map(
        lambda x, spec: augment_spec(spec, x.shape, mesh, axis, min_size),
        params, base)


def shard_params(params: Any, mesh: Mesh, specs: Any) -> Any:
    """Place a parameter pytree per the FSDP specs."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def zero1_shard_opt(opt_state: Any, mesh: Mesh, axis: str = "data",
                    min_size: int = DEFAULT_MIN_SIZE) -> Any:
    """ZeRO-1: re-place an optimizer state with every moment tensor
    additionally sharded over ``axis``, leaving the PARAMETERS' layout
    untouched.

    This is the composition the manual pipeline schedules need: stage
    params must keep their ``pipe``-sharded, data-replicated placement
    (the 1F1B/GPipe ``shard_map`` in_specs are a contract about layout),
    but the Adam moments — 2x param memory — only appear in the optax
    update OUTSIDE the schedule, at the GSPMD level, where XLA inserts
    the grad reduce-scatter into the moment shards and the update
    all-gather back to the replicated params automatically.

    Works on any optax state with no param-tree bookkeeping: ``tx.init``
    builds moments via ``zeros_like(param)``, which PRESERVES each
    param's NamedSharding — so augmenting every array leaf's own spec
    with ``axis`` yields exactly "param layout + data", TP/PP axes
    included.  Scalars (step counts) and already-``axis``-sharded leaves
    pass through unchanged."""
    def place(x):
        sh = getattr(x, "sharding", None)
        if not isinstance(sh, NamedSharding) or getattr(x, "ndim", 0) == 0:
            return x
        spec = augment_spec(sh.spec, x.shape, mesh, axis, min_size)
        if spec == sh.spec:
            return x
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(place, opt_state)


def state_out_shardings(state: Any):
    """Derive jit ``out_shardings`` from an already-placed state pytree —
    pins parameters AND optimizer moments back to their FSDP shards after
    the update, so the compiler cannot 'helpfully' leave them gathered."""
    return jax.tree.map(lambda x: x.sharding, state)
