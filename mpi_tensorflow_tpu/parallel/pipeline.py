"""Pipeline parallelism (PP): GPipe-style microbatched stage pipeline.

The layer stack is split into P stages whose parameters live sharded over a
``pipe`` mesh axis (one stage per shard).  A batch is cut into M microbatches
that flow stage-to-stage through ``ppermute`` neighbor hops: at tick t, stage
s processes microbatch t-s while its neighbors work on adjacent microbatches
— the classic pipeline schedule with (P-1) bubble ticks around M useful ones.
The whole schedule is a ``lax.scan``, so reverse-mode autodiff derives the
backward pipeline automatically (the transpose of ``ppermute`` is the
reverse hop).

No counterpart in the reference (SURVEY.md §2 checklist: PP absent); part of
the full parallelism-strategy coverage.  Use ``pipeline`` inside
``shard_map`` with the ``pipe`` axis in scope — see ``make_pipelined_fn`` for
the jit-ready wrapper.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline(stage_fn: Callable, stage_params: Any, microbatches,
             axis: str = "pipe", with_mb_index: bool = False):
    """Run ``stage_fn(params, x) -> y`` as a P-stage pipeline.

    Inside ``shard_map``: ``stage_params`` is this shard's stage parameters,
    ``microbatches`` has shape (M, mb, ...) and must hold the SAME full set
    of microbatches on every shard (replicated over ``axis``); the result is
    the final stage's outputs, (M, mb, ...), valid on every shard.

    ``with_mb_index=True`` calls ``stage_fn(params, x, mb_idx)`` where
    ``mb_idx`` is the index of the microbatch this stage is processing at
    the current tick (clipped to [0, M-1] during bubble ticks, whose outputs
    are discarded anyway) — the hook stateful-per-microbatch ops (dropout
    rng folding) need to decorrelate microbatches.
    """
    n_stages = lax.axis_size(axis)
    stage_idx = lax.axis_index(axis)
    m = microbatches.shape[0]
    ticks = m + n_stages - 1
    out_dtype = microbatches.dtype
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick_fn(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (if any); other stages use the
        # activation handed to them by the previous stage last tick
        feed_idx = jnp.clip(t, 0, m - 1)
        fed = jnp.where(stage_idx == 0,
                        microbatches[feed_idx].astype(state.dtype), state)
        if with_mb_index:
            # at tick t, stage s works on microbatch t-s (pipeline skew)
            y = stage_fn(stage_params, fed,
                         jnp.clip(t - stage_idx, 0, m - 1))
        else:
            y = stage_fn(stage_params, fed)
        # last stage emits microbatch t-(P-1) when it is valid
        out_idx = t - (n_stages - 1)
        valid = (stage_idx == n_stages - 1) & (out_idx >= 0)
        outputs = lax.cond(
            valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y.astype(out_dtype), jnp.clip(out_idx, 0, m - 1), 0),
            lambda o: o,
            outputs)
        # hand activations to the next stage
        state = lax.ppermute(y, axis, perm_fwd)
        return (state, outputs), None

    state0 = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    state0 = state0 + jnp.sum(microbatches[:1]) * 0   # inherit varying axes
    outputs0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(tick_fn, (state0, outputs0),
                               jnp.arange(ticks))
    # every shard returns the outputs; only the last stage's copy is real —
    # broadcast it so the result is replicated over the pipe axis
    src = n_stages - 1
    outputs = lax.psum(
        jnp.where(stage_idx == src, outputs, jnp.zeros_like(outputs)), axis)
    return outputs


# ---------------------------------------------------------------------------
# interleaved 1F1B
# ---------------------------------------------------------------------------
#
# Slot algebra (P stages, M microbatches, one op per stage per tick):
#
#   forward  of microbatch i at stage s:  tick  s + 2i
#   backward of microbatch i at stage s:  tick  2P-1-s + 2i
#
# Checks: F and B land on disjoint tick parities per stage (never collide);
# a message produced at tick t is consumed by the neighbor at t+1 (one
# ppermute per tick each way); the last tick is 2M+2P-3, so the schedule is
# T = 2(M+P-1) ticks with exactly 2(P-1) idle ticks per stage — idle
# fraction (P-1)/(M+P-1), the 1F1B bubble (pinned by
# tests/test_moe_pipeline.py::TestOneFOneB::test_bubble_accounting).
# Microbatch i's input activation is stashed from its F tick to its B tick;
# at stage s that window holds at most P-s microbatches, so a P-slot ring
# buffer (indexed i mod P) suffices — O(P) activation memory, the whole
# point of 1F1B over end-to-end GPipe's O(M).
#
# The backward recomputes the stage forward from the stashed INPUT via
# jax.vjp at the B tick (activation recompute, the standard large-model
# setting) — VJP closures cannot live in a scan carry.  Gradients are
# accumulated in the carry and the function returns them directly
# (value-and-grad style); callers wrap it in jax.custom_vjp to splice the
# manual grads into an outer autodiff (models/bert_pipeline.py).

def schedule_table(n_stages: int, num_microbatches: int) -> list:
    """The 1F1B slot table as plain data — SAME predicate arithmetic as
    ``pipeline_1f1b``'s tick_fn, in python ints, so tests can pin the
    schedule's structural claims (bubble fraction, O(P) stash occupancy,
    neighbor-message timing) without tracing.  Returns
    ``table[t][s] = ("F"|"B", mb_index) | None``."""
    n, m = n_stages, num_microbatches
    ticks = 2 * (m + n - 1)
    table = []
    for t in range(ticks):
        row = []
        for s in range(n):
            f_num = t - s
            b_num = t - (2 * n - 1 - s)
            op = None
            if f_num >= 0 and f_num % 2 == 0 and f_num // 2 < m:
                op = ("F", f_num // 2)
            if b_num >= 0 and b_num % 2 == 0 and b_num // 2 < m:
                assert op is None, "F/B collision — parity argument broken"
                op = ("B", b_num // 2)
            row.append(op)
        table.append(row)
    return table


def interleaved_ring_depth(n_stages: int, num_microbatches: int) -> int:
    """Per-chunk ring-buffer depth for the interleaved schedule: 2P
    slots reach the Megatron-ideal bubble (P-deep rings throttle the
    warmup back to the plain-1F1B bubble); M slots suffice when the
    stream is shorter than that."""
    return max(1, min(2 * n_stages, num_microbatches))


def interleaved_table(n_stages: int, v: int, num_microbatches: int) -> list:
    """Interleaved-1F1B schedule: ``v`` virtual stage chunks per device.

    Chunk ``k`` (of ``V = v * n_stages``) lives on device ``k % P`` with
    local index ``j = k // P``; every forward message rides the +1 ring
    hop, every backward the -1 hop — same neighbor topology as plain
    1F1B, just more chunks.  Built by dependency-driven greedy list
    scheduling (backward-first, then earliest (mb, chunk)), honoring:

    - message latency 1 tick (consume at >= produce + 1);
    - one op per device per tick;
    - Q-slot ring buffers per chunk for the stash and the in-flight
      messages: F(k, i) needs B(k, i-Q) done (stash slot ``i % Q`` free)
      and F(k+1, i-Q) done (the consumer's input slot free); mirrored
      for backward cotangents.

    Returns ``table[t][d] = ("F"|"B", chunk_local_j, mb_index) | None``.
    Achieves the Megatron-ideal schedule length ``2vM + 2(P-1)`` ticks —
    bubble ``(P-1)/(vM+P-1)``, ~v-fold below plain 1F1B (pinned by
    tests).  The price is the deeper ring: ``Q = min(2P, M)`` slots per
    chunk (``interleaved_ring_depth``) instead of plain 1F1B's P —
    Megatron's warmup keeps up to ``2(P-1) + (v-1)P + 1`` chunk-ops in
    flight per device, more than P-deep rings can hold (a P-deep ring
    caps the schedule at the PLAIN bubble; measured while building
    this) — and v x the ring messages.
    """
    P_, M, V = n_stages, num_microbatches, v * n_stages
    Q = interleaved_ring_depth(n_stages, num_microbatches)
    tick_f: dict = {}
    tick_b: dict = {}

    def done_before(d_, key, t):
        """op done strictly before tick t (message latency)."""
        return key in d_ and d_[key] < t

    def done_by(d_, key, t):
        """op done at or before tick t (slot freed; same-tick is safe —
        reads happen during the owner's tick, overwrites at a later
        one, and two ops never share a device-tick)."""
        return key in d_ and d_[key] <= t

    def b_ready(k, i, t):
        if (k, i) in tick_b or not done_before(tick_f, (k, i), t):
            return False
        if k < V - 1 and not done_before(tick_b, (k + 1, i), t):
            return False
        # this B's cotangent message lands in chunk k-1's ring slot
        # (i % Q): the previous occupant must have been consumed
        if k > 0 and i >= Q and \
                not done_by(tick_b, (k - 1, i - Q), t):
            return False
        return True

    def f_ready(k, i, t):
        if (k, i) in tick_f:
            return False
        if k > 0 and not done_before(tick_f, (k - 1, i), t):
            return False
        # stash ring slot (i % Q) free: B of the slot's prior tenant done
        if i >= Q and not done_by(tick_b, (k, i - Q), t):
            return False
        # this F's output message lands in chunk k+1's ring slot (i % Q):
        # its previous occupant must have been consumed
        if k < V - 1 and i >= Q and \
                not done_by(tick_f, (k + 1, i - Q), t):
            return False
        return True

    # Megatron-style fixed op order per device: microbatches advance in
    # GROUPS of P per chunk (breadth-first over the group, then the next
    # chunk) — depth-first (push one mb through all chunks) stalls on the
    # cross-device round-trip and yields a WORSE bubble than plain 1F1B.
    # B order mirrors F with chunks reversed (B(k) depends on B(k+1)).
    def f_order(d):
        for g0 in range(0, M, P_):
            group = range(g0, min(g0 + P_, M))
            for j in range(v):
                for i in group:
                    yield (j * P_ + d, i)

    def b_order(d):
        for g0 in range(0, M, P_):
            group = range(g0, min(g0 + P_, M))
            for j in reversed(range(v)):
                for i in group:
                    yield (j * P_ + d, i)

    f_seq = [list(f_order(d)) for d in range(P_)]
    b_seq = [list(b_order(d)) for d in range(P_)]
    f_ptr = [0] * P_
    b_ptr = [0] * P_
    # Megatron's warmup depth: 2(P-d-1) + (v-1)P forward chunk-ops before
    # the first backward; steady state then holds in-flight constant
    # (strict one-F-one-B), cooldown drains.  Encoded as a preference on
    # in-flight count, work-conserving (falls back to the other op kind
    # rather than idling when the preferred one is not ready).
    target = [min(2 * (P_ - d - 1) + (v - 1) * P_ + 1, v * M)
              for d in range(P_)]
    table: list = []
    t = 0
    while len(tick_b) < V * M:
        row: list = [None] * P_
        for d in range(P_):
            f_ok = (f_ptr[d] < len(f_seq[d])
                    and f_ready(*f_seq[d][f_ptr[d]], t))
            b_ok = (b_ptr[d] < len(b_seq[d])
                    and b_ready(*b_seq[d][b_ptr[d]], t))
            in_flight = f_ptr[d] - b_ptr[d]
            pick_b = b_ok and (in_flight >= target[d] or not f_ok)
            if pick_b:
                k, i = b_seq[d][b_ptr[d]]
                b_ptr[d] += 1
                row[d] = ("B", k // P_, i)
                tick_b[(k, i)] = t
            elif f_ok:
                k, i = f_seq[d][f_ptr[d]]
                f_ptr[d] += 1
                row[d] = ("F", k // P_, i)
                tick_f[(k, i)] = t
        table.append(row)
        t += 1
        assert t <= 8 * V * (M + P_), "interleaved scheduler wedged"
    return table


def schedule_cost(n_stages: int, num_microbatches: int,
                  uniform_stages: bool) -> dict:
    """Tick-level stage-body accounting for one ``pipeline_1f1b`` pass —
    the measured truth of what ``uniform_stages`` costs (VERDICT r4 #4).

    Counts per device, in stage-body runs (the backward's recompute
    replay counts as one forward body; its vjp backward as two — the
    standard 1:3 fwd:bwd flop ratio):

    - gated (``uniform_stages=False``, collective-free meshes only):
      exactly M forward ops and M backward ops execute — the lax.cond
      skips bubble ticks.  Useful work only.
    - uniform (required whenever stage bodies or the head carry
      collectives): the forward body AND the backward replay+vjp run
      every tick — ``2*(M+P-1)`` times each — because collectives may
      not sit under a slot-gated cond.  Total body-equivalents are
      ``2*(M+P-1)/M`` times the useful work: ~2x GPipe's unconditional
      scan even at P=1, shrinking toward 2x as M >> P.

    The uniform schedule buys the O(P) activation stash (vs GPipe's
    O(M)) at that compute price; ``schedule="1f1b"`` on a
    collective-free mesh keeps the gated fast path and pays nothing.
    """
    m, p = num_microbatches, n_stages
    ticks = 2 * (m + p - 1)
    if uniform_stages:
        f_runs = b_runs = ticks
    else:
        f_runs = b_runs = m
    useful = 4 * m               # M forward (1) + M backward (3)
    total = f_runs + 3 * b_runs
    return {"ticks": ticks, "fwd_body_runs": f_runs,
            "bwd_body_runs": b_runs, "useful_body_equiv": useful,
            "total_body_equiv": total,
            "overhead_ratio": total / useful,
            "bubble_fraction": (p - 1) / (m + p - 1)}


def _bwd_core(stage_call: Callable, stage_p: Any, last_fn: Callable,
              last_params: Any, aux_i: Any, x, incoming_dy, is_last,
              gate, uniform: bool):
    """The backward op shared by both 1F1B executors: replay the stage
    from its stashed input, seed the output cotangent from the head
    (last stage/chunk) or the incoming message, and differentiate.

    ``stage_call(params, x) -> y`` is the stage body closed over
    everything but its differentiable inputs.  Under ``uniform`` the
    head math runs unconditionally and is masked by ``gate & is_last``
    (collectives may not sit under the rank-varying cond — see
    ``pipeline_1f1b``); the gated path keeps the ``lax.cond`` and is
    valid for collective-free stages/heads only.

    Returns ``(dsp, dx, dlp_add, li_add)``: raw stage-param and input
    cotangents (caller masks/accumulates — the two executors index
    their grads differently) plus ready-masked head-grad and loss
    addends."""
    yb, vjp_fn = jax.vjp(stage_call, stage_p, x)

    def head_math(yb):
        li, last_vjp = jax.vjp(
            lambda lp, yy: last_fn(lp, yy, aux_i), last_params, yb)
        dlp, dy = last_vjp(jnp.ones((), li.dtype))
        return li, dlp, dy

    if uniform:
        li, dlp, dy_head = head_math(yb)
        on_last = gate & is_last
        dlp_add = jax.tree.map(
            lambda d: jnp.where(on_last, d, jnp.zeros_like(d)), dlp)
        li_add = jnp.where(on_last, li, 0.0).astype(jnp.float32)
        dy = jnp.where(is_last, dy_head,
                       incoming_dy.astype(dy_head.dtype))
    else:
        def last_stage(yb):
            li, dlp, dy = head_math(yb)
            # f32 to match mid_stage's zero (cond branch types must
            # agree even for a low-precision last_fn)
            return (dy,
                    jax.tree.map(
                        lambda d: jnp.where(gate, d, jnp.zeros_like(d)),
                        dlp),
                    jnp.where(gate, li, 0.0).astype(jnp.float32))

        def mid_stage(yb):
            return (incoming_dy.astype(yb.dtype),
                    jax.tree.map(jnp.zeros_like, last_params),
                    jnp.zeros((), jnp.float32))

        dy, dlp_add, li_add = lax.cond(is_last, last_stage, mid_stage, yb)
    dsp, dx = vjp_fn(dy)
    return dsp, dx, dlp_add, li_add


def pipeline_1f1b(stage_fn: Callable, last_fn: Callable, stage_params: Any,
                  last_params: Any, microbatches, mb_aux: Any,
                  axis: str = "pipe", *, uniform_stages: bool = True):
    """Interleaved one-forward-one-backward pipeline schedule.

    Inside ``shard_map`` with ``axis`` in scope.  Per pipe shard:

    - ``stage_fn(sp, x, mb_idx) -> y``: this shard's stage.
    - ``last_fn(lp, y, aux_i) -> scalar``: microbatch i's loss contribution
      (already globally normalized so contributions SUM to the loss);
      evaluated only on the last stage's shard.
    - ``stage_params``: this shard's stage parameters.
    - ``last_params``: head/loss parameters — replicated over ``axis``;
      they MAY be sharded over other mesh axes (e.g. a vocab-parallel
      decoder over ``model``), in which case ``last_fn`` owns the
      cross-shard collectives and the caller owns the partial-cotangent
      reductions on the returned grads (see bert_pipeline's
      ``_reduce_partials``).
    - ``microbatches``: (M, mb, ...) — the SAME full stream on every pipe
      shard.  ``mb_aux``: pytree with leading M axis (labels/masks/...).
    - ``uniform_stages``: MUST be True whenever ``stage_fn`` contains
      collectives over mesh axes other than ``axis`` (ring attention's
      ppermute over 'seq', TP psums over 'model'): those collectives'
      groups span devices whose slot predicates agree, but placing them
      under a pipe-rank-dependent ``lax.cond`` is unsound regardless — a
      minimal repro crashes XLA:CPU's thunk executor, and the full model
      silently computed a wrong seq-sharded forward.  True runs the
      stage body and its vjp unconditionally every tick and masks the
      results (GPipe's scan always worked this way).  False keeps the
      slot-gated ``lax.cond`` fast path — valid ONLY for collective-free
      stages (plain pipe x data), where it skips the bubble-tick
      compute.

    Returns ``(loss, d_stage_params, d_last_params, d_microbatches)`` —
    loss/d_last/d_micro are summed over ``axis`` (zeros contributed by
    non-owning stages), d_stage_params is this shard's own stage grads.
    """
    n = lax.axis_size(axis)
    s_idx = lax.axis_index(axis)
    m = microbatches.shape[0]
    ticks = 2 * (m + n - 1)
    x_shape = microbatches.shape[1:]
    f32 = jnp.float32

    def tick_fn(carry, t):
        fwd_msg, bwd_msg, stash, gs, gl, loss, dx_out = carry
        # forward: stage s OWNS microbatch (t-s)/2 when parity/range fit.
        # Under ``uniform_stages`` the stage body runs UNCONDITIONALLY
        # every tick and its result is masked by f_on: the stage may
        # contain collectives (ring attention's ppermute over 'seq', TP
        # psums over 'model') and a lax.cond on the pipe-dependent slot
        # predicate would put them under control flow — UNSOUND (the
        # minimal repro crashes XLA:CPU's thunk executor; the full model
        # silently corrupted the seq-sharded forward).  GPipe's
        # pipeline() already runs stages unconditionally; the gated
        # fast path below remains for collective-free stages only.
        f_num = t - s_idx
        i_f = jnp.clip(f_num // 2, 0, m - 1)
        f_on = (f_num >= 0) & (f_num % 2 == 0) & (f_num // 2 < m)
        x_in = jnp.where(s_idx == 0,
                         microbatches[i_f].astype(fwd_msg.dtype), fwd_msg)
        if uniform_stages:
            y_all = stage_fn(stage_params, x_in, i_f)
            y = jnp.where(f_on, y_all, jnp.zeros(x_shape, y_all.dtype))
        else:
            y = lax.cond(
                f_on,
                lambda xx: stage_fn(stage_params, xx, i_f),
                lambda xx: jnp.zeros(x_shape, fwd_msg.dtype), x_in)
        # carry updates hold NO collectives — always safely slot-gated
        stash = lax.cond(
            f_on,
            lambda s: lax.dynamic_update_index_in_dim(s, x_in, i_f % n, 0),
            lambda s: s, stash)

        # backward: stage s owns microbatch (t-(2n-1-s))/2.  Same rule:
        # under uniform_stages the stage replay (and its vjp — reverse
        # ppermute hops) runs unconditionally; only the ACCUMULATIONS
        # are masked by b_on.
        b_num = t - (2 * n - 1 - s_idx)
        i_b = jnp.clip(b_num // 2, 0, m - 1)
        b_on = (b_num >= 0) & (b_num % 2 == 0) & (b_num // 2 < m)

        def bwd_math(c):
            """The shared backward body (``_bwd_core``): stage replay +
            head-or-message cotangent + vjp.  The head math runs
            unconditionally on the uniform path — ``last_fn`` may carry
            collectives over OTHER mesh axes (vocab-parallel CE's psum
            over 'model') and the ``s_idx == n-1`` predicate varies
            across pipe ranks, the same unsound pattern the uniform path
            exists to avoid.  Accumulations masked by ``gate`` (constant
            True on the gated path — the cond already gates)."""
            bwd_msg, stash, gs, gl, loss, dx_out, gate = c
            x = stash[i_b % n]
            aux_i = jax.tree.map(lambda a: a[i_b], mb_aux)
            dsp, dx, dlp_add, li_add = _bwd_core(
                lambda sp, xx: stage_fn(sp, xx, i_b), stage_params,
                last_fn, last_params, aux_i, x, bwd_msg,
                s_idx == n - 1, gate, uniform_stages)
            gl = jax.tree.map(jnp.add, gl, dlp_add)
            loss = loss + li_add
            gs = jax.tree.map(
                lambda g, d: g + jnp.where(gate, d, jnp.zeros_like(d)),
                gs, dsp)
            # only stage 0's input cotangents are the embedding stream's
            dx_out = lax.cond(
                gate & (s_idx == 0),
                lambda d: lax.dynamic_update_index_in_dim(
                    d, dx.astype(f32), i_b, 0),
                lambda d: d, dx_out)
            dx_send = jnp.where(gate, dx.astype(fwd_msg.dtype),
                                jnp.zeros(x_shape, fwd_msg.dtype))
            return dx_send, stash, gs, gl, loss, dx_out

        if uniform_stages:
            dx_send, stash, gs, gl, loss, dx_out = bwd_math(
                (bwd_msg, stash, gs, gl, loss, dx_out, b_on))
        else:
            dx_send, stash, gs, gl, loss, dx_out = lax.cond(
                b_on,
                lambda c: bwd_math(c),
                lambda c: (jnp.zeros(x_shape, fwd_msg.dtype),) + c[1:6],
                (bwd_msg, stash, gs, gl, loss, dx_out, jnp.bool_(True)))

        perm_f = [(j, (j + 1) % n) for j in range(n)]
        perm_b = [(j, (j - 1) % n) for j in range(n)]
        fwd_msg = lax.ppermute(y, axis, perm_f)
        bwd_msg = lax.ppermute(dx_send, axis, perm_b)
        return (fwd_msg, bwd_msg, stash, gs, gl, loss, dx_out), None

    zero_like_local = lambda tree: jax.tree.map(
        lambda x: jnp.zeros(jnp.shape(x), f32), tree)
    # seed the messages/stash from the stream so they inherit its
    # varying-axes type under shard_map's type checks
    seed = jnp.sum(microbatches[:1]) * 0
    init = (
        jnp.zeros(x_shape, microbatches.dtype) + seed,
        jnp.zeros(x_shape, microbatches.dtype) + seed,
        jnp.zeros((n,) + x_shape, microbatches.dtype) + seed,
        zero_like_local(stage_params),
        zero_like_local(last_params),
        jnp.zeros((), f32),
        jnp.zeros((m,) + x_shape, f32) + seed,
    )
    (_, _, _, gs, gl, loss, dx_out), _ = lax.scan(
        tick_fn, init, jnp.arange(ticks))
    # loss/gl/dx_out live on one stage each (zeros elsewhere): sum the ring
    loss = lax.psum(loss, axis)
    gl = jax.tree.map(lambda x: lax.psum(x, axis), gl)
    dx_out = lax.psum(dx_out, axis)
    return loss, gs, gl, dx_out


def pipeline_1f1b_interleaved(stage_fn: Callable, last_fn: Callable,
                              chunk_params: Any, last_params: Any,
                              microbatches, mb_aux: Any,
                              axis: str = "pipe", *, v: int,
                              n_stages: int,
                              uniform_stages: bool = True):
    """Interleaved 1F1B: ``v`` virtual stage chunks per device.

    Same contract as ``pipeline_1f1b`` except ``chunk_params`` carries a
    leading ``(v, ...)`` axis — this device's chunks, where local chunk
    ``j`` is GLOBAL chunk ``k = j * P + device`` (chunks ascend round-
    robin so every hop is the +1 ring neighbor) — and ``stage_fn(cp, x,
    mb_idx, chunk_k)`` receives the global chunk index for layer-offset
    bookkeeping (dropout fold-ins).

    Executes the static ``interleaved_table`` schedule inside one
    ``lax.scan``: per tick each device runs its scheduled op (F body, or
    B replay+vjp, or idle), reads/writes Q-slot ring buffers
    (``interleaved_ring_depth``) for the stash and the in-flight
    messages, and exchanges one fwd (+1) and one bwd (-1) ppermute.
    Bubble = (P-1)/(vM+P-1), ~v-fold below plain 1F1B; activation
    memory is 3*v*Q microbatch slots (stash + two message rings) vs
    plain's ~P — the classic interleaving trade plus this executor's
    separate-buffer simplicity.

    ``uniform_stages`` as in ``pipeline_1f1b``: True runs both bodies
    every tick and masks (required for collectives inside stages /
    head); False slot-gates with ``lax.cond`` (collective-free only).

    Returns ``(loss, d_chunk_params, d_last_params, d_microbatches)``.
    """
    import numpy as np

    P_ = n_stages
    s_idx = lax.axis_index(axis)
    M = microbatches.shape[0]
    V = v * P_
    Q = interleaved_ring_depth(P_, M)
    x_shape = microbatches.shape[1:]
    f32 = jnp.float32

    # ---- bake the static schedule as per-(tick, device) index tables
    table = interleaved_table(P_, v, M)
    T = len(table)
    kind = np.zeros((T, P_), np.int32)          # 0 idle / 1 F / 2 B
    jj = np.zeros((T, P_), np.int32)
    ii = np.zeros((T, P_), np.int32)
    for t, row in enumerate(table):
        for d, op in enumerate(row):
            if op is None:
                continue
            kind[t, d] = 1 if op[0] == "F" else 2
            jj[t, d] = op[1]
            ii[t, d] = op[2]
    # arrival routing: a message in the carry at tick t was produced at
    # t-1.  fwd from device d-1 (k -> k+1), bwd from device d+1 (k -> k-1).
    fs_on = np.zeros((T, P_), bool)
    fs_j = np.zeros((T, P_), np.int32)
    fs_slot = np.zeros((T, P_), np.int32)
    bs_on = np.zeros((T, P_), bool)
    bs_j = np.zeros((T, P_), np.int32)
    bs_slot = np.zeros((T, P_), np.int32)
    for t in range(1, T):
        for d in range(P_):
            src = table[t - 1][(d - 1) % P_]
            if src is not None and src[0] == "F":
                k = src[1] * P_ + (d - 1) % P_
                if k < V - 1:
                    fs_on[t, d] = True
                    fs_j[t, d] = (k + 1) // P_
                    fs_slot[t, d] = src[2] % Q
            src = table[t - 1][(d + 1) % P_]
            if src is not None and src[0] == "B":
                k = src[1] * P_ + (d + 1) % P_
                if k > 0:
                    bs_on[t, d] = True
                    bs_j[t, d] = (k - 1) // P_
                    bs_slot[t, d] = src[2] % Q
    as_const = jnp.asarray
    KIND, JJ, II = as_const(kind), as_const(jj), as_const(ii)
    FS_ON, FS_J, FS_SLOT = as_const(fs_on), as_const(fs_j), as_const(fs_slot)
    BS_ON, BS_J, BS_SLOT = as_const(bs_on), as_const(bs_j), as_const(bs_slot)

    sel_chunk = lambda tree, j: jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, j, 0, keepdims=False), tree)

    def tick_fn(carry, t):
        (fwd_msg, bwd_msg, fwd_buf, bwd_buf, stash,
         gs, gl, loss, dx_out) = carry
        knd = KIND[t, s_idx]
        j = JJ[t, s_idx]
        i = II[t, s_idx]
        k_glob = j * P_ + s_idx
        slot = i % Q

        # ---- store arrivals (carry messages were produced last tick)
        fwd_buf = lax.cond(
            FS_ON[t, s_idx],
            lambda b: b.at[FS_J[t, s_idx], FS_SLOT[t, s_idx]].set(fwd_msg),
            lambda b: b, fwd_buf)
        bwd_buf = lax.cond(
            BS_ON[t, s_idx],
            lambda b: b.at[BS_J[t, s_idx], BS_SLOT[t, s_idx]].set(bwd_msg),
            lambda b: b, bwd_buf)

        f_on = knd == 1
        b_on = knd == 2
        from_stream = (k_glob == 0) & f_on
        x_in = jnp.where(from_stream,
                         microbatches[jnp.clip(i, 0, M - 1)]
                         .astype(fwd_buf.dtype),
                         fwd_buf[j, slot])
        cp_f = sel_chunk(chunk_params, j)
        if uniform_stages:
            y_all = stage_fn(cp_f, x_in, i, k_glob)
            y = jnp.where(f_on, y_all, jnp.zeros(x_shape, y_all.dtype))
        else:
            y = lax.cond(
                f_on,
                lambda xx: stage_fn(cp_f, xx, i, k_glob),
                lambda xx: jnp.zeros(x_shape, fwd_buf.dtype), x_in)
        stash = lax.cond(
            f_on,
            lambda s: s.at[j, slot].set(x_in),
            lambda s: s, stash)

        def bwd_math(c):
            bwd_buf, stash, gs, gl, loss, dx_out, gate = c
            x = stash[j, slot]
            cp_b = sel_chunk(chunk_params, j)
            aux_i = jax.tree.map(lambda a: a[jnp.clip(i, 0, M - 1)],
                                 mb_aux)
            dcp, dx, dlp_add, li_add = _bwd_core(
                lambda cp, xx: stage_fn(cp, xx, i, k_glob), cp_b,
                last_fn, last_params, aux_i, x, bwd_buf[j, slot],
                k_glob == V - 1, gate, uniform_stages)
            gl = jax.tree.map(jnp.add, gl, dlp_add)
            loss = loss + li_add
            gs = jax.tree.map(
                lambda g, d: g.at[j].add(
                    jnp.where(gate, d, jnp.zeros_like(d))), gs, dcp)
            dx_out = lax.cond(
                gate & (k_glob == 0),
                lambda o: lax.dynamic_update_index_in_dim(
                    o, dx.astype(f32), jnp.clip(i, 0, M - 1), 0),
                lambda o: o, dx_out)
            dx_send = jnp.where(gate, dx.astype(fwd_msg.dtype),
                                jnp.zeros(x_shape, fwd_msg.dtype))
            return dx_send, stash, gs, gl, loss, dx_out

        if uniform_stages:
            dx_send, stash, gs, gl, loss, dx_out = bwd_math(
                (bwd_buf, stash, gs, gl, loss, dx_out, b_on))
        else:
            dx_send, stash, gs, gl, loss, dx_out = lax.cond(
                b_on,
                lambda c: bwd_math(c),
                lambda c: (jnp.zeros(x_shape, fwd_msg.dtype),) + c[1:6],
                (bwd_buf, stash, gs, gl, loss, dx_out, jnp.bool_(True)))

        perm_f = [(q, (q + 1) % P_) for q in range(P_)]
        perm_b = [(q, (q - 1) % P_) for q in range(P_)]
        fwd_msg = lax.ppermute(
            jnp.where(f_on, y, jnp.zeros(x_shape, y.dtype)), axis, perm_f)
        bwd_msg = lax.ppermute(dx_send, axis, perm_b)
        return (fwd_msg, bwd_msg, fwd_buf, bwd_buf, stash,
                gs, gl, loss, dx_out), None

    zero_like_local = lambda tree: jax.tree.map(
        lambda x: jnp.zeros(jnp.shape(x), f32), tree)
    seed = jnp.sum(microbatches[:1]) * 0
    mdt = microbatches.dtype
    init = (
        jnp.zeros(x_shape, mdt) + seed,
        jnp.zeros(x_shape, mdt) + seed,
        jnp.zeros((v, Q) + x_shape, mdt) + seed,
        jnp.zeros((v, Q) + x_shape, mdt) + seed,
        jnp.zeros((v, Q) + x_shape, mdt) + seed,
        zero_like_local(chunk_params),
        zero_like_local(last_params),
        jnp.zeros((), f32),
        jnp.zeros((M,) + x_shape, f32) + seed,
    )
    (_, _, _, _, _, gs, gl, loss, dx_out), _ = lax.scan(
        tick_fn, init, jnp.arange(T))
    loss = lax.psum(loss, axis)
    gl = jax.tree.map(lambda x: lax.psum(x, axis), gl)
    dx_out = lax.psum(dx_out, axis)
    return loss, gs, gl, dx_out


def make_pipelined_fn(stage_fn: Callable, mesh: Mesh,
                      num_microbatches: int, axis: str = "pipe"):
    """jit-ready wrapper: ``f(stacked_params, batch) -> out``.

    ``stacked_params``: pytree with a leading stage dimension (length = pipe
    axis size), placed sharded over ``axis``.  ``batch``: (N, ...) global
    batch, replicated; it is cut into ``num_microbatches`` equal slices.
    """
    def fn(stacked_params, batch):
        def inner(stacked_params, batch):
            params = jax.tree.map(lambda x: x[0], stacked_params)
            mb = batch.reshape((num_microbatches,
                                batch.shape[0] // num_microbatches)
                               + batch.shape[1:])
            out = pipeline(stage_fn, params, mb, axis)
            return out.reshape(batch.shape[0], *out.shape[2:])

        return jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=P(),
            check_vma=False,
        )(stacked_params, batch)

    return fn
