"""Pipeline parallelism (PP): GPipe-style microbatched stage pipeline.

The layer stack is split into P stages whose parameters live sharded over a
``pipe`` mesh axis (one stage per shard).  A batch is cut into M microbatches
that flow stage-to-stage through ``ppermute`` neighbor hops: at tick t, stage
s processes microbatch t-s while its neighbors work on adjacent microbatches
— the classic pipeline schedule with (P-1) bubble ticks around M useful ones.
The whole schedule is a ``lax.scan``, so reverse-mode autodiff derives the
backward pipeline automatically (the transpose of ``ppermute`` is the
reverse hop).

No counterpart in the reference (SURVEY.md §2 checklist: PP absent); part of
the full parallelism-strategy coverage.  Use ``pipeline`` inside
``shard_map`` with the ``pipe`` axis in scope — see ``make_pipelined_fn`` for
the jit-ready wrapper.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline(stage_fn: Callable, stage_params: Any, microbatches,
             axis: str = "pipe", with_mb_index: bool = False):
    """Run ``stage_fn(params, x) -> y`` as a P-stage pipeline.

    Inside ``shard_map``: ``stage_params`` is this shard's stage parameters,
    ``microbatches`` has shape (M, mb, ...) and must hold the SAME full set
    of microbatches on every shard (replicated over ``axis``); the result is
    the final stage's outputs, (M, mb, ...), valid on every shard.

    ``with_mb_index=True`` calls ``stage_fn(params, x, mb_idx)`` where
    ``mb_idx`` is the index of the microbatch this stage is processing at
    the current tick (clipped to [0, M-1] during bubble ticks, whose outputs
    are discarded anyway) — the hook stateful-per-microbatch ops (dropout
    rng folding) need to decorrelate microbatches.
    """
    n_stages = lax.axis_size(axis)
    stage_idx = lax.axis_index(axis)
    m = microbatches.shape[0]
    ticks = m + n_stages - 1
    out_dtype = microbatches.dtype
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick_fn(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (if any); other stages use the
        # activation handed to them by the previous stage last tick
        feed_idx = jnp.clip(t, 0, m - 1)
        fed = jnp.where(stage_idx == 0,
                        microbatches[feed_idx].astype(state.dtype), state)
        if with_mb_index:
            # at tick t, stage s works on microbatch t-s (pipeline skew)
            y = stage_fn(stage_params, fed,
                         jnp.clip(t - stage_idx, 0, m - 1))
        else:
            y = stage_fn(stage_params, fed)
        # last stage emits microbatch t-(P-1) when it is valid
        out_idx = t - (n_stages - 1)
        valid = (stage_idx == n_stages - 1) & (out_idx >= 0)
        outputs = lax.cond(
            valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y.astype(out_dtype), jnp.clip(out_idx, 0, m - 1), 0),
            lambda o: o,
            outputs)
        # hand activations to the next stage
        state = lax.ppermute(y, axis, perm_fwd)
        return (state, outputs), None

    state0 = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    state0 = state0 + jnp.sum(microbatches[:1]) * 0   # inherit varying axes
    outputs0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(tick_fn, (state0, outputs0),
                               jnp.arange(ticks))
    # every shard returns the outputs; only the last stage's copy is real —
    # broadcast it so the result is replicated over the pipe axis
    src = n_stages - 1
    outputs = lax.psum(
        jnp.where(stage_idx == src, outputs, jnp.zeros_like(outputs)), axis)
    return outputs


# ---------------------------------------------------------------------------
# interleaved 1F1B
# ---------------------------------------------------------------------------
#
# Slot algebra (P stages, M microbatches, one op per stage per tick):
#
#   forward  of microbatch i at stage s:  tick  s + 2i
#   backward of microbatch i at stage s:  tick  2P-1-s + 2i
#
# Checks: F and B land on disjoint tick parities per stage (never collide);
# a message produced at tick t is consumed by the neighbor at t+1 (one
# ppermute per tick each way); the last tick is 2M+2P-3, so the schedule is
# T = 2(M+P-1) ticks with exactly 2(P-1) idle ticks per stage — idle
# fraction (P-1)/(M+P-1), the 1F1B bubble (pinned by
# tests/test_moe_pipeline.py::TestOneFOneB::test_bubble_accounting).
# Microbatch i's input activation is stashed from its F tick to its B tick;
# at stage s that window holds at most P-s microbatches, so a P-slot ring
# buffer (indexed i mod P) suffices — O(P) activation memory, the whole
# point of 1F1B over end-to-end GPipe's O(M).
#
# The backward recomputes the stage forward from the stashed INPUT via
# jax.vjp at the B tick (activation recompute, the standard large-model
# setting) — VJP closures cannot live in a scan carry.  Gradients are
# accumulated in the carry and the function returns them directly
# (value-and-grad style); callers wrap it in jax.custom_vjp to splice the
# manual grads into an outer autodiff (models/bert_pipeline.py).

def schedule_table(n_stages: int, num_microbatches: int) -> list:
    """The 1F1B slot table as plain data — SAME predicate arithmetic as
    ``pipeline_1f1b``'s tick_fn, in python ints, so tests can pin the
    schedule's structural claims (bubble fraction, O(P) stash occupancy,
    neighbor-message timing) without tracing.  Returns
    ``table[t][s] = ("F"|"B", mb_index) | None``."""
    n, m = n_stages, num_microbatches
    ticks = 2 * (m + n - 1)
    table = []
    for t in range(ticks):
        row = []
        for s in range(n):
            f_num = t - s
            b_num = t - (2 * n - 1 - s)
            op = None
            if f_num >= 0 and f_num % 2 == 0 and f_num // 2 < m:
                op = ("F", f_num // 2)
            if b_num >= 0 and b_num % 2 == 0 and b_num // 2 < m:
                assert op is None, "F/B collision — parity argument broken"
                op = ("B", b_num // 2)
            row.append(op)
        table.append(row)
    return table


def schedule_cost(n_stages: int, num_microbatches: int,
                  uniform_stages: bool) -> dict:
    """Tick-level stage-body accounting for one ``pipeline_1f1b`` pass —
    the measured truth of what ``uniform_stages`` costs (VERDICT r4 #4).

    Counts per device, in stage-body runs (the backward's recompute
    replay counts as one forward body; its vjp backward as two — the
    standard 1:3 fwd:bwd flop ratio):

    - gated (``uniform_stages=False``, collective-free meshes only):
      exactly M forward ops and M backward ops execute — the lax.cond
      skips bubble ticks.  Useful work only.
    - uniform (required whenever stage bodies or the head carry
      collectives): the forward body AND the backward replay+vjp run
      every tick — ``2*(M+P-1)`` times each — because collectives may
      not sit under a slot-gated cond.  Total body-equivalents are
      ``2*(M+P-1)/M`` times the useful work: ~2x GPipe's unconditional
      scan even at P=1, shrinking toward 2x as M >> P.

    The uniform schedule buys the O(P) activation stash (vs GPipe's
    O(M)) at that compute price; ``schedule="1f1b"`` on a
    collective-free mesh keeps the gated fast path and pays nothing.
    """
    m, p = num_microbatches, n_stages
    ticks = 2 * (m + p - 1)
    if uniform_stages:
        f_runs = b_runs = ticks
    else:
        f_runs = b_runs = m
    useful = 4 * m               # M forward (1) + M backward (3)
    total = f_runs + 3 * b_runs
    return {"ticks": ticks, "fwd_body_runs": f_runs,
            "bwd_body_runs": b_runs, "useful_body_equiv": useful,
            "total_body_equiv": total,
            "overhead_ratio": total / useful,
            "bubble_fraction": (p - 1) / (m + p - 1)}


def pipeline_1f1b(stage_fn: Callable, last_fn: Callable, stage_params: Any,
                  last_params: Any, microbatches, mb_aux: Any,
                  axis: str = "pipe", *, uniform_stages: bool = True):
    """Interleaved one-forward-one-backward pipeline schedule.

    Inside ``shard_map`` with ``axis`` in scope.  Per pipe shard:

    - ``stage_fn(sp, x, mb_idx) -> y``: this shard's stage.
    - ``last_fn(lp, y, aux_i) -> scalar``: microbatch i's loss contribution
      (already globally normalized so contributions SUM to the loss);
      evaluated only on the last stage's shard.
    - ``stage_params``: this shard's stage parameters.
    - ``last_params``: head/loss parameters — replicated over ``axis``;
      they MAY be sharded over other mesh axes (e.g. a vocab-parallel
      decoder over ``model``), in which case ``last_fn`` owns the
      cross-shard collectives and the caller owns the partial-cotangent
      reductions on the returned grads (see bert_pipeline's
      ``_reduce_partials``).
    - ``microbatches``: (M, mb, ...) — the SAME full stream on every pipe
      shard.  ``mb_aux``: pytree with leading M axis (labels/masks/...).
    - ``uniform_stages``: MUST be True whenever ``stage_fn`` contains
      collectives over mesh axes other than ``axis`` (ring attention's
      ppermute over 'seq', TP psums over 'model'): those collectives'
      groups span devices whose slot predicates agree, but placing them
      under a pipe-rank-dependent ``lax.cond`` is unsound regardless — a
      minimal repro crashes XLA:CPU's thunk executor, and the full model
      silently computed a wrong seq-sharded forward.  True runs the
      stage body and its vjp unconditionally every tick and masks the
      results (GPipe's scan always worked this way).  False keeps the
      slot-gated ``lax.cond`` fast path — valid ONLY for collective-free
      stages (plain pipe x data), where it skips the bubble-tick
      compute.

    Returns ``(loss, d_stage_params, d_last_params, d_microbatches)`` —
    loss/d_last/d_micro are summed over ``axis`` (zeros contributed by
    non-owning stages), d_stage_params is this shard's own stage grads.
    """
    n = lax.axis_size(axis)
    s_idx = lax.axis_index(axis)
    m = microbatches.shape[0]
    ticks = 2 * (m + n - 1)
    x_shape = microbatches.shape[1:]
    f32 = jnp.float32

    def tick_fn(carry, t):
        fwd_msg, bwd_msg, stash, gs, gl, loss, dx_out = carry
        # forward: stage s OWNS microbatch (t-s)/2 when parity/range fit.
        # Under ``uniform_stages`` the stage body runs UNCONDITIONALLY
        # every tick and its result is masked by f_on: the stage may
        # contain collectives (ring attention's ppermute over 'seq', TP
        # psums over 'model') and a lax.cond on the pipe-dependent slot
        # predicate would put them under control flow — UNSOUND (the
        # minimal repro crashes XLA:CPU's thunk executor; the full model
        # silently corrupted the seq-sharded forward).  GPipe's
        # pipeline() already runs stages unconditionally; the gated
        # fast path below remains for collective-free stages only.
        f_num = t - s_idx
        i_f = jnp.clip(f_num // 2, 0, m - 1)
        f_on = (f_num >= 0) & (f_num % 2 == 0) & (f_num // 2 < m)
        x_in = jnp.where(s_idx == 0,
                         microbatches[i_f].astype(fwd_msg.dtype), fwd_msg)
        if uniform_stages:
            y_all = stage_fn(stage_params, x_in, i_f)
            y = jnp.where(f_on, y_all, jnp.zeros(x_shape, y_all.dtype))
        else:
            y = lax.cond(
                f_on,
                lambda xx: stage_fn(stage_params, xx, i_f),
                lambda xx: jnp.zeros(x_shape, fwd_msg.dtype), x_in)
        # carry updates hold NO collectives — always safely slot-gated
        stash = lax.cond(
            f_on,
            lambda s: lax.dynamic_update_index_in_dim(s, x_in, i_f % n, 0),
            lambda s: s, stash)

        # backward: stage s owns microbatch (t-(2n-1-s))/2.  Same rule:
        # under uniform_stages the stage replay (and its vjp — reverse
        # ppermute hops) runs unconditionally; only the ACCUMULATIONS
        # are masked by b_on.
        b_num = t - (2 * n - 1 - s_idx)
        i_b = jnp.clip(b_num // 2, 0, m - 1)
        b_on = (b_num >= 0) & (b_num % 2 == 0) & (b_num // 2 < m)

        def bwd_math(c):
            """The shared backward body: stage replay + head-or-message
            cotangent + vjp.  Accumulations masked by ``gate`` (constant
            True on the gated path — the cond already gates)."""
            bwd_msg, stash, gs, gl, loss, dx_out, gate = c
            x = stash[i_b % n]
            yb, vjp_fn = jax.vjp(
                lambda sp, xx: stage_fn(sp, xx, i_b), stage_params, x)

            def head_math(yb):
                aux_i = jax.tree.map(lambda a: a[i_b], mb_aux)
                li, last_vjp = jax.vjp(
                    lambda lp, yy: last_fn(lp, yy, aux_i), last_params, yb)
                dlp, dy = last_vjp(jnp.ones((), li.dtype))
                return li, dlp, dy

            if uniform_stages:
                # ``last_fn`` may itself contain collectives over OTHER
                # mesh axes (vocab-parallel CE's psum/all_gather over
                # 'model').  The ``s_idx == n-1`` predicate varies across
                # pipe ranks, so putting those collectives under a cond is
                # the same unsound pattern the uniform path exists to
                # avoid (each 'model' psum group is branch-uniform today,
                # but that is fragile across XLA versions).  Run the head
                # math unconditionally and mask by rank+slot instead.
                li, dlp, dy_head = head_math(yb)
                on_last = gate & (s_idx == n - 1)
                gl = jax.tree.map(
                    lambda g, d: g + jnp.where(on_last, d,
                                               jnp.zeros_like(d)),
                    gl, dlp)
                loss = loss + jnp.where(on_last, li, 0.0)
                dy = jnp.where(s_idx == n - 1, dy_head,
                               bwd_msg.astype(dy_head.dtype))
            else:
                def last_stage(args):
                    # gated path: stages are collective-free by contract,
                    # and the head's TP psums (if any) would span
                    # same-pipe-rank devices that share this branch
                    yb, gl, loss = args
                    li, dlp, dy = head_math(yb)
                    gl = jax.tree.map(
                        lambda g, d: g + jnp.where(gate, d,
                                                   jnp.zeros_like(d)),
                        gl, dlp)
                    return dy, gl, loss + jnp.where(gate, li, 0.0)

                def mid_stage(args):
                    yb, gl, loss = args
                    return bwd_msg.astype(yb.dtype), gl, loss

                dy, gl, loss = lax.cond(s_idx == n - 1, last_stage,
                                        mid_stage, (yb, gl, loss))
            dsp, dx = vjp_fn(dy)
            gs = jax.tree.map(
                lambda g, d: g + jnp.where(gate, d, jnp.zeros_like(d)),
                gs, dsp)
            # only stage 0's input cotangents are the embedding stream's
            dx_out = lax.cond(
                gate & (s_idx == 0),
                lambda d: lax.dynamic_update_index_in_dim(
                    d, dx.astype(f32), i_b, 0),
                lambda d: d, dx_out)
            dx_send = jnp.where(gate, dx.astype(fwd_msg.dtype),
                                jnp.zeros(x_shape, fwd_msg.dtype))
            return dx_send, stash, gs, gl, loss, dx_out

        if uniform_stages:
            dx_send, stash, gs, gl, loss, dx_out = bwd_math(
                (bwd_msg, stash, gs, gl, loss, dx_out, b_on))
        else:
            dx_send, stash, gs, gl, loss, dx_out = lax.cond(
                b_on,
                lambda c: bwd_math(c),
                lambda c: (jnp.zeros(x_shape, fwd_msg.dtype),) + c[1:6],
                (bwd_msg, stash, gs, gl, loss, dx_out, jnp.bool_(True)))

        perm_f = [(j, (j + 1) % n) for j in range(n)]
        perm_b = [(j, (j - 1) % n) for j in range(n)]
        fwd_msg = lax.ppermute(y, axis, perm_f)
        bwd_msg = lax.ppermute(dx_send, axis, perm_b)
        return (fwd_msg, bwd_msg, stash, gs, gl, loss, dx_out), None

    zero_like_local = lambda tree: jax.tree.map(
        lambda x: jnp.zeros(jnp.shape(x), f32), tree)
    # seed the messages/stash from the stream so they inherit its
    # varying-axes type under shard_map's type checks
    seed = jnp.sum(microbatches[:1]) * 0
    init = (
        jnp.zeros(x_shape, microbatches.dtype) + seed,
        jnp.zeros(x_shape, microbatches.dtype) + seed,
        jnp.zeros((n,) + x_shape, microbatches.dtype) + seed,
        zero_like_local(stage_params),
        zero_like_local(last_params),
        jnp.zeros((), f32),
        jnp.zeros((m,) + x_shape, f32) + seed,
    )
    (_, _, _, gs, gl, loss, dx_out), _ = lax.scan(
        tick_fn, init, jnp.arange(ticks))
    # loss/gl/dx_out live on one stage each (zeros elsewhere): sum the ring
    loss = lax.psum(loss, axis)
    gl = jax.tree.map(lambda x: lax.psum(x, axis), gl)
    dx_out = lax.psum(dx_out, axis)
    return loss, gs, gl, dx_out


def make_pipelined_fn(stage_fn: Callable, mesh: Mesh,
                      num_microbatches: int, axis: str = "pipe"):
    """jit-ready wrapper: ``f(stacked_params, batch) -> out``.

    ``stacked_params``: pytree with a leading stage dimension (length = pipe
    axis size), placed sharded over ``axis``.  ``batch``: (N, ...) global
    batch, replicated; it is cut into ``num_microbatches`` equal slices.
    """
    def fn(stacked_params, batch):
        def inner(stacked_params, batch):
            params = jax.tree.map(lambda x: x[0], stacked_params)
            mb = batch.reshape((num_microbatches,
                                batch.shape[0] // num_microbatches)
                               + batch.shape[1:])
            out = pipeline(stage_fn, params, mb, axis)
            return out.reshape(batch.shape[0], *out.shape[2:])

        return jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=P(),
            check_vma=False,
        )(stacked_params, batch)

    return fn
