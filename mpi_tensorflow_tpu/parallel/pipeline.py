"""Pipeline parallelism (PP): GPipe-style microbatched stage pipeline.

The layer stack is split into P stages whose parameters live sharded over a
``pipe`` mesh axis (one stage per shard).  A batch is cut into M microbatches
that flow stage-to-stage through ``ppermute`` neighbor hops: at tick t, stage
s processes microbatch t-s while its neighbors work on adjacent microbatches
— the classic pipeline schedule with (P-1) bubble ticks around M useful ones.
The whole schedule is a ``lax.scan``, so reverse-mode autodiff derives the
backward pipeline automatically (the transpose of ``ppermute`` is the
reverse hop).

No counterpart in the reference (SURVEY.md §2 checklist: PP absent); part of
the full parallelism-strategy coverage.  Use ``pipeline`` inside
``shard_map`` with the ``pipe`` axis in scope — see ``make_pipelined_fn`` for
the jit-ready wrapper.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline(stage_fn: Callable, stage_params: Any, microbatches,
             axis: str = "pipe", with_mb_index: bool = False):
    """Run ``stage_fn(params, x) -> y`` as a P-stage pipeline.

    Inside ``shard_map``: ``stage_params`` is this shard's stage parameters,
    ``microbatches`` has shape (M, mb, ...) and must hold the SAME full set
    of microbatches on every shard (replicated over ``axis``); the result is
    the final stage's outputs, (M, mb, ...), valid on every shard.

    ``with_mb_index=True`` calls ``stage_fn(params, x, mb_idx)`` where
    ``mb_idx`` is the index of the microbatch this stage is processing at
    the current tick (clipped to [0, M-1] during bubble ticks, whose outputs
    are discarded anyway) — the hook stateful-per-microbatch ops (dropout
    rng folding) need to decorrelate microbatches.
    """
    n_stages = lax.axis_size(axis)
    stage_idx = lax.axis_index(axis)
    m = microbatches.shape[0]
    ticks = m + n_stages - 1
    out_dtype = microbatches.dtype
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick_fn(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (if any); other stages use the
        # activation handed to them by the previous stage last tick
        feed_idx = jnp.clip(t, 0, m - 1)
        fed = jnp.where(stage_idx == 0,
                        microbatches[feed_idx].astype(state.dtype), state)
        if with_mb_index:
            # at tick t, stage s works on microbatch t-s (pipeline skew)
            y = stage_fn(stage_params, fed,
                         jnp.clip(t - stage_idx, 0, m - 1))
        else:
            y = stage_fn(stage_params, fed)
        # last stage emits microbatch t-(P-1) when it is valid
        out_idx = t - (n_stages - 1)
        valid = (stage_idx == n_stages - 1) & (out_idx >= 0)
        outputs = lax.cond(
            valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y.astype(out_dtype), jnp.clip(out_idx, 0, m - 1), 0),
            lambda o: o,
            outputs)
        # hand activations to the next stage
        state = lax.ppermute(y, axis, perm_fwd)
        return (state, outputs), None

    state0 = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    state0 = state0 + jnp.sum(microbatches[:1]) * 0   # inherit varying axes
    outputs0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(tick_fn, (state0, outputs0),
                               jnp.arange(ticks))
    # every shard returns the outputs; only the last stage's copy is real —
    # broadcast it so the result is replicated over the pipe axis
    src = n_stages - 1
    outputs = lax.psum(
        jnp.where(stage_idx == src, outputs, jnp.zeros_like(outputs)), axis)
    return outputs


def make_pipelined_fn(stage_fn: Callable, mesh: Mesh,
                      num_microbatches: int, axis: str = "pipe"):
    """jit-ready wrapper: ``f(stacked_params, batch) -> out``.

    ``stacked_params``: pytree with a leading stage dimension (length = pipe
    axis size), placed sharded over ``axis``.  ``batch``: (N, ...) global
    batch, replicated; it is cut into ``num_microbatches`` equal slices.
    """
    def fn(stacked_params, batch):
        def inner(stacked_params, batch):
            params = jax.tree.map(lambda x: x[0], stacked_params)
            mb = batch.reshape((num_microbatches,
                                batch.shape[0] // num_microbatches)
                               + batch.shape[1:])
            out = pipeline(stage_fn, params, mb, axis)
            return out.reshape(batch.shape[0], *out.shape[2:])

        return jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=P(),
            check_vma=False,
        )(stacked_params, batch)

    return fn
