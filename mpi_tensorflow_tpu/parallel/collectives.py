"""Collectives: the MPI roles the reference uses, on XLA primitives.

The reference's communication surface (SURVEY.md §2 #8) is: ``Scatter`` x6 at
startup (mpipy.py:236-241), ``Gather`` x4 per sync (mpipy.py:121-127), and —
notably absent — the ``Allreduce`` its own README promises.  On TPU these
roles map to:

| MPI role (reference)        | TPU-native primitive here                  |
|-----------------------------|--------------------------------------------|
| ``Scatter`` (root-0 fan-out)| per-host slicing (``data.sharding``) — no  |
|                             | root, no network fan-out needed            |
| ``Gather`` (to root)        | ``all_gather`` in-graph / host             |
|                             | ``process_allgather`` for metrics          |
| ``Allreduce`` (intended)    | ``psum`` / ``pmean`` over the mesh axis    |
| ``Bcast`` (absent but      | ``pbroadcast`` below (mask + psum)          |
| needed for correct avg)     |                                            |
| ``Barrier`` (commented out, | unnecessary in-graph (SPMD program order); |
| mpipy.py:93)                | ``sync_global_devices`` for host phases    |

All in-graph functions below must be called inside ``shard_map`` (they take a
mesh axis *name*).  They are thin, typed wrappers — the point is to make the
communication layer an explicit, testable component like the reference's,
rather than scattering raw ``lax`` calls through the codebase.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def allreduce_sum(x, axis: str = "data"):
    """The per-step gradient reduction (the reference's *intended* op)."""
    return lax.psum(x, axis)


def allreduce_mean(x, axis: str = "data"):
    """Normalized allreduce — equals the reference's ``np.mean(gathered, 0)``
    at mpipy.py:130-137, but delivered to every shard, not just rank 0."""
    return lax.pmean(x, axis)


def allreduce_max(x, axis: str = "data"):
    return lax.pmax(x, axis)


def allgather(x, axis: str = "data", *, tiled: bool = False):
    """``MPI.Gather``-to-all (mpipy.py:121-127 gathers to root; on TPU the
    symmetric form is natural and costs the same over ICI)."""
    return lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x, axis: str = "data"):
    """Sum-and-shard along the leading dim — the building block for sharded
    optimizer states (ZeRO-style; absent from the reference)."""
    return lax.psum_scatter(x, axis, tiled=True)


def pbroadcast(x, axis: str = "data", root: int = 0):
    """``MPI.Bcast`` from ``root`` — the collective the reference's
    ``bcast_parameters`` is named for but never performs (SURVEY.md §2 #11)."""
    idx = lax.axis_index(axis)
    return lax.psum(jnp.where(idx == root, x, jnp.zeros_like(x)), axis)


def ppermute_shift(x, axis: str, shift: int = 1):
    """Ring rotation by ``shift`` — the primitive under ring attention."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str = "data"):
    """In-graph shard id — the ``comm.Get_rank()`` analogue inside a step."""
    return lax.axis_index(axis)


# --- host-level (outside jit) ---

def host_allgather(x):
    """Gather a host-local array across processes (metric aggregation —
    replaces the reference's root-0 Gather of weights for averaging)."""
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x)


def barrier(name: str = "barrier"):
    """Cross-host sync point (the reference's commented-out ``Barrier``,
    mpipy.py:93)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
