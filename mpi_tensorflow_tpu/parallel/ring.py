"""Ring attention: sequence/context parallelism over a mesh axis.

Long sequences are sharded along a ``seq`` mesh axis; each shard holds a
block of queries and a block of keys/values.  K/V blocks rotate around the
ring via ``ppermute`` (ICI neighbor exchanges) while each shard accumulates
its queries' attention with the streaming (online) softmax — no shard ever
materializes the full (S, S) score matrix or the full K/V, so sequence
length scales with the number of shards at constant per-chip memory.

This subsystem has no counterpart in the reference (no attention, no
sequence axis — SURVEY.md §2 parallelism checklist); it is required by the
framework goal of first-class long-context training.

Call ``ring_attention`` inside ``shard_map`` with the ``seq`` axis in scope;
``dense_attention`` is the single-shard reference implementation (also used
when the mesh has no seq axis).

Kernel note: the per-hop online-softmax update stays in XLA rather than the
Pallas flash kernel (ops/flash_attention.py).  Using the Pallas kernel per
hop would require carrying its (o, m, l) accumulators through HBM between
hops AND a chunk-level custom VJP for the scan's backward.  Instead the hop
itself goes BLOCKWISE above a threshold: for S_local > ``_CHUNK_ABOVE`` the
hop streams the K/V block in ``block_k``-wide chunks through the same
online-softmax update (a nested ``lax.scan``), so per-hop score memory is
O(S_local * block_k) instead of O(S_local^2) — the regime S_local >= 4k
needs.  Each chunk update is ``jax.checkpoint``ed: the backward recomputes
chunk scores rather than storing every chunk's probabilities, keeping the
training-step footprint bounded as well.  Below the threshold the single-
block hop is kept (fewer scans, and the (S_local, S_local) block fuses
fine).  The Ulysses path is where the Pallas kernel pays off (each shard
sees the full sequence) and does use it (models/bert.py `_attention`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = float("-inf")


def dense_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None):
    """Plain softmax attention.  q,k,v: (B, H, S, D)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None]
        ki = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(ki > qi, NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# chunk the hop's K/V block when the local sequence exceeds this (the
# (S_local, S_local) fp32 score block at 1024 is 4 MB per (B, H) — beyond
# it, blockwise wins; below it, fusion of the single block is cheaper)
_CHUNK_ABOVE = 1024
_DEFAULT_BLOCK_K = 512


def _online_update(q, kb, vb, o, m, l, qpos, kpos, scale, causal):
    """One online-softmax accumulator update against K/V block ``kb/vb``.
    ``qpos``/``kpos``: absolute positions for the causal mask (ignored when
    ``causal`` is False).  Shared by the single-block and chunked hops."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kb,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        s = jnp.where(kpos[None, :] > qpos[:, None], NEG_INF, s)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # all-masked-so-far rows keep m == -inf; normalize against 0 there so
    # exp() never sees (-inf) - (-inf)
    m_use = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_use[..., None])
    corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_use))
    l = l * corr + jnp.sum(p, axis=-1)
    o = o * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(vb.dtype), vb,
        preferred_element_type=jnp.float32)
    return o, m_new, l


def ring_attention(q, k, v, axis_name: str = "seq", *,
                   causal: bool = False, scale: Optional[float] = None,
                   block_k: Optional[int] = None):
    """Blockwise ring attention.  q,k,v: (B, H, S_local, D) per shard.

    Equivalent to ``dense_attention`` on the gathered sequence (validated in
    tests/test_ring.py); communication is n-1 neighbor ``ppermute`` hops
    overlapping compute.  Per-shard score memory is O(S_local^2) for short
    shards and O(S_local * block_k) once the hop goes blockwise
    (S_local > 1024, or ``block_k`` set explicitly — see module docstring).
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    bq = q.shape[2]
    s_local = k.shape[2]
    if block_k is None and s_local > _CHUNK_ABOVE:
        # auto: the largest divisor of S_local <= the default block (gcd);
        # degenerate shard lengths (gcd < 128: tiny chunks would serialize
        # the MXU) keep the single-block hop rather than erroring — a
        # caller that passed no block_k must never see a divisibility error
        import math

        cand = math.gcd(s_local, _DEFAULT_BLOCK_K)
        block_k = cand if cand >= 128 else None
    if block_k is not None and (block_k <= 0 or s_local % block_k):
        raise ValueError(
            f"block_k {block_k} must divide the local K length {s_local}")
    # the accumulators must carry the same varying-axes type as q/k/v (they
    # are per-shard values), or the scan carry type check fails; deriving
    # them from q (rather than lax.pvary) inherits whatever set of mesh axes
    # q varies over — seq here, plus data/model when nested in a wider mesh
    zero_q = jnp.sum(q.astype(jnp.float32), axis=-1) * 0.0   # (B, H, Sq)
    o = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32) \
        + zero_q[..., None]
    m = jnp.full(q.shape[:3], NEG_INF, jnp.float32) + zero_q
    l = zero_q

    def body(carry, i):
        o, m, l, kb, vb = carry
        blk = (my - i) % n                                # global idx of kb
        qpos = my * bq + jnp.arange(bq)
        if block_k is None:
            kpos = blk * s_local + jnp.arange(s_local)
            o, m, l = _online_update(q, kb, vb, o, m, l, qpos, kpos,
                                     scale, causal)
        else:
            nc = s_local // block_k
            kcs = jnp.moveaxis(
                kb.reshape(kb.shape[:2] + (nc, block_k, kb.shape[3])), 2, 0)
            vcs = jnp.moveaxis(
                vb.reshape(vb.shape[:2] + (nc, block_k, vb.shape[3])), 2, 0)

            def chunk(acc, xs):
                o, m, l = acc
                kc, vc, ci = xs
                kpos = blk * s_local + ci * block_k + jnp.arange(block_k)
                return _online_update(q, kc, vc, o, m, l, qpos, kpos,
                                      scale, causal), None

            # remat: the backward recomputes each chunk's scores instead of
            # storing every chunk's (B, H, Sq, block_k) probabilities —
            # this is what keeps the TRAINING footprint at O(Sq * block_k)
            (o, m, l), _ = lax.scan(jax.checkpoint(chunk), (o, m, l),
                                    (kcs, vcs, jnp.arange(nc)))
        perm = [(j, (j + 1) % n) for j in range(n)]
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (o, m, l, kb, vb), None

    (o, m, l, _, _), _ = lax.scan(body, (o, m, l, k, v), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l[..., None]).astype(q.dtype)
