"""Ring attention: sequence/context parallelism over a mesh axis.

Long sequences are sharded along a ``seq`` mesh axis; each shard holds a
block of queries and a block of keys/values.  K/V blocks rotate around the
ring via ``ppermute`` (ICI neighbor exchanges) while each shard accumulates
its queries' attention with the streaming (online) softmax — no shard ever
materializes the full (S, S) score matrix or the full K/V, so sequence
length scales with the number of shards at constant per-chip memory.

This subsystem has no counterpart in the reference (no attention, no
sequence axis — SURVEY.md §2 parallelism checklist); it is required by the
framework goal of first-class long-context training.

Call ``ring_attention`` inside ``shard_map`` with the ``seq`` axis in scope;
``dense_attention`` is the single-shard reference implementation (also used
when the mesh has no seq axis).

Kernel note: the per-hop online-softmax update stays in XLA rather than the
Pallas flash kernel (ops/flash_attention.py).  Each hop's score block is
(S_local, S_local) and lives entirely in registers/VMEM under XLA fusion;
using the Pallas kernel per hop would require carrying its (o, m, l)
accumulators through HBM between hops AND a chunk-level custom VJP for the
scan's backward — cost without benefit at the S_local (<= a few K) a ring
shard holds.  The Ulysses path is where the kernel pays off (each shard
sees the full sequence) and does use it (models/bert.py `_attention`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = float("-inf")


def dense_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None):
    """Plain softmax attention.  q,k,v: (B, H, S, D)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None]
        ki = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(ki > qi, NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def ring_attention(q, k, v, axis_name: str = "seq", *,
                   causal: bool = False, scale: Optional[float] = None):
    """Blockwise ring attention.  q,k,v: (B, H, S_local, D) per shard.

    Equivalent to ``dense_attention`` on the gathered sequence (validated in
    tests/test_ring.py); per-shard memory is O(S_local^2) scores instead of
    O(S^2), and communication is n-1 neighbor ``ppermute`` hops overlapping
    compute.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    bq = q.shape[2]
    # the accumulators must carry the same varying-axes type as q/k/v (they
    # are per-shard values), or the scan carry type check fails; deriving
    # them from q (rather than lax.pvary) inherits whatever set of mesh axes
    # q varies over — seq here, plus data/model when nested in a wider mesh
    zero_q = jnp.sum(q.astype(jnp.float32), axis=-1) * 0.0   # (B, H, Sq)
    o = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32) \
        + zero_q[..., None]
    m = jnp.full(q.shape[:3], NEG_INF, jnp.float32) + zero_q
    l = zero_q

    def body(carry, i):
        o, m, l, kb, vb = carry
        blk = (my - i) % n                                # global idx of kb
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kb,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = my * bq + jnp.arange(bq)[:, None]
            kpos = blk * kb.shape[2] + jnp.arange(kb.shape[2])[None, :]
            s = jnp.where(kpos > qpos, NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # all-masked-so-far rows keep m == -inf; normalize against 0 there so
        # exp() never sees (-inf) - (-inf)
        m_use = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_use[..., None])
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_use))
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vb.dtype), vb,
            preferred_element_type=jnp.float32)
        perm = [(j, (j + 1) % n) for j in range(n)]
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (o, m_new, l, kb, vb), None

    (o, m, l, _, _), _ = lax.scan(body, (o, m, l, k, v), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l[..., None]).astype(q.dtype)
