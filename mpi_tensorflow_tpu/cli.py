"""Command-line entry point.

Zero-flag invocation reproduces the reference's hard-wired defaults
(``iteration = 2``, ``batch_size = 64``, ``image_size = 28``, 10 classes —
mpipy.py:18-21) scaled transparently from one chip to a pod slice; every
constant is also exposed as a flag, which the reference lacks entirely
(SURVEY.md §5 config row).

    python -m mpi_tensorflow_tpu                 # the `mpiexec -n N python
                                                 # mpipy.py` equivalent
    python -m mpi_tensorflow_tpu --sync avg50    # reference-fidelity sync
    python -m mpi_tensorflow_tpu --model resnet20 --dataset cifar10
"""

from __future__ import annotations

import argparse

from mpi_tensorflow_tpu.config import Config


TRANSFORMER_MODELS = ("bert_base", "moe_bert", "gpt_base", "encdec_t5")

def build_parser() -> argparse.ArgumentParser:
    d = Config()
    p = argparse.ArgumentParser(
        prog="mpi_tensorflow_tpu",
        description="TPU-native data-parallel trainer "
                    "(capabilities of youzhenfei1995/mpi-Tensorflow)")
    p.add_argument("--epochs", type=int, default=d.epochs,
                   help="the reference's `iteration` (mpipy.py:18)")
    p.add_argument("--batch-size", type=int, default=d.batch_size,
                   help="per-shard batch size (mpipy.py:20)")
    p.add_argument("--image-size", type=int, default=d.image_size)
    p.add_argument("--num-classes", type=int, default=d.num_classes,
                   help="the reference's misnamed `num_channel` (mpipy.py:21)")
    p.add_argument("--base-lr", type=float, default=d.base_lr)
    p.add_argument("--lr-decay", type=float, default=d.lr_decay)
    p.add_argument("--momentum", type=float, default=d.momentum)
    p.add_argument("--weight-decay", type=float, default=d.weight_decay)
    p.add_argument("--log-every", type=int, default=d.log_every)
    p.add_argument("--early-stop-patience", type=int,
                   default=d.early_stop_patience,
                   help="stop when validation error hasn't improved for N "
                        "trace points (0 = off, the reference's behavior — "
                        "it scatters validation shards and never reads "
                        "them, mpipy.py:236-241)")
    p.add_argument("--sync", choices=["psum", "avg50"], default=d.sync,
                   help="psum: per-step gradient allreduce (sync SGD); "
                        "avg50: the reference's periodic parameter averaging "
                        "with its rank-0-only bug fixed")
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--data-dir", default=d.data_dir)
    p.add_argument("--model", default=d.model,
                   choices=["mnist_cnn", "resnet20", "resnet50", "vit",
                            "bert_base", "moe_bert", "gpt_base",
                            "encdec_t5"])
    p.add_argument("--dataset", default=d.dataset,
                   choices=["mnist", "cifar10", "imagenet_synthetic",
                            "mlm_synthetic"])
    p.add_argument("--checkpoint-dir", default=None,
                   help="save train state here at the log cadence")
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest checkpoint in --checkpoint-dir")
    p.add_argument("--mesh", default=None,
                   help="mesh spec, e.g. 'data=8' or 'data=4,model=2'; "
                        "default: all devices on one data axis")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace here")
    p.add_argument("--metrics-dir", default=None,
                   help="stream scalar metrics here: TensorBoard event "
                        "files (when tensorboardX is available) plus a "
                        "metrics.jsonl that needs no reader dependency")
    p.add_argument("--fused-steps", type=int, default=None,
                   help="train steps per device dispatch (lax.scan). "
                        "Default: the --log-every cadence for psum mode "
                        "(one dispatch per trace window), 1 for avg50. "
                        "Pass 1 for the reference's one-dispatch-per-step "
                        "shape")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize transformer layers (jax.checkpoint): "
                        "trade recompute FLOPs for peak activation HBM")
    p.add_argument("--text-file", default=None,
                   help="train the LM families on a local text file "
                        "(data/corpus.py) instead of the synthetic stream")
    p.add_argument("--vocab-file", default=None,
                   help="WordPiece vocabulary for --text-file (one token "
                        "per line, BERT vocab.txt layout); default: "
                        "self-contained byte-level tokenizer (vocab 261)")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="elastic recovery: restart from the latest "
                        "checkpoint after transient infrastructure "
                        "failures (train/elastic.py; pair with "
                        "--checkpoint-dir)")
    p.add_argument("--param-sharding",
                   choices=["replicated", "fsdp", "zero1"],
                   default=d.param_sharding,
                   help="transformer-family state layout: replicated "
                        "(default), fsdp (params+moments sharded over "
                        "'data', ZeRO-3-style), or zero1 (optimizer "
                        "moments sharded, params keep their layout — "
                        "composes with pipe meshes)")
    p.add_argument("--prefetch", choices=["auto", "native", "thread", "off"],
                   default=d.prefetch,
                   help="background window assembly for the fused loop "
                        "(native = C++ worker, data/prefetch.py)")
    p.add_argument("--pp-schedule",
                   choices=["gpipe", "1f1b", "1f1b_interleaved"],
                   default=d.pp_schedule,
                   help="pipeline schedule for --mesh pipe=N runs: gpipe "
                        "(autodiff backward), 1f1b (one-forward-one-"
                        "backward; same bubble, O(P) activation stash), "
                        "or 1f1b_interleaved (--virtual-stages chunks per "
                        "device; bubble shrinks ~v-fold)")
    p.add_argument("--virtual-stages", type=int, default=d.virtual_stages,
                   help="virtual chunks per device for "
                        "--pp-schedule 1f1b_interleaved")
    p.add_argument("--grad-accum", type=int, default=d.grad_accum,
                   help="microbatches accumulated per optimizer step "
                        "(activation-memory / batch-size trade)")
    p.add_argument("--precision", choices=["fp32", "bf16"], default=d.precision,
                   help="compute dtype for matmuls/convs (bf16 doubles MXU "
                        "throughput; params and loss stay fp32)")
    p.add_argument("--optimizer", choices=["adamw", "lamb"],
                   default=d.optimizer,
                   help="transformer-family optimizer (lamb = layer-wise "
                        "trust ratios, the large-batch BERT recipe); the "
                        "image families keep the reference's momentum SGD")
    p.add_argument("--serve-pool-blocks", type=int,
                   default=d.serve_pool_blocks,
                   help="serving: paged KV pool size in blocks (block 0 "
                        "reserved as the null block; serving/paged_cache)")
    p.add_argument("--serve-block-size", type=int,
                   default=d.serve_block_size,
                   help="serving: cache entries per pool block")
    p.add_argument("--serve-max-slots", type=int,
                   default=d.serve_max_slots,
                   help="serving: concurrent sequences (continuous-"
                        "batching decode batch cap)")
    p.add_argument("--serve-max-seq-len", type=int,
                   default=d.serve_max_seq_len,
                   help="serving: per-request prompt+output cap (sizes "
                        "the per-sequence block table)")
    p.add_argument("--serve-kernel", choices=["auto", "xla", "pallas"],
                   default=d.serve_kernel,
                   help="serving: paged-attention lowering — auto picks "
                        "the fused Pallas decode kernel on TPU when its "
                        "compile probe passes and the XLA gather path "
                        "otherwise; xla/pallas force one side "
                        "(ops/paged_attention.resolve_kernel)")
    p.add_argument("--serve-kv-dtype", choices=["fp32", "int8", "int4"],
                   default=d.serve_kv_dtype,
                   help="serving: paged-pool storage format — fp32 "
                        "keeps the blocks in the model compute dtype "
                        "(byte-for-byte the pre-quantization pool); "
                        "int8 stores symmetric-absmax codes with "
                        "per-(block, head, slot) fp32 row scales "
                        "(~4x effective KV capacity), dequantized "
                        "inside the attention consume paths "
                        "(serving/paged_cache, ops/paged_attention); "
                        "int4 nibble-packs two codes per byte with "
                        "per-group fp32 scales (--serve-kv-group) plus "
                        "a full-precision self lane for each step's "
                        "own tokens — the next capacity rung")
    p.add_argument("--serve-kv-group", type=int, default=d.serve_kv_group,
                   help="serving: int4 scale-group size along head_dim "
                        "— one fp32 scale per group (clamped to "
                        "head_dim on small heads, must divide it); "
                        "smaller groups quantize tighter at more scale "
                        "bytes; consumed only with --serve-kv-dtype "
                        "int4")
    p.add_argument("--serve-kv-tier", choices=["off", "host"],
                   default=d.serve_kv_tier,
                   help="serving: host-RAM KV block tier — host "
                        "demotes cold prefix-cache blocks to host "
                        "memory on eviction and promotes them back "
                        "into fresh device blocks when a later prompt "
                        "matches their trie path, so multi-turn "
                        "sessions stop re-paying prefill; requires "
                        "--serve-prefix-cache on; off is byte-for-byte "
                        "untiered (serving/paged_cache.HostBlockStore)")
    p.add_argument("--serve-prefix-cache", choices=["off", "on"],
                   default=d.serve_prefix_cache,
                   help="serving: radix prefix cache — on shares "
                        "already-cached full prompt blocks across "
                        "requests (refcounted block reuse, copy-on-"
                        "write on divergence, LRU trie eviction under "
                        "pool pressure; serving/prefix_cache); off "
                        "preserves the unshared behavior byte-for-byte")
    p.add_argument("--serve-prefix-gen", choices=["off", "on"],
                   default=d.serve_prefix_gen,
                   help="serving: prefix cache v2 — on additionally "
                        "caches a finished request's generated full "
                        "blocks in the trie (multi-turn reuse) and "
                        "shares partial tail blocks via a one-compile "
                        "row-prefix copy; off keeps "
                        "--serve-prefix-cache on behavior byte-for-"
                        "byte; requires --serve-prefix-cache on")
    p.add_argument("--serve-prefix-route", choices=["off", "on"],
                   default=d.serve_prefix_route,
                   help="serving: prefix-aware fleet routing — on "
                        "biases sessionless placement toward the "
                        "replica whose trie caches the prompt's "
                        "leading full block (load-bounded; never "
                        "overrides the health gate, never changes "
                        "tokens; serving/router); requires "
                        "--serve-prefix-cache on")
    p.add_argument("--serve-speculative",
                   choices=["off", "ngram", "draft-model"],
                   default=d.serve_speculative,
                   help="serving: speculative decoding — ngram drafts "
                        "from the sequence's own earlier tokens, "
                        "draft-model runs a tiny CausalLm over its own "
                        "paged pool; k drafted tokens verify in ONE "
                        "batched forward and only the argmax-matching "
                        "prefix is emitted, so greedy outputs stay "
                        "token-identical to off (the byte-for-byte "
                        "one-token loop; serving/speculative)")
    p.add_argument("--serve-draft-k", type=int, default=d.serve_draft_k,
                   help="serving: speculative draft window — tokens "
                        "proposed per verify forward (dispatch width "
                        "draft_k + 1); >= 1")
    p.add_argument("--serve-draft-auto", choices=["off", "on"],
                   default=d.serve_draft_auto,
                   help="serving: auto-tune the speculative draft "
                        "window — on adapts the effective k to an EWMA "
                        "of the observed accept length, clamped to "
                        "[1, --serve-draft-k] (the verify dispatch "
                        "width never changes, so the zero-recompile "
                        "contract is untouched); needs a drafter "
                        "(--serve-speculative ngram|draft-model)")
    p.add_argument("--serve-mixed-batch", choices=["off", "on"],
                   default=d.serve_mixed_batch,
                   help="serving: stall-free mixed batching — on fuses "
                        "budget-capped prefill chunks from multiple "
                        "mid-prefill sequences into the decode dispatch "
                        "so every step is ONE forward (chunked-prefill "
                        "math, token-identical to off by construction); "
                        "off preserves the two-dispatch prefill-then-"
                        "decode loop byte-for-byte")
    p.add_argument("--serve-prefill-budget", type=int,
                   default=d.serve_prefill_budget,
                   help="serving: mixed-batching budget — max prefill "
                        "tokens fused into one step across all "
                        "mid-prefill sequences (>= 1; consumed only "
                        "with --serve-mixed-batch on)")
    p.add_argument("--serve-tp", type=int, default=d.serve_tp,
                   help="serving: tensor-parallel shards for the decode "
                        "engine — >1 partitions the paged pool's head "
                        "axis, the QKV/O projections, and the MLP over "
                        "a tp mesh axis (serving/tp; one psum per "
                        "row-parallel output, block tables replicated)."
                        " Must divide the model's heads/mlp dims and "
                        "fit the visible device count")
    p.add_argument("--serve-replicas", type=int, default=d.serve_replicas,
                   help="serving: data-parallel engine replicas fronted "
                        "by the serving router (session-affinity "
                        "placement + least-load admission over queue "
                        "depth / pool occupancy / shed rate); each "
                        "replica owns its own pool and scheduler")
    p.add_argument("--serve-deadline-ms", type=float,
                   default=d.serve_deadline_ms,
                   help="serving: default per-request TTL from arrival; "
                        "work not complete by then fails with "
                        "deadline_exceeded instead of occupying a slot "
                        "(default: no deadline)")
    p.add_argument("--serve-queue-depth", type=int,
                   default=d.serve_queue_depth,
                   help="serving: bound on the waiting queue; a full "
                        "queue load-sheds the newest submit with a "
                        "queue_full reason (default: unbounded)")
    p.add_argument("--serve-max-evictions", type=int,
                   default=d.serve_max_evictions,
                   help="serving: a request preempted more than this "
                        "many times fails with evicted_too_often "
                        "instead of requeueing forever (default: "
                        "unbounded)")
    p.add_argument("--serve-failover-backoff-ms", type=float,
                   default=d.serve_failover_backoff_ms,
                   help="serving replica circuit breaker: base probe "
                        "backoff after a transient replica fault "
                        "(doubled per consecutive fault, capped at "
                        "64x) before the router rebuilds and probes "
                        "the replica back in (serving/router)")
    p.add_argument("--serve-drain-ms", type=float,
                   default=d.serve_drain_ms,
                   help="serving: graceful-drain budget after SIGTERM — "
                        "in-flight sequences finish inside it, the rest "
                        "terminate with status `drained` (default: "
                        "finish all in-flight work)")
    p.add_argument("--serve-workload",
                   choices=["poisson", "bursty", "multi-tenant",
                            "diurnal"], default=d.serve_workload,
                   help="serving: synthetic trace shape for bench "
                        "--mode serving (serving/loadgen) — poisson is "
                        "the historical byte-identical default; bursty "
                        "= 2-state MMPP arrivals; multi-tenant adds an "
                        "interactive-vs-batch tenant mix with "
                        "per-tenant SLOs and sticky sessions; diurnal "
                        "= raised-cosine rate envelope")
    p.add_argument("--serve-slo-ms", type=float, default=d.serve_slo_ms,
                   help="serving: per-request latency budget, stamped "
                        "as each request's deadline; the goodput block "
                        "scores tokens/sec from requests that finished "
                        "within it (default: no SLO)")
    p.add_argument("--serve-trace", choices=["off", "on"],
                   default=d.serve_trace,
                   help="serving: request-lifecycle + step-phase "
                        "tracing (serving/tracing) — host-side span "
                        "stamps (zero device syncs) plus the "
                        "`breakdown` latency-attribution block in "
                        "bench detail; off is byte-for-byte the "
                        "untraced behavior")
    p.add_argument("--serve-trace-out", type=str,
                   default=d.serve_trace_out,
                   help="serving: write the run's Chrome trace-event "
                        "JSON here (open in Perfetto or "
                        "chrome://tracing); requires --serve-trace on")
    p.add_argument("--prng", choices=["threefry", "rbg", "unsafe_rbg"],
                   default=d.prng_impl,
                   help="dropout-mask PRNG: threefry (JAX default, "
                        "bit-reproducible) or rbg/unsafe_rbg (XLA "
                        "RngBitGenerator — much cheaper mask generation on "
                        "TPU; a BERT step generates 25 (B,S,E) masks). "
                        "Parameter init always uses threefry")
    return p


def parse_mesh(spec: str | None):
    if spec is None:
        return None
    out = {}
    for part in spec.split(","):
        k, v = part.split("=")
        out[k.strip()] = int(v)
    return out


def config_from_args(args) -> Config:
    return Config(
        epochs=args.epochs, image_size=args.image_size,
        batch_size=args.batch_size, num_classes=args.num_classes,
        base_lr=args.base_lr, lr_decay=args.lr_decay, momentum=args.momentum,
        weight_decay=args.weight_decay, log_every=args.log_every,
        early_stop_patience=args.early_stop_patience,
        sync=args.sync, seed=args.seed, data_dir=args.data_dir,
        model=args.model, dataset=args.dataset,
        mesh_shape=parse_mesh(args.mesh), text_file=args.text_file,
        vocab_file=args.vocab_file,
        checkpoint_dir=args.checkpoint_dir, resume=args.resume,
        metrics_dir=args.metrics_dir,
        precision=args.precision, prng_impl=args.prng,
        optimizer=args.optimizer, grad_accum=args.grad_accum,
        pp_schedule=args.pp_schedule,
        virtual_stages=args.virtual_stages,
        param_sharding=args.param_sharding,
        serve_pool_blocks=args.serve_pool_blocks,
        serve_block_size=args.serve_block_size,
        serve_max_slots=args.serve_max_slots,
        serve_max_seq_len=args.serve_max_seq_len,
        serve_kernel=args.serve_kernel,
        serve_kv_dtype=args.serve_kv_dtype,
        serve_kv_group=args.serve_kv_group,
        serve_kv_tier=args.serve_kv_tier,
        serve_prefix_cache=args.serve_prefix_cache,
        serve_prefix_gen=args.serve_prefix_gen,
        serve_prefix_route=args.serve_prefix_route,
        serve_speculative=args.serve_speculative,
        serve_draft_k=args.serve_draft_k,
        serve_draft_auto=args.serve_draft_auto,
        serve_mixed_batch=args.serve_mixed_batch,
        serve_prefill_budget=args.serve_prefill_budget,
        serve_tp=args.serve_tp,
        serve_replicas=args.serve_replicas,
        serve_deadline_ms=args.serve_deadline_ms,
        serve_queue_depth=args.serve_queue_depth,
        serve_max_evictions=args.serve_max_evictions,
        serve_drain_ms=args.serve_drain_ms,
        serve_failover_backoff_ms=args.serve_failover_backoff_ms,
        serve_workload=args.serve_workload,
        serve_slo_ms=args.serve_slo_ms,
        serve_trace=args.serve_trace,
        serve_trace_out=args.serve_trace_out,
        prefetch=args.prefetch, remat=args.remat,
        fused_steps=(args.fused_steps if args.fused_steps is not None
                     else (args.log_every if args.sync == "psum" else 1)),
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    config = config_from_args(args)

    # flag-combination guards run BEFORE any jax/backend touch (fail fast,
    # no device init on a doomed invocation)
    if args.max_restarts > 0 and not config.checkpoint_dir:
        raise SystemExit(
            "--max-restarts needs --checkpoint-dir: without checkpoints a "
            "restart would silently re-train from step 0")
    if config.text_file and config.model not in ("bert_base", "moe_bert",
                                                 "gpt_base"):
        raise SystemExit(
            f"--text-file applies to the language-model families "
            f"(bert_base, moe_bert, gpt_base); --model {config.model} "
            f"would silently ignore it")
    if config.vocab_file and not config.text_file:
        raise SystemExit("--vocab-file only applies with --text-file")
    if config.optimizer != "adamw" and config.model not in TRANSFORMER_MODELS:
        raise SystemExit(
            f"--optimizer {config.optimizer} applies to the transformer "
            f"families; the image families train with the reference's "
            f"momentum SGD (mpipy.py:65) and would silently ignore it")
    if config.param_sharding != "replicated" and config.model not in TRANSFORMER_MODELS:
        raise SystemExit(
            f"--param-sharding {config.param_sharding} applies to the "
            f"transformer families (GSPMD step); the image loop keeps "
            f"the reference's replicated layout and would silently "
            f"ignore it")
    if args.virtual_stages != Config.virtual_stages \
            and config.pp_schedule != "1f1b_interleaved":
        raise SystemExit(
            f"--virtual-stages {args.virtual_stages} applies only with "
            f"--pp-schedule 1f1b_interleaved; schedule "
            f"{config.pp_schedule!r} would silently ignore it")
    if config.serve_block_size < 1 or config.serve_pool_blocks < 2 \
            or config.serve_max_slots < 1 or config.serve_max_seq_len < 1:
        raise SystemExit(
            f"bad --serve-* geometry: pool-blocks "
            f"{config.serve_pool_blocks} (>= 2; block 0 is reserved), "
            f"block-size {config.serve_block_size} (>= 1), max-slots "
            f"{config.serve_max_slots} (>= 1), max-seq-len "
            f"{config.serve_max_seq_len} (>= 1)")
    if config.serve_kv_dtype not in ("fp32", "int8", "int4"):
        # argparse choices guard the CLI path; this covers programmatic
        # Config construction routed through main
        raise SystemExit(
            f"bad --serve-kv-dtype {config.serve_kv_dtype!r}: "
            f"must be fp32|int8|int4")
    if config.serve_kv_group < 1:
        raise SystemExit(
            f"bad --serve-kv-group {config.serve_kv_group}: must be "
            f">= 1 (one fp32 scale per group of head_dim channels)")
    if config.serve_kv_tier not in ("off", "host"):
        # argparse choices guard the CLI path; this covers programmatic
        # Config construction routed through main
        raise SystemExit(
            f"bad --serve-kv-tier {config.serve_kv_tier!r}: "
            f"must be off|host")
    if config.serve_kv_tier == "host" \
            and config.serve_prefix_cache == "off":
        raise SystemExit(
            "--serve-kv-tier host demotes/promotes radix-trie blocks; "
            "with --serve-prefix-cache off there are no trie paths to "
            "key the host store by — turn the cache on or drop the tier")
    if config.serve_prefix_cache not in ("off", "on"):
        # argparse choices guard the CLI path; this covers programmatic
        # Config construction routed through main
        raise SystemExit(
            f"bad --serve-prefix-cache {config.serve_prefix_cache!r}: "
            f"must be off|on")
    if config.serve_prefix_gen not in ("off", "on"):
        # argparse choices guard the CLI path; this covers programmatic
        # Config construction routed through main
        raise SystemExit(
            f"bad --serve-prefix-gen {config.serve_prefix_gen!r}: "
            f"must be off|on")
    if config.serve_prefix_route not in ("off", "on"):
        # argparse choices guard the CLI path; this covers programmatic
        # Config construction routed through main
        raise SystemExit(
            f"bad --serve-prefix-route {config.serve_prefix_route!r}: "
            f"must be off|on")
    if config.serve_prefix_gen == "on" \
            and config.serve_prefix_cache == "off":
        raise SystemExit(
            "--serve-prefix-gen on extends the radix prefix cache; with "
            "--serve-prefix-cache off it would be silently ignored — "
            "turn the cache on or drop it")
    if config.serve_prefix_route == "on" \
            and config.serve_prefix_cache == "off":
        raise SystemExit(
            "--serve-prefix-route on routes by cached prefixes; with "
            "--serve-prefix-cache off there is nothing to route by — "
            "turn the cache on or drop it")
    if config.serve_kernel not in ("auto", "xla", "pallas"):
        # argparse choices guard the CLI path; this covers programmatic
        # Config construction routed through main
        raise SystemExit(
            f"bad --serve-kernel {config.serve_kernel!r}: "
            f"must be auto|xla|pallas")
    if config.serve_speculative not in ("off", "ngram", "draft-model") \
            or config.serve_draft_k < 1:
        raise SystemExit(
            f"bad --serve-speculative config: mode "
            f"{config.serve_speculative!r} (off|ngram|draft-model), "
            f"draft-k {config.serve_draft_k} (>= 1)")
    if config.serve_draft_auto not in ("off", "on"):
        raise SystemExit(
            f"bad --serve-draft-auto {config.serve_draft_auto!r}: "
            f"must be off|on")
    if config.serve_draft_auto == "on" \
            and config.serve_speculative == "off":
        raise SystemExit(
            "--serve-draft-auto on tunes the speculative draft window; "
            "with --serve-speculative off it would be silently ignored "
            "— pick a drafter or drop it")
    if config.serve_mixed_batch not in ("off", "on"):
        # argparse choices guard the CLI path; this covers programmatic
        # Config construction routed through main
        raise SystemExit(
            f"bad --serve-mixed-batch {config.serve_mixed_batch!r}: "
            f"must be off|on")
    if config.serve_prefill_budget < 1:
        raise SystemExit(
            f"bad --serve-prefill-budget {config.serve_prefill_budget}: "
            f"the per-step fused prefill token budget must be >= 1")
    if config.serve_mixed_batch == "on" \
            and config.serve_speculative != "off":
        raise SystemExit(
            "--serve-mixed-batch on and --serve-speculative each replace "
            "the decode dispatch with their own fused forward; they do "
            "not compose — pick one")
    if config.serve_tp < 1 or config.serve_replicas < 1:
        # range guards only: head/mlp divisibility and the device-count
        # bound need the model geometry and an initialized backend, so
        # they live where both are known (serving/tp.check_geometry at
        # engine construction)
        raise SystemExit(
            f"bad distributed-serving knobs: --serve-tp "
            f"{config.serve_tp} (>= 1), --serve-replicas "
            f"{config.serve_replicas} (>= 1)")
    if (config.serve_deadline_ms is not None
            and config.serve_deadline_ms <= 0) \
            or (config.serve_queue_depth is not None
                and config.serve_queue_depth < 1) \
            or (config.serve_max_evictions is not None
                and config.serve_max_evictions < 1) \
            or (config.serve_drain_ms is not None
                and config.serve_drain_ms < 0) \
            or config.serve_failover_backoff_ms <= 0:
        raise SystemExit(
            f"bad --serve-* fault policy: deadline-ms "
            f"{config.serve_deadline_ms} (> 0), queue-depth "
            f"{config.serve_queue_depth} (>= 1), max-evictions "
            f"{config.serve_max_evictions} (>= 1), drain-ms "
            f"{config.serve_drain_ms} (>= 0), failover-backoff-ms "
            f"{config.serve_failover_backoff_ms} (> 0)")
    if config.serve_workload not in ("poisson", "bursty", "multi-tenant",
                                     "diurnal"):
        # argparse choices guard the CLI path; this covers programmatic
        # Config construction routed through main
        raise SystemExit(
            f"bad --serve-workload {config.serve_workload!r}: must be "
            f"poisson|bursty|multi-tenant|diurnal")
    if config.serve_slo_ms is not None and not config.serve_slo_ms > 0:
        raise SystemExit(
            f"bad --serve-slo-ms {config.serve_slo_ms}: the latency "
            f"budget must be > 0 ms")
    if config.serve_trace not in ("off", "on"):
        # argparse choices guard the CLI path; this covers programmatic
        # Config construction routed through main
        raise SystemExit(
            f"bad --serve-trace {config.serve_trace!r}: must be off|on")
    if config.serve_trace_out is not None and config.serve_trace != "on":
        raise SystemExit(
            f"--serve-trace-out {config.serve_trace_out!r} requires "
            f"--serve-trace on (there is no trace to write otherwise)")

    from mpi_tensorflow_tpu.parallel import mesh as meshlib

    meshlib.initialize_distributed()

    from mpi_tensorflow_tpu.utils import profiling

    def run_once():
        if config.model in ("bert_base", "moe_bert", "gpt_base",
                            "encdec_t5"):
            from mpi_tensorflow_tpu.train import mlm_loop

            return mlm_loop.train_mlm(config)
        from mpi_tensorflow_tpu.train import loop

        return loop.train(config)

    with profiling.trace(args.profile_dir):
        if args.max_restarts > 0:
            from mpi_tensorflow_tpu.train import elastic

            def on_restart(i, e):
                # retries resume from the latest committed checkpoint
                config.resume = True

            elastic.run_with_recovery(run_once,
                                      max_restarts=args.max_restarts,
                                      on_restart=on_restart)
        else:
            run_once()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
