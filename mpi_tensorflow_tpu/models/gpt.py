"""Decoder-only causal LM (GPT-style) — the autoregressive family.

The reference has no transformer at all; BASELINE.json's directed scale-out
stops at BERT-base MLM.  This family demonstrates the framework's
generality beyond the directed set: the SAME encoder blocks, sharding
rules, attention kernels (causal flash / causal ring / causal Ulysses),
loss machinery (chunked CE), optimizer, loops, and checkpointing drive an
autoregressive LM — only the attention mask and the loss targets change.

Implementation: subclasses ``BertMlm`` with ``causal=True`` (the mask is
threaded through BertMlm._attention's dense/ring/Ulysses/flash paths — one
implementation, no copied override) and
- next-token loss: CE of position t against token t+1, over ALL positions
  (no mask packing — every position carries loss), using the same chunked
  online-logsumexp CE so (B, S, V) logits never materialize;
- untied LM head option is intentionally omitted: weight tying matches the
  MLM family and keeps vocab-parallel TP identical.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from mpi_tensorflow_tpu.models import bert as bert_lib
from mpi_tensorflow_tpu.models import bert_pipeline
from mpi_tensorflow_tpu.models.bert import _layernorm
from mpi_tensorflow_tpu.ops import paged_attention as paged_ops
from mpi_tensorflow_tpu.utils import engagement


def _shift_targets(tokens):
    """THE next-token supervision definition, shared by the plain and
    pipelined causal families (they are not linked by MRO): targets are
    the inputs shifted left padded with 0, and the final position's
    weight is 0 (unsupervised).  Returns ``(targets, weights)``."""
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1)
    w = jnp.ones(tokens.shape, jnp.float32).at[:, -1].set(0.0)
    return targets, w


@dataclasses.dataclass(frozen=True)
class CausalLm(bert_lib.BertMlm):
    """GPT-style causal LM on the shared transformer stack."""
    causal: bool = True

    def loss(self, params, model_state, batch, labels=None, *, rng=None,
             train: bool = False):
        """Next-token CE.  ``batch``: dict with ``tokens`` (B, S) (or the
        raw (B, S) int array); ``labels`` is ignored — targets are the
        inputs shifted left, with the final position unsupervised."""
        tokens = batch["tokens"] if isinstance(batch, dict) else batch
        h, aux = self._encode_aux(params, tokens, train=train, rng=rng)
        t = self.head_hidden(params, h)
        targets, w = _shift_targets(tokens)
        ce = self._ce(params, t, targets)                       # (B, S)
        loss = jnp.sum(ce * w) / jnp.maximum(jnp.sum(w), 1.0)
        return loss + self._aux_weight() * aux, model_state

    def _packs_positions(self) -> bool:
        return False   # every position carries loss — no mask packing

    # ------------------------------------------------------------------
    # autoregressive inference: KV cache + generate()
    #
    # The reference ships batched (non-autoregressive) inference only
    # (mpipy.py:169-183); decoding extends that role to this family.
    # TPU-shaped: the cache is a STATIC (B, H, max_len, D) buffer per
    # layer updated with lax.dynamic_update_slice, the decode loop is a
    # lax.scan — no data-dependent Python control flow, one compilation.
    # ------------------------------------------------------------------

    def init_cache(self, batch_size: int, max_len: int) -> list:
        """Per-layer K/V buffers (zeros).  ``max_len`` caps prompt+output;
        under learned positions it must fit the pos_emb table — rope has
        no table and decodes to any length."""
        c = self.cfg
        if c.pos_kind == "learned" and max_len > c.max_positions:
            raise ValueError(
                f"max_len {max_len} exceeds max_positions {c.max_positions}")
        z = jnp.zeros((batch_size, c.heads, max_len, c.head_dim), c.dtype)
        return [{"k": z, "v": z} for _ in range(c.layers)]

    def forward_with_cache(self, params, tokens, cache, offset):
        """Forward ``tokens`` (B, S_in) occupying absolute positions
        [offset, offset+S_in), reading/writing the KV cache.

        One implementation serves both phases: prefill (S_in = prompt
        length, offset 0) and single-token decode (S_in = 1, traced
        offset).  Returns (fp32 logits (B, S_in, V), updated cache).

        Distributed decode: when the model carries a mesh, the same
        logical-axis constraints as training apply — batch over ``data``,
        attention heads (and therefore the KV cache's H dim) over
        ``model`` with GSPMD inserting the row-parallel psum in
        ``attn_out_proj``; the cache length dim stays replicated
        (``pos``) so the traced-offset dynamic_update_slice never crosses
        a shard boundary.  Math is kept in lockstep with the training
        stack — pinned by the incremental-vs-full parity test and the
        sharded-vs-single-device decode test (tests/test_gpt.py)."""
        c = self.cfg
        dt = c.dtype
        B, S_in = tokens.shape
        L = cache[0]["k"].shape[2]
        offset = jnp.asarray(offset, jnp.int32)

        if c.pos_kind == "rope":
            h = params["tok_emb"][tokens]
        else:
            pos_emb = lax.dynamic_slice(
                params["pos_emb"], (offset, 0), (S_in, c.hidden))
            h = params["tok_emb"][tokens] + pos_emb[None]
        h = _layernorm(h, params["emb_ln"]).astype(dt)
        h = self._constrain(h, ("batch", "seq", "embed"))

        pos = offset + jnp.arange(S_in)                    # (S_in,) absolute
        col = jnp.arange(L)
        # causal visibility over the cache: key position <= query position
        vis = col[None, :] <= pos[:, None]                 # (S_in, L)

        qkv_axes = ("batch", "heads", "seq", "head_dim")
        cache_axes = ("batch", "heads", "pos", "head_dim")
        new_cache = []
        for lp, cc in zip(params["layers"], cache):
            q, k, v = bert_lib.qkv_proj(lp, h, dt, fused=c.fused_qkv)
            if c.pos_kind == "rope":
                # rotate at ABSOLUTE positions; keys enter the cache
                # already rotated, so cached entries never re-rotate
                q = bert_lib.rope(q, pos)
                k = bert_lib.rope(k, pos)
            q = self._constrain(q, qkv_axes)
            ck = lax.dynamic_update_slice(cc["k"], k, (0, 0, offset, 0))
            cv = lax.dynamic_update_slice(cc["v"], v, (0, 0, offset, 0))
            ck = self._constrain(ck, cache_axes)
            cv = self._constrain(cv, cache_axes)
            new_cache.append({"k": ck, "v": cv})
            # the ONE fp32 masked-softmax implementation, shared with the
            # paged path (ops/paged_attention) — token parity between the
            # two holds by construction, not by review discipline
            a = paged_ops.masked_softmax_attention(
                q, ck, cv, vis[None, None], dt)
            a = bert_lib.attn_out_proj(lp, a, dt)
            h = _layernorm(h + a, lp["ln1"]).astype(dt)
            h = self._constrain(h, ("batch", "seq", "embed"))
            m = bert_lib.gelu_mlp(
                lp, h, dt,
                constrain=lambda m_: self._constrain(
                    m_, ("batch", "seq", "mlp")))
            h = _layernorm(h + m, lp["ln2"]).astype(dt)
            h = self._constrain(h, ("batch", "seq", "embed"))

        t = self.head_hidden(params, h)
        logits = jnp.einsum("bse,ve->bsv", t, params["tok_emb"].astype(dt)) \
            + params["mlm"]["out_b"]
        logits = self._constrain(logits, ("batch", "seq", "vocab"))
        return logits.astype(jnp.float32), new_cache

    def forward_paged(self, params, tokens, pools, block_tables, lengths,
                      valid=None, kernel: str = "xla", reduce=None):
        """Forward ``tokens`` (B, S_in) through the PAGED KV cache: row
        ``b`` occupies absolute positions [lengths[b], lengths[b]+S_in),
        reading/writing the per-layer block pools (serving/paged_cache)
        through its block table.  One implementation serves both serving
        phases — chunked prefill (S_in = chunk) and single-token decode
        (S_in = 1) — mirroring how ``forward_with_cache`` serves
        prefill+decode on the contiguous path.

        pools:        per-layer [{"k", "v"}] block pools, each
                      (num_blocks, H, block_size, D) — head-major,
                      ops/paged_attention's layout.  An int8 pool
                      (--serve-kv-dtype int8) additionally carries
                      {"k_scale", "v_scale"} (num_blocks, H, block_size)
                      fp32 row scales (serving/paged_cache.init_pools);
                      writes then quantize on store and attention
                      dequantizes inside the consume path
        block_tables: (B, NB) int32 pool block ids, position order;
                      entries beyond a row's allocation must be the null
                      block (0)
        lengths:      (B,) int32 cache entries already written per row
        valid:        optional (B, S_in) bool; False lanes (padded
                      prefill tail, inactive decode slots) scatter into
                      the null block and their outputs are garbage the
                      caller discards
        kernel:       "xla" (gather + dense masked softmax) or "pallas"
                      (fused Pallas kernel streaming pool blocks in
                      place) — a STATIC choice resolved host-side
                      (ops/paged_attention.resolve_kernel); per-row
                      ``lengths`` flow into the attention op either way,
                      so the kernel can bound its block walk by live
                      tokens instead of relying on the visibility mask
                      alone
        reduce:       manual-TP allreduce hook applied to each layer's
                      row-parallel partial outputs (attention out-proj
                      and MLP down-proj) BEFORE their bias — the
                      serving tensor-parallel path (serving/tp) calls
                      this under shard_map with heads/mlp (and the
                      pool's head axis) sharded over a ``tp`` mesh axis
                      and passes ``lax.psum`` here; None keeps the
                      single-shard math byte-for-byte

        Returns (fp32 logits (B, S_in, V), updated pools).  The math
        shares ``forward_with_cache``'s layers AND its attention
        (``ops/paged_attention.masked_softmax_attention`` on the XLA
        path; the Pallas kernel's online softmax is pinned against it by
        tests/test_paged_kernel.py) — so greedy decode through this path
        is token-identical to ``generate`` (tests/test_serving.py).
        """
        c = self.cfg
        dt = c.dtype
        B, S_in = tokens.shape
        lengths = jnp.asarray(lengths, jnp.int32)
        pos = lengths[:, None] + jnp.arange(S_in, dtype=jnp.int32)  # (B, S)
        if valid is None:
            valid = jnp.ones((B, S_in), bool)

        if c.pos_kind == "rope":
            h = params["tok_emb"][tokens]
        else:
            # same rows dynamic_slice would fetch, but gathered per-row
            # (each sequence sits at its own offset); clip covers padded
            # lanes whose nominal position runs past the table
            h = params["tok_emb"][tokens] \
                + params["pos_emb"][jnp.clip(pos, 0, c.max_positions - 1)]
        h = _layernorm(h, params["emb_ln"]).astype(dt)
        h = self._constrain(h, ("batch", "seq", "embed"))

        qkv_axes = ("batch", "heads", "seq", "head_dim")
        engagement.record("paged_attention", kernel)
        new_pools = []
        for lp, pl in zip(params["layers"], pools):
            q, k, v = bert_lib.qkv_proj(lp, h, dt, fused=c.fused_qkv)
            if c.pos_kind == "rope":
                # rotate at ABSOLUTE per-row positions; keys enter the
                # pool already rotated (as on the contiguous path)
                q = bert_lib.rope(q, pos)
                k = bert_lib.rope(k, pos)
            q = self._constrain(q, qkv_axes)
            if "k_scale" in pl and pl["k_scale"].ndim == 4:
                # int4 pool (--serve-kv-dtype int4, 4-d group scales):
                # group-quantize on store, consume through attend's
                # dequantizing paths WITH the fp-residual self lane —
                # the in-register k/v of this step's own tokens give
                # each query an exact fp score/value for its own
                # position (KIVI); the fp K/V still never touch the pool
                pk, ks = paged_ops.write_kv_quant_int4(
                    pl["k"], pl["k_scale"], k, block_tables, pos, valid)
                pv, vs = paged_ops.write_kv_quant_int4(
                    pl["v"], pl["v_scale"], v, block_tables, pos, valid)
                new_pools.append({"k": pk, "v": pv,
                                  "k_scale": ks, "v_scale": vs})
                a = paged_ops.attend(q, pk, pv, block_tables, lengths,
                                     dt, kernel=kernel,
                                     k_scale=ks, v_scale=vs,
                                     k_new=k, v_new=v)
            elif "k_scale" in pl:
                # int8 pool (--serve-kv-dtype int8): quantize on store —
                # codes and per-row scales scatter through the same
                # block/offset indexing — and consume through attend's
                # dequantizing paths; the fp K/V never touch the pool
                pk, ks = paged_ops.write_kv_quant(
                    pl["k"], pl["k_scale"], k, block_tables, pos, valid)
                pv, vs = paged_ops.write_kv_quant(
                    pl["v"], pl["v_scale"], v, block_tables, pos, valid)
                new_pools.append({"k": pk, "v": pv,
                                  "k_scale": ks, "v_scale": vs})
                a = paged_ops.attend(q, pk, pv, block_tables, lengths,
                                     dt, kernel=kernel,
                                     k_scale=ks, v_scale=vs)
            else:
                pk = paged_ops.write_kv(pl["k"], k, block_tables, pos,
                                        valid)
                pv = paged_ops.write_kv(pl["v"], v, block_tables, pos,
                                        valid)
                new_pools.append({"k": pk, "v": pv})
                a = paged_ops.attend(q, pk, pv, block_tables, lengths,
                                     dt, kernel=kernel)
            a = bert_lib.attn_out_proj(lp, a, dt, reduce=reduce)
            h = _layernorm(h + a, lp["ln1"]).astype(dt)
            h = self._constrain(h, ("batch", "seq", "embed"))
            m = bert_lib.gelu_mlp(
                lp, h, dt,
                constrain=lambda m_: self._constrain(
                    m_, ("batch", "seq", "mlp")),
                reduce=reduce)
            h = _layernorm(h + m, lp["ln2"]).astype(dt)
            h = self._constrain(h, ("batch", "seq", "embed"))

        t = self.head_hidden(params, h)
        logits = jnp.einsum("bse,ve->bsv", t, params["tok_emb"].astype(dt)) \
            + params["mlm"]["out_b"]
        logits = self._constrain(logits, ("batch", "seq", "vocab"))
        return logits.astype(jnp.float32), new_pools

    def generate(self, params, prompt, max_new_tokens: int, *,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, rng=None,
                 cache_len: int | None = None):
        """Autoregressive decode: greedy (``temperature == 0``) or
        temperature sampling, optionally filtered by ``top_k`` (keep the k
        highest-probability tokens) and/or ``top_p`` (nucleus: keep the
        smallest prefix of the probability-sorted vocab whose mass reaches
        p).  ``prompt``: (B, S0) int ids.  Returns
        (B, S0 + max_new_tokens) — the prompt with the continuation.

        Prefill computes the whole prompt in one batched forward (MXU-
        friendly); the per-token loop is a ``lax.scan`` over a static
        cache, so the whole call is one ``jit`` compilation.

        ``cache_len`` overrides the KV-cache capacity (default: exactly
        prompt + new tokens).  Every decode step attends over the full
        (masked) cache buffer, so per-step cost scales with the CAPACITY,
        not the occupancy — benchmark arms comparing different generation
        lengths must pin the same cache_len or the comparison is
        apples-to-oranges (bench.measure_decode does)."""
        if temperature > 0.0 and rng is None:
            raise ValueError("temperature sampling needs an rng")
        if (top_k > 0 or top_p < 1.0) and temperature <= 0.0:
            raise ValueError(
                "top_k/top_p filter the sampling distribution; they have "
                "no effect under greedy decoding (temperature 0) — pass "
                "temperature > 0")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if max_new_tokens < 0:
            raise ValueError(f"max_new_tokens must be >= 0, "
                             f"got {max_new_tokens}")
        if max_new_tokens == 0:
            return prompt
        B, S0 = prompt.shape
        total = S0 + max_new_tokens
        if cache_len is not None and cache_len < total:
            raise ValueError(f"cache_len {cache_len} < prompt + "
                             f"max_new_tokens ({total})")
        cache = self.init_cache(B, cache_len or total)
        logits, cache = self.forward_with_cache(params, prompt, cache, 0)
        first = self._sample(logits[:, -1], temperature, rng, 0,
                             top_k=top_k, top_p=top_p)

        def step(carry, i):
            cache, token, key = carry
            logits, cache = self.forward_with_cache(
                params, token[:, None], cache, S0 + i)
            nxt = self._sample(logits[:, 0], temperature, key, i + 1,
                               top_k=top_k, top_p=top_p)
            return (cache, nxt, key), token

        (_, last, _), toks = lax.scan(
            step, (cache, first, rng if rng is not None
                   else jax.random.key(0)),
            jnp.arange(max_new_tokens - 1))
        out = jnp.concatenate([toks.T, last[:, None]], axis=1) \
            if max_new_tokens > 1 else first[:, None]
        return jnp.concatenate([prompt, out], axis=1)

    def beam_search(self, params, prompt, max_new_tokens: int, *,
                    num_beams: int = 4, length_penalty: float = 0.0,
                    cache_len: int | None = None):
        """Fixed-length beam search over the KV-cache decode path.

        ``prompt``: (B, S0) int ids.  Returns ``(sequences, scores)``:
        sequences (B, num_beams, S0 + max_new_tokens) sorted by score
        descending, scores (B, num_beams) = sum of chosen-token log-probs
        divided by ``(new_tokens) ** length_penalty`` (0 = pure sum, the
        default; >0 favors longer... equal-length here, so it only
        rescales uniformly — exposed for API parity with samplers).

        TPU-shaped like ``generate``: beams fold into the batch dimension
        for the forward pass ((B*beam, 1) tokens per step), the per-step
        beam reindex is a ``take_along_axis`` gather over a (B, beam, ...)
        view of every cache leaf, and the whole loop is one ``lax.scan``
        — static shapes, one compilation.  No EOS semantics: the LM
        families train on streams without a terminator token, so beams
        always extend to the full length.

        ``cache_len`` pins the KV-cache capacity, exactly as in
        ``generate`` (decode cost scales with capacity, not occupancy —
        timing arms at different lengths must share one capacity)."""
        if max_new_tokens < 1:
            raise ValueError("beam_search needs max_new_tokens >= 1")
        if num_beams < 1:
            raise ValueError(f"num_beams must be >= 1, got {num_beams}")
        B, S0 = prompt.shape
        K = num_beams
        total = S0 + max_new_tokens
        if cache_len is not None and cache_len < total:
            raise ValueError(f"cache_len {cache_len} < prompt + "
                             f"max_new_tokens ({total})")
        V = self.cfg.vocab_size

        # prefill once at batch B, then tile the cache K-fold
        cache = self.init_cache(B, cache_len or total)
        logits, cache = self.forward_with_cache(params, prompt, cache, 0)
        logp0 = jax.nn.log_softmax(logits[:, -1], axis=-1)      # (B, V)
        scores, first = lax.top_k(logp0, K)                     # (B, K)
        cache = jax.tree.map(
            lambda c: jnp.repeat(c, K, axis=0), cache)          # (B*K, ...)

        def step(carry, i):
            cache, scores, token = carry                # token: (B, K)
            logits, cache = self.forward_with_cache(
                params, token.reshape(B * K, 1), cache, S0 + i)
            logp = jax.nn.log_softmax(
                logits[:, 0].reshape(B, K, V), axis=-1)
            cand = scores[..., None] + logp             # (B, K, V)
            scores, flat = lax.top_k(cand.reshape(B, K * V), K)
            parent = flat // V                          # which beam (B, K)
            nxt = (flat % V).astype(jnp.int32)
            # reindex every cache leaf to the surviving beams
            def reindex(c):
                v = c.reshape(B, K, *c.shape[1:])
                idx = parent.reshape(B, K, *([1] * (v.ndim - 2)))
                return jnp.take_along_axis(v, idx, axis=1) \
                    .reshape(B * K, *c.shape[1:])
            cache = jax.tree.map(reindex, cache)
            return (cache, scores, nxt), (parent, nxt)

        if max_new_tokens > 1:
            (_, scores, _), (parents, toks) = lax.scan(
                step, (cache, scores, first),
                jnp.arange(max_new_tokens - 1))
            # backtrack: follow parent pointers from the final beam slots.
            # At reverse position t the carry indexes step-(t+1) slots:
            # the token emitted there is toks[t][slot], and the chain
            # continues at parents[t][slot] (a step-t slot).
            def backtrack(beam_idx, xs):
                parent, tok = xs                         # (B, K) each
                cur_tok = jnp.take_along_axis(tok, beam_idx, 1)
                prev_idx = jnp.take_along_axis(parent, beam_idx, 1)
                return prev_idx, cur_tok

            beam_idx0 = jnp.tile(jnp.arange(K)[None], (B, 1))
            final_idx, rev = lax.scan(
                backtrack, beam_idx0, (parents, toks), reverse=True)
            # reverse=True stacks ys at their forward indices: rev[t] is
            # the token at generated position t+1 on each final beam
            mid = jnp.moveaxis(rev, 0, -1)               # (B, K, T-1)
            root = jnp.take_along_axis(first, final_idx, 1)  # (B, K)
            out = jnp.concatenate([root[..., None], mid], axis=-1)
        else:
            out = first[..., None]                       # (B, K, 1)
        seqs = jnp.concatenate(
            [jnp.broadcast_to(prompt[:, None], (B, K, S0)), out], axis=-1)
        if length_penalty:
            scores = scores / (float(max_new_tokens) ** length_penalty)
        return seqs, scores

    def _sample(self, logits, temperature, rng, i, *, top_k: int = 0,
                top_p: float = 1.0):
        """(B, V) fp32 logits -> (B,) token ids.

        The top-k / top-p filters run in DESCENDING-SORTED logit space and
        the categorical draw happens there too — the winning sorted slot
        is then mapped back through the sort's index vector.  Sampling in
        sorted space keeps every step gather-shaped (no (B, V) scatter,
        which XLA:TPU would serialize)."""
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        key = jax.random.fold_in(rng, i)
        logits = logits / temperature
        V = logits.shape[-1]
        if top_k <= 0 and top_p >= 1.0:
            return jax.random.categorical(
                key, logits, axis=-1).astype(jnp.int32)
        # full descending sort (lax.top_k of the whole vocab)
        srt, idx = lax.top_k(logits, V)
        neg = jnp.finfo(srt.dtype).min
        if top_k > 0:
            keep_k = jnp.arange(V) < min(top_k, V)          # (V,)
            srt = jnp.where(keep_k[None], srt, neg)
        if top_p < 1.0:
            probs = jax.nn.softmax(srt, axis=-1)
            # exclusive cumulative mass BEFORE each slot: slot survives if
            # the mass above it is still < p (the top slot always survives)
            cum = jnp.cumsum(probs, axis=-1) - probs
            srt = jnp.where(cum < top_p, srt, neg)
        choice = jax.random.categorical(key, srt, axis=-1)  # sorted slot
        return jnp.take_along_axis(
            idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)


@dataclasses.dataclass(frozen=True)
class PipelinedCausalLm(bert_pipeline.PipelinedBertMlm):
    """Causal LM under pipeline parallelism: the decoder-only stack
    pipelined over the mesh's ``pipe`` axis (GPipe or 1F1B,
    bert_pipeline.PipelinedBertMlm), every stage layer attending with the
    autoregressive mask (``causal=True`` flows into the stage body's
    ``dense_attention`` exactly as on the non-pipelined path).

    Loss: next-token CE over every position (final position
    unsupervised), expressed through the inherited pipelined loss by
    passing shifted targets as labels and the position weights as the
    mask — ``cfg.ce_positions`` must be "all" (guarded at construction:
    the pipelined loss consults the config directly, and masked-position
    packing is an MLM concept)."""
    causal: bool = True

    def __post_init__(self):
        super().__post_init__()
        if self.cfg.ce_positions != "all":
            raise ValueError(
                "PipelinedCausalLm computes next-token CE at every "
                "position; construct it with ce_positions='all' "
                f"(got {self.cfg.ce_positions!r}) rather than silently "
                "ignoring the packing config")

    def loss(self, params, model_state, batch, labels=None, *, rng=None,
             train: bool = False):
        """``batch``: dict with ``tokens`` (B, S) or the raw array;
        ``labels`` is ignored — targets are the inputs shifted left."""
        tokens = batch["tokens"] if isinstance(batch, dict) else batch
        targets, w = _shift_targets(tokens)
        return super().loss(params, model_state,
                            {"tokens": tokens, "mask": w}, targets,
                            rng=rng, train=train)
