"""Decoder-only causal LM (GPT-style) — the autoregressive family.

The reference has no transformer at all; BASELINE.json's directed scale-out
stops at BERT-base MLM.  This family demonstrates the framework's
generality beyond the directed set: the SAME encoder blocks, sharding
rules, attention kernels (causal flash / causal ring / causal Ulysses),
loss machinery (chunked CE), optimizer, loops, and checkpointing drive an
autoregressive LM — only the attention mask and the loss targets change.

Implementation: subclasses ``BertMlm`` with ``causal=True`` (the mask is
threaded through BertMlm._attention's dense/ring/Ulysses/flash paths — one
implementation, no copied override) and
- next-token loss: CE of position t against token t+1, over ALL positions
  (no mask packing — every position carries loss), using the same chunked
  online-logsumexp CE so (B, S, V) logits never materialize;
- untied LM head option is intentionally omitted: weight tying matches the
  MLM family and keeps vocab-parallel TP identical.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from mpi_tensorflow_tpu.models import bert as bert_lib


@dataclasses.dataclass(frozen=True)
class CausalLm(bert_lib.BertMlm):
    """GPT-style causal LM on the shared transformer stack."""
    causal: bool = True

    def loss(self, params, model_state, batch, labels=None, *, rng=None,
             train: bool = False):
        """Next-token CE.  ``batch``: dict with ``tokens`` (B, S) (or the
        raw (B, S) int array); ``labels`` is ignored — targets are the
        inputs shifted left, with the final position unsupervised."""
        tokens = batch["tokens"] if isinstance(batch, dict) else batch
        h, aux = self._encode_aux(params, tokens, train=train, rng=rng)
        t = self.head_hidden(params, h)
        targets = jnp.concatenate(
            [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1)
        ce = self._ce(params, t, targets)                       # (B, S)
        w = jnp.ones_like(ce).at[:, -1].set(0.0)                # drop last
        loss = jnp.sum(ce * w) / jnp.maximum(jnp.sum(w), 1.0)
        return loss + self._aux_weight() * aux, model_state

    def _packs_positions(self) -> bool:
        return False   # every position carries loss — no mask packing
