"""Mixture-of-Experts BERT — expert parallelism (EP).

Switch-Transformer-style top-1 routed MoE replacing the dense MLP in every
other encoder layer.  Routing is *capacity-based*: each expert owns a fixed
(X, C, E) token buffer with ``C = capacity_factor * tokens / num_experts``;
tokens are placed by scatter (position-in-expert via a cumulative count) and
read back by gather, so per-expert compute is ``C`` tokens — the routed MLP
costs ~``capacity_factor x`` one dense MLP **independent of the number of
experts** (vs. the dense one-hot dispatch einsum, which pays
``num_experts x``).  Tokens past capacity are dropped: their MLP output is
zero and the residual stream carries them through unchanged (Switch
Transformer, Fedus et al. 2021).

Expert weight stacks carry a leading ``expert`` logical axis sharded over
the ``expert`` mesh axis (parallel/sharding_rules.py); the scatter/gather
between token space (sharded over ``data``) and expert space (sharded over
``expert``) is lowered by XLA GSPMD to the expert all-to-all exchange.  A
load-balancing auxiliary loss keeps routing uniform.

No counterpart in the reference (SURVEY.md §2 checklist: EP absent); part of
the framework's full parallelism-strategy coverage (DP/TP/SP/EP + pipeline
in parallel/pipeline.py).  Encoder structure, dropout, MLM head, and loss
are inherited from models/bert.py — only the MLP block is overridden.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from mpi_tensorflow_tpu.models import bert as bert_lib
from mpi_tensorflow_tpu.models import bert_pipeline
from mpi_tensorflow_tpu.models.bert import _norm_init


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    num_experts: int = 4
    top_k: int = 1               # 1 = Switch routing; 2 = GShard-style
                                 # (second choice fills remaining capacity,
                                 # outputs combined with normalized gates)
    capacity_factor: float = 1.25  # expert buffer = cf * tokens / experts
    aux_loss_weight: float = 0.01
    every_other: bool = True     # MoE on odd layers, dense MLP on even

    def __post_init__(self):
        if self.top_k not in (1, 2):
            raise ValueError(f"top_k must be 1 (Switch) or 2 (GShard), "
                             f"got {self.top_k}")


@dataclasses.dataclass(frozen=True)
class MoeBertMlm(bert_lib.BertMlm):
    """BERT-MLM with routed expert MLPs.  Inherits the full encoder
    (attention, dropout, remat), MLM head, and loss; overrides init/axes and
    the per-layer MLP block."""
    moe: MoeConfig = MoeConfig()

    def _is_moe_layer(self, idx: int) -> bool:
        return (idx % 2 == 1) if self.moe.every_other else True

    def init(self, rng):
        params = super().init(rng)
        c, m = self.cfg, self.moe
        keys = iter(jax.random.split(jax.random.fold_in(rng, 77),
                                     4 * c.layers + 4))
        for i, lp in enumerate(params["layers"]):
            if not self._is_moe_layer(i):
                continue
            del lp["w1"], lp["b1"], lp["w2"], lp["b2"]
            lp["router"] = _norm_init(next(keys), (c.hidden, m.num_experts))
            lp["ew1"] = _norm_init(next(keys),
                                   (m.num_experts, c.hidden, c.mlp))
            lp["eb1"] = jnp.zeros((m.num_experts, c.mlp))
            lp["ew2"] = _norm_init(next(keys),
                                   (m.num_experts, c.mlp, c.hidden))
            lp["eb2"] = jnp.zeros((m.num_experts, c.hidden))
        return params

    def logical_axes(self):
        axes = super().logical_axes()
        for i, la in enumerate(axes["layers"]):
            if not self._is_moe_layer(i):
                continue
            del la["w1"], la["b1"], la["w2"], la["b2"]
            la["router"] = ("embed", "expert_classes")
            la["ew1"] = ("expert", "embed", "mlp")
            la["eb1"] = ("expert", "mlp")
            la["ew2"] = ("expert", "mlp", "embed")
            la["eb2"] = ("expert", "embed")
        return axes

    def capacity(self, num_tokens: int) -> int:
        """Per-expert buffer length: cf * tokens / experts, rounded up to a
        multiple of 8 (TPU sublane) and at least 8."""
        import math

        c = math.ceil(self.moe.capacity_factor * num_tokens
                      / self.moe.num_experts)
        return max(8, ((c + 7) // 8) * 8)

    def _moe_mlp(self, h, lp):
        """Capacity-routed top-k (k in {1, 2}) expert MLP.
        h: (B, S, E) -> (out, aux)."""
        dt = self.cfg.dtype
        X = self.moe.num_experts
        B, S, E = h.shape
        N = B * S
        C = self.capacity(N)
        hf = h.reshape(N, E)

        # --- route: top-k experts + positions in their buffers ---
        gate_logits = jnp.einsum("ne,ec->nc", hf, lp["router"].astype(dt))
        gates = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
        top1 = jnp.argmax(gates, axis=-1)                       # (N,)
        gate1 = jnp.take_along_axis(gates, top1[:, None],
                                    axis=-1)[:, 0]              # (N,)
        onehot1 = jax.nn.one_hot(top1, X, dtype=jnp.int32)      # (N, X)
        # k-th token routed to expert x gets buffer slot k (first-come)
        pos1 = jnp.sum(jnp.cumsum(onehot1, axis=0) * onehot1, axis=-1) - 1
        keep1 = pos1 < C                                        # drop overflow
        # dropped tokens target the sacrificial overflow row X*C
        slot1 = jnp.where(keep1, top1 * C + pos1, X * C)        # (N,)

        routes = [(slot1, keep1, gate1)]
        if self.moe.top_k == 2:
            # GShard-style second choice: fills whatever capacity the
            # first-choice assignment left in each expert's buffer
            g2 = gates - gates * jax.nn.one_hot(top1, X)        # mask choice 1
            top2 = jnp.argmax(g2, axis=-1)
            gate2 = jnp.take_along_axis(g2, top2[:, None], axis=-1)[:, 0]
            onehot2 = jax.nn.one_hot(top2, X, dtype=jnp.int32)
            occupancy1 = jnp.minimum(jnp.sum(onehot1, axis=0), C)   # (X,)
            pos2 = jnp.sum(jnp.cumsum(onehot2, axis=0) * onehot2,
                           axis=-1) - 1 + occupancy1[top2]
            keep2 = pos2 < C
            slot2 = jnp.where(keep2, top2 * C + pos2, X * C)
            # normalize the two gates over what was actually routed
            denom = jnp.maximum(gate1 + gate2, 1e-9)
            routes = [(slot1, keep1, gate1 / denom),
                      (slot2, keep2, gate2 / denom)]

        # --- dispatch: scatter tokens into the (X, C, E) expert buffers ---
        buf = jnp.zeros((X * C + 1, E), dt)
        for slot, _, _ in routes:
            buf = buf.at[slot].set(hf.astype(dt))
        xin = buf[:X * C].reshape(X, C, E)
        xin = self._constrain(xin, ("expert", "capacity", "embed"))

        # --- expert compute: batched matmuls over the expert axis ---
        a = jax.nn.gelu(jnp.einsum("xce,xef->xcf", xin, lp["ew1"].astype(dt))
                        + lp["eb1"].astype(dt)[:, None, :])
        a = self._constrain(a, ("expert", "capacity", "mlp"))
        xout = jnp.einsum("xcf,xfe->xce", a, lp["ew2"].astype(dt)) \
            + lp["eb2"].astype(dt)[:, None, :]
        xout = self._constrain(xout, ("expert", "capacity", "embed"))

        # --- combine: gather each token's expert output(s) (zero if
        # dropped — the residual connection carries it unchanged) ---
        flat = jnp.concatenate([xout.reshape(X * C, E),
                                jnp.zeros((1, E), dt)], axis=0)
        out = jnp.zeros((N, E), dt)
        for slot, keep, w in routes:
            out = out + flat[slot] * (w * keep)[:, None].astype(dt)
        out = out.reshape(B, S, E)

        # Switch load-balance loss: X * sum_x frac_tokens_x * mean_gate_x
        # (first-choice fractions, as in both Switch and GShard)
        frac = jnp.mean(onehot1.astype(jnp.float32), axis=0)
        mean_gate = jnp.mean(gates, axis=0)
        aux = X * jnp.sum(frac * mean_gate)
        return out, aux

    def _mlp_block(self, lp, h, idx: int):
        if not self._is_moe_layer(idx):
            return super()._mlp_block(lp, h, idx)
        return self._moe_mlp(h, lp)

    def _aux_weight(self) -> float:
        return self.moe.aux_loss_weight

    # kept for callers that want logits + aux in one pass
    def apply_with_aux(self, params, tokens, *, train: bool = False,
                       rng=None):
        dt = self.cfg.dtype
        h, aux = self._encode_aux(params, tokens, train=train, rng=rng)
        t = self.head_hidden(params, h)
        logits = jnp.einsum("bse,ve->bsv", t, params["tok_emb"].astype(dt)) \
            + params["mlm"]["out_b"]
        return logits.astype(jnp.float32), aux


@dataclasses.dataclass(frozen=True)
class PipelinedMoeBertMlm(bert_pipeline.PipelinedBertMlm, MoeBertMlm):
    """MoE under pipeline parallelism: encoder stages pipelined over the
    mesh's ``pipe`` axis (GPipe or 1F1B, bert_pipeline.PipelinedBertMlm),
    each stage layer routing its MLP through the capacity-based expert
    dispatch (MoeBertMlm._moe_mlp, run mesh-free inside the pipeline
    shard_map).

    Composition contract this round (guarded at construction):
    - layers are UNIFORMLY MoE (``every_other=False``) — stage stacking
      (bert_pipeline.stack_layers) needs homogeneous layer pytrees;
    - experts live replicated within each stage (no ``expert`` mesh axis
      under PP: the routed scatter/gather is token-local inside the pipe
      shard_map, the EP all-to-all belongs to the non-pipelined path);
    - no Megatron TP inside MoE stages (``model`` axis): the expert
      weights' ``mlp`` logical axis would shard over it and the dispatch
      has no row-parallel reduction yet;
    - ``aux_loss_weight == 0`` — the load-balance aux term is not
      threaded through the pipeline schedule; capacity routing still
      bounds per-expert load (overflow drops), it is the balancing
      *gradient* that is absent.
    """
    moe: MoeConfig = MoeConfig(every_other=False, aux_loss_weight=0.0)

    def __post_init__(self):
        super().__post_init__()          # pos_kind guard
        if self.moe.every_other:
            raise ValueError(
                "pipelined MoE needs uniform expert layers "
                "(MoeConfig(every_other=False)): stage stacking requires "
                "homogeneous layer pytrees")
        if self.moe.aux_loss_weight != 0.0:
            raise ValueError(
                "pipelined MoE does not thread the load-balance aux loss "
                "through the pipeline schedule; set "
                "MoeConfig(aux_loss_weight=0.0) explicitly rather than "
                "silently dropping the term")
        if self.mesh is not None:
            # seq: the routed dispatch computes capacity/positions over
            # its LOCAL tokens — under sequence sharding that silently
            # becomes per-shard routing, a different algorithm
            for axis in ("expert", "model", "seq"):
                if self.mesh.shape.get(axis, 1) > 1:
                    raise ValueError(
                        f"pipelined MoE supports pipe x data meshes only "
                        f"this round (got {axis}="
                        f"{self.mesh.shape[axis]}); drop the {axis!r} "
                        f"axis rather than silently ignoring it")

    def _plain_mlp(self, lp, h, reduce):
        # inside the pipe shard_map GSPMD annotations are illegal — run
        # the routed dispatch on a mesh-free view (same trick as the
        # 1F1B head path); the aux term is guarded to weight 0 above
        out, _aux = dataclasses.replace(self, mesh=None)._moe_mlp(h, lp)
        return out
