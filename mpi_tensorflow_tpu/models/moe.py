"""Mixture-of-Experts BERT — expert parallelism (EP).

Switch-Transformer-style top-1 routed MoE replacing the dense MLP in every
other encoder layer.  Expert weight stacks carry a leading ``expert`` logical
axis sharded over the ``expert`` mesh axis (parallel/sharding_rules.py);
dispatch/combine are einsums over the expert dimension, so XLA GSPMD lowers
them to the expert all-to-all exchange.  A load-balancing auxiliary loss
(Switch Transformer, Fedus et al. 2021) keeps routing uniform.

No counterpart in the reference (SURVEY.md §2 checklist: EP absent); part of
the framework's full parallelism-strategy coverage (DP/TP/SP/EP + pipeline
in parallel/pipeline.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from mpi_tensorflow_tpu.models import bert as bert_lib
from mpi_tensorflow_tpu.models.bert import _layernorm, _norm_init


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    bert: bert_lib.BertConfig = bert_lib.BERT_TINY
    num_experts: int = 4
    aux_loss_weight: float = 0.01
    every_other: bool = True     # MoE on odd layers, dense MLP on even


@dataclasses.dataclass(frozen=True)
class MoeBertMlm(bert_lib.BertMlm):
    """BERT-MLM with routed expert MLPs.  Inherits attention/embedding/loss
    machinery; overrides init/axes/forward for the MoE blocks."""
    moe: MoeConfig = MoeConfig()

    def _is_moe_layer(self, idx: int) -> bool:
        return (idx % 2 == 1) if self.moe.every_other else True

    def init(self, rng):
        params = super().init(rng)
        c, m = self.cfg, self.moe
        keys = iter(jax.random.split(jax.random.fold_in(rng, 77),
                                     4 * c.layers + 4))
        for i, lp in enumerate(params["layers"]):
            if not self._is_moe_layer(i):
                continue
            del lp["w1"], lp["b1"], lp["w2"], lp["b2"]
            lp["router"] = _norm_init(next(keys), (c.hidden, m.num_experts))
            lp["ew1"] = _norm_init(next(keys),
                                   (m.num_experts, c.hidden, c.mlp))
            lp["eb1"] = jnp.zeros((m.num_experts, c.mlp))
            lp["ew2"] = _norm_init(next(keys),
                                   (m.num_experts, c.mlp, c.hidden))
            lp["eb2"] = jnp.zeros((m.num_experts, c.hidden))
        return params

    def logical_axes(self):
        axes = super().logical_axes()
        for i, la in enumerate(axes["layers"]):
            if not self._is_moe_layer(i):
                continue
            del la["w1"], la["b1"], la["w2"], la["b2"]
            la["router"] = ("embed", "expert_classes")
            la["ew1"] = ("expert", "embed", "mlp")
            la["eb1"] = ("expert", "mlp")
            la["ew2"] = ("expert", "mlp", "embed")
            la["eb2"] = ("expert", "embed")
        return axes

    def _moe_mlp(self, h, lp, dt):
        """Top-1 routed expert MLP.  h: (B, S, E).  Returns (out, aux_loss)."""
        gate_logits = jnp.einsum("bse,ec->bsc", h, lp["router"].astype(dt))
        gates = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
        top1 = jnp.argmax(gates, axis=-1)                      # (B, S)
        ne = self.moe.num_experts
        dispatch = jax.nn.one_hot(top1, ne, dtype=dt)          # (B, S, X)
        top_gate = jnp.sum(gates * dispatch.astype(jnp.float32),
                           axis=-1)                            # (B, S)
        # dispatch tokens to experts (-> all-to-all under an expert mesh axis)
        xin = jnp.einsum("bsx,bse->xbse", dispatch, h)
        a = jax.nn.gelu(jnp.einsum("xbse,xef->xbsf", xin,
                                   lp["ew1"].astype(dt))
                        + lp["eb1"].astype(dt)[:, None, None, :])
        xout = jnp.einsum("xbsf,xfe->xbse", a, lp["ew2"].astype(dt)) \
            + lp["eb2"].astype(dt)[:, None, None, :]
        out = jnp.einsum("xbse,bsx->bse", xout, dispatch)
        out = out * top_gate[..., None].astype(dt)
        # Switch load-balance loss: ne * sum_x frac_tokens_x * mean_gate_x
        frac = jnp.mean(dispatch.astype(jnp.float32), axis=(0, 1))
        mean_gate = jnp.mean(gates, axis=(0, 1))
        aux = ne * jnp.sum(frac * mean_gate)
        return out, aux

    def apply(self, params, batch, *, train: bool = False, rng=None,
              return_aux: bool = False):
        c = self.cfg
        dt = c.dtype
        tokens = batch
        B, S = tokens.shape
        aux_total = 0.0
        h = params["tok_emb"][tokens] + params["pos_emb"][None, :S]
        h = _layernorm(h, params["emb_ln"]).astype(dt)
        h = self._constrain(h, ("batch", "seq", "embed"))

        for i, lp in enumerate(params["layers"]):
            q = jnp.einsum("bse,ehd->bhsd", h, lp["wq"].astype(dt)) \
                + lp["bq"].astype(dt)[None, :, None, :]
            k = jnp.einsum("bse,ehd->bhsd", h, lp["wk"].astype(dt)) \
                + lp["bk"].astype(dt)[None, :, None, :]
            v = jnp.einsum("bse,ehd->bhsd", h, lp["wv"].astype(dt)) \
                + lp["bv"].astype(dt)[None, :, None, :]
            a = self._attention(q, k, v)
            a = jnp.einsum("bhsd,hde->bse", a, lp["wo"].astype(dt)) \
                + lp["bo"].astype(dt)
            h = _layernorm(h + a, lp["ln1"]).astype(dt)
            h = self._constrain(h, ("batch", "seq", "embed"))
            if self._is_moe_layer(i):
                m, aux = self._moe_mlp(h, lp, dt)
                aux_total = aux_total + aux
            else:
                m = jax.nn.gelu(
                    jnp.einsum("bse,ef->bsf", h, lp["w1"].astype(dt))
                    + lp["b1"].astype(dt))
                m = jnp.einsum("bsf,fe->bse", m, lp["w2"].astype(dt)) \
                    + lp["b2"].astype(dt)
            h = _layernorm(h + m, lp["ln2"]).astype(dt)
            h = self._constrain(h, ("batch", "seq", "embed"))

        t = jax.nn.gelu(h @ params["mlm"]["w"].astype(dt)
                        + params["mlm"]["b"].astype(dt))
        t = _layernorm(t, params["mlm"]["ln"]).astype(dt)
        logits = jnp.einsum("bse,ve->bsv", t, params["tok_emb"].astype(dt)) \
            + params["mlm"]["out_b"]
        logits = logits.astype(jnp.float32)
        if return_aux:
            return logits, aux_total
        return logits

    def loss(self, params, model_state, batch, labels, *, rng=None,
             train: bool = False):
        logits, aux = self.apply(params, batch["tokens"], train=train,
                                 rng=rng, return_aux=True)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        ce = logz - gold
        mask = batch["mask"].astype(jnp.float32)
        loss = jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return loss + self.moe.aux_loss_weight * aux, model_state
