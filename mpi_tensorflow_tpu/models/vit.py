"""Vision Transformer — the transformer stack applied to the image task.

The reference's only model is a 2-conv CNN (mpipy.py:38-53); the scale-out
families added ResNets (conv) and BERT/GPT/MoE (token transformers).  ViT
closes the loop between the two stacks: the image families' data pipeline,
train step, and loop drive the SAME encoder layers as BERT
(`bert._run_layers` / `bert.init_encoder_layer` — one definition, so a
layer change can never diverge the families), with patch embedding in
place of token embedding and a CLS-token classification head in place of
the MLM head.

TPU shape notes: patch extraction is a reshape/transpose + one (N, P²C)
x (P²C, E) matmul — no gathers; the sequence length is static
(N = (H/P)(W/P) + 1 CLS), so the whole step jits once.  The encoder
inherits every BertConfig lever (remat, fused_qkv, flash_min_seq — the
latter moot at ViT's short N, where XLA dense attention is the measured
winner anyway).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from mpi_tensorflow_tpu.models import bert as bert_lib


@dataclasses.dataclass(frozen=True)
class VitConfig:
    image_size: int = 32
    patch: int = 4
    channels: int = 3
    num_classes: int = 10
    hidden: int = 192         # ViT-Tiny geometry for CIFAR by default
    layers: int = 12
    heads: int = 3
    mlp: int = 768
    dropout: float = 0.1
    dtype: Any = jnp.float32
    remat: bool = False

    @property
    def num_patches(self) -> int:
        if self.image_size % self.patch:
            raise ValueError(f"image_size {self.image_size} not divisible "
                             f"by patch {self.patch}")
        return (self.image_size // self.patch) ** 2


VIT_TINY_CIFAR = VitConfig()
VIT_S16_IMAGENET = VitConfig(image_size=224, patch=16, num_classes=1000,
                             hidden=384, layers=12, heads=6, mlp=1536)


@dataclasses.dataclass(frozen=True)
class VisionTransformer:
    cfg: VitConfig = VIT_TINY_CIFAR

    @property
    def num_classes(self) -> int:
        return self.cfg.num_classes

    def _bert_cfg(self) -> bert_lib.BertConfig:
        c = self.cfg
        return dataclasses.replace(
            bert_lib.BERT_TINY, hidden=c.hidden, layers=c.layers,
            heads=c.heads, mlp=c.mlp, dropout=c.dropout, dtype=c.dtype,
            remat=c.remat, max_positions=c.num_patches + 1)

    def _encoder(self) -> bert_lib.BertMlm:
        """The shared encoder stack, configured for this ViT (no mesh:
        the image loop is the DP path; use_flash is irrelevant at ViT's
        short sequence — flash_min_seq keeps XLA attention)."""
        return bert_lib.BertMlm(self._bert_cfg())

    # ---------------- init ----------------

    def init(self, rng):
        c = self.cfg
        bcfg = self._bert_cfg()
        k = iter(jax.random.split(rng, 8 + 6 * c.layers))
        pdim = c.patch * c.patch * c.channels
        params = {
            "patch_w": bert_lib._norm_init(next(k), (pdim, c.hidden)),
            "patch_b": jnp.zeros((c.hidden,)),
            "cls": bert_lib._norm_init(next(k), (1, 1, c.hidden)),
            "pos_emb": bert_lib._norm_init(
                next(k), (c.num_patches + 1, c.hidden)),
            "emb_ln": {"scale": jnp.ones((c.hidden,)),
                       "bias": jnp.zeros((c.hidden,))},
            "layers": [bert_lib.init_encoder_layer(k, bcfg)
                       for _ in range(c.layers)],
            "head_ln": {"scale": jnp.ones((c.hidden,)),
                        "bias": jnp.zeros((c.hidden,))},
            "head_w": bert_lib._norm_init(next(k),
                                          (c.hidden, c.num_classes)),
            "head_b": jnp.zeros((c.num_classes,)),
        }
        return params

    # ---------------- forward ----------------

    def _patchify(self, images):
        """(B, H, W, C) -> (B, N, P*P*C) by pure reshape/transpose."""
        c = self.cfg
        B, H, W, C = images.shape
        g = H // c.patch
        x = images.reshape(B, g, c.patch, g, c.patch, C)
        return x.transpose(0, 1, 3, 2, 4, 5).reshape(
            B, g * g, c.patch * c.patch * C)

    def apply(self, params, images, *, train: bool = False, rng=None):
        """(B, H, W, C) float images -> (B, num_classes) fp32 logits."""
        c = self.cfg
        dt = c.dtype
        x = self._patchify(images.astype(dt))
        h = x @ params["patch_w"].astype(dt) + params["patch_b"].astype(dt)
        B = h.shape[0]
        cls = jnp.broadcast_to(params["cls"].astype(dt), (B, 1, c.hidden))
        h = jnp.concatenate([cls, h], axis=1) + \
            params["pos_emb"][None].astype(dt)
        h = bert_lib._layernorm(h, params["emb_ln"])
        if train and c.dropout > 0.0:
            if rng is None:
                raise ValueError("dropout needs an rng in train mode")
            h = bert_lib.dropout_mask(h, c.dropout,
                                      jax.random.fold_in(rng, 1))
        h = h.astype(dt)
        # the SHARED encoder layer stack; dropout streams continue from
        # the embedding site exactly like the token path
        h, _ = self._encoder()._run_layers(
            {"layers": params["layers"]}, h, train=train, rng=rng,
            drop_start=1)
        cls_out = bert_lib._layernorm(h[:, 0].astype(jnp.float32),
                                      params["head_ln"])
        logits = cls_out @ params["head_w"] + params["head_b"]
        return logits.astype(jnp.float32)

    def l2_params(self, params) -> list:
        return []   # transformer families use decoupled weight decay
