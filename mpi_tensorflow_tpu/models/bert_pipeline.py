"""Pipeline-parallel BERT-MLM: the encoder stack as GPipe stages.

``PipelinedBertMlm`` is the real-model counterpart of the generic schedule
in parallel/pipeline.py (which round 1 only exercised with toy stage fns):
the L encoder layers are split into ``pipe`` stages of L/P layers whose
parameters carry a leading ``stage`` logical axis sharded over the ``pipe``
mesh axis.  Embeddings and the MLM head stay replicated outside the
pipeline (they are ~1% of encoder FLOPs at BERT-base geometry).  The full
*training* step — loss, backward, optimizer — runs through the schedule:
``train/gspmd.make_gspmd_train_step`` works unchanged because this is just
a ``BertMlm`` whose encoder calls ``parallel.pipeline.pipeline`` inside a
``shard_map``; reverse-mode autodiff of the scanned schedule yields the
backward pipeline (reverse ``ppermute`` hops) automatically.

Composition: ``pipe x data`` (each data shard runs its own microbatch
stream through the stages).  The loss-side machinery (masked-position
packing, chunked CE) is inherited.  Dropout trains unmodified: the
schedule hands each stage the index of the microbatch it is processing
(parallel/pipeline.py ``with_mb_index``), and dropout keys are folded on
(data shard, microbatch, global layer, site) so every microbatch draws
independent masks — including under remat, which replays the same fold
inputs and hence identical masks in the recomputation.

Memory schedule: GPipe stores ~M microbatch boundary activations for the
backward pipeline.  The 1F1B peak of O(P) in-flight activations is obtained
compositionally: set ``num_microbatches = P`` and use the train step's
``grad_accum`` to scan over microbatch *groups* — each group pipelines P
microbatches (peak O(P) activations, exactly 1F1B's), and groups accumulate
gradients sequentially (pinned by
tests/test_moe_pipeline.py::test_pipeline_with_grad_accum).  The price vs a
hand-interleaved 1F1B is bubble fraction ((P-1)/(2P-1) per group instead of
(P-1)/(M+P-1) overall); ``cfg.remat`` additionally recomputes within-stage
activations in the backward.  TP/SP inside a stage and a hand-interleaved
1F1B schedule remain future work.

No counterpart in the reference (SURVEY.md §2 checklist: PP absent).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mpi_tensorflow_tpu.models import bert as bert_lib
from mpi_tensorflow_tpu.models.bert import _layernorm
from mpi_tensorflow_tpu.parallel import pipeline as pipeline_lib
from mpi_tensorflow_tpu.parallel import ring


def stack_layers(layers: list, num_stages: int):
    """List of L per-layer param dicts -> stacked pytree of
    (num_stages, L/num_stages, ...) arrays (stage-major, layer order
    preserved)."""
    L = len(layers)
    if L % num_stages:
        raise ValueError(f"{L} layers not divisible by {num_stages} stages")
    return jax.tree.map(
        lambda *xs: jnp.stack(xs).reshape(
            (num_stages, L // num_stages) + xs[0].shape), *layers)


@dataclasses.dataclass(frozen=True)
class PipelinedBertMlm(bert_lib.BertMlm):
    """BERT-MLM with the encoder pipelined over the mesh's ``pipe`` axis."""
    num_microbatches: int = 4

    @property
    def _num_stages(self) -> int:
        return self.mesh.shape.get("pipe", 1) if self.mesh is not None else 1

    def init(self, rng):
        params = super().init(rng)
        params["layers"] = stack_layers(params["layers"], self._num_stages)
        return params

    def logical_axes(self):
        axes = super().logical_axes()
        layer0 = axes["layers"][0]
        axes["layers"] = {k: ("stage", "layer") + v
                          for k, v in layer0.items()
                          if not isinstance(v, dict)}
        for k, v in layer0.items():
            if isinstance(v, dict):   # layernorm sub-dicts
                axes["layers"][k] = {kk: ("stage", "layer") + vv
                                     for kk, vv in v.items()}
        return axes

    def _plain_layer(self, lp, h, drop=None):
        """One encoder layer with no mesh constraints — runs inside the
        pipe ``shard_map`` where GSPMD annotations are unavailable.  Same
        math as BertMlm's layer.  ``drop``: ``None`` (eval / dropout off) or
        a ``site -> key`` function yielding this layer's per-site dropout
        keys (already folded on microbatch and global layer index)."""
        dt = self.cfg.dtype

        def dropout(x, site):
            if drop is None:
                return x
            return bert_lib.dropout_mask(x, self.cfg.dropout, drop(site))

        q, k, v = bert_lib.qkv_proj(lp, h, dt)
        a = ring.dense_attention(q, k, v)
        a = bert_lib.attn_out_proj(lp, a, dt)
        h = _layernorm(h + dropout(a, 0), lp["ln1"]).astype(dt)
        m = bert_lib.gelu_mlp(lp, h, dt)
        return _layernorm(h + dropout(m, 1), lp["ln2"]).astype(dt)

    def _dropping(self, train: bool, rng) -> bool:
        if not (train and self.cfg.dropout > 0.0):
            return False
        if rng is None:
            raise ValueError("dropout needs an rng in train mode")
        return True

    def _stage(self, stage_params, x, rng=None, mb_idx=None,
               stage_idx=None):
        """Run this stage's L/P layers sequentially (scan over the layer
        dim of the stacked params).  When ``rng`` is set, dropout keys are
        folded on (microbatch, global layer, site) so every microbatch at
        every layer draws an independent mask — and a remat recomputation
        replays the identical mask (keys are pure functions of the fold
        inputs)."""
        Lp = jax.tree.leaves(stage_params)[0].shape[0]

        def body(h, inp):
            lp, li = inp
            drop = None
            if rng is not None:
                gl = stage_idx * Lp + li      # global layer index
                kb = jax.random.fold_in(jax.random.fold_in(rng, mb_idx), gl)
                drop = lambda site: jax.random.fold_in(kb, site)  # noqa: E731
            return self._plain_layer(lp, h, drop=drop), None

        if self.cfg.remat:
            # recompute stage activations in the backward pipeline: the
            # scanned schedule then stores only stage-boundary activations
            # per tick instead of every layer's internals (the GPipe
            # activation-memory story)
            body = jax.checkpoint(body)
        h, _ = lax.scan(body, x, (stage_params, jnp.arange(Lp)))
        return h

    def _encode_aux(self, params, tokens, *, train: bool = False, rng=None):
        c = self.cfg
        dropping = self._dropping(train, rng)
        dt = c.dtype
        B, S = tokens.shape
        h = params["tok_emb"][tokens] + params["pos_emb"][None, :S]
        h = _layernorm(h, params["emb_ln"])
        if dropping:
            # embedding dropout (BertMlm's first site), on a stream index
            # no in-stage fold chain can collide with
            h = bert_lib.dropout_mask(h, c.dropout,
                                      jax.random.fold_in(rng, 2 ** 30))
        h = h.astype(dt)
        h = self._constrain(h, ("batch", "seq", "embed"))

        n_stages = self._num_stages
        if n_stages == 1:   # no pipe axis: plain sequential stack
            flat = jax.tree.map(
                lambda x: x.reshape((-1,) + x.shape[2:]), params["layers"])
            h = self._stage(flat, h, rng=rng if dropping else None,
                            mb_idx=jnp.int32(0), stage_idx=jnp.int32(0))
            return h, jnp.zeros((), jnp.float32)

        M = self.num_microbatches
        dp = self.mesh.shape.get("data", 1)
        if (B // dp) % M:
            raise ValueError(
                f"per-data-shard batch {B // dp} not divisible by "
                f"{M} microbatches")
        h_spec = P("data" if dp > 1 else None)

        def inner(stacked_local, hl, key):
            stage_params = jax.tree.map(lambda x: x[0], stacked_local)
            mb = hl.reshape((M, hl.shape[0] // M) + hl.shape[1:])
            if dropping:
                # decorrelate the data shards' masks too (each data shard
                # pipelines a different slice of the global batch)
                key = jax.random.fold_in(
                    key, lax.axis_index("data") if dp > 1 else 0)
                sidx = lax.axis_index("pipe")
                out = pipeline_lib.pipeline(
                    lambda p, x, mi: self._stage(p, x, rng=key, mb_idx=mi,
                                                 stage_idx=sidx),
                    stage_params, mb, "pipe", with_mb_index=True)
            else:
                out = pipeline_lib.pipeline(
                    lambda p, x: self._stage(p, x), stage_params, mb, "pipe")
            return out.reshape(hl.shape)

        key = rng if dropping else jax.random.key(0)
        h = jax.shard_map(
            inner, mesh=self.mesh,
            in_specs=(P("pipe"), h_spec, P()), out_specs=h_spec,
            check_vma=False)(params["layers"], h, key)
        h = self._constrain(h, ("batch", "seq", "embed"))
        return h, jnp.zeros((), jnp.float32)
