"""Pipeline-parallel BERT-MLM: the encoder stack as GPipe stages.

``PipelinedBertMlm`` is the real-model counterpart of the generic schedule
in parallel/pipeline.py (which round 1 only exercised with toy stage fns):
the L encoder layers are split into ``pipe`` stages of L/P layers whose
parameters carry a leading ``stage`` logical axis sharded over the ``pipe``
mesh axis.  Embeddings and the MLM head stay replicated outside the
pipeline (they are ~1% of encoder FLOPs at BERT-base geometry).  The full
*training* step — loss, backward, optimizer — runs through the schedule:
``train/gspmd.make_gspmd_train_step`` works unchanged because this is just
a ``BertMlm`` whose encoder calls ``parallel.pipeline.pipeline`` inside a
``shard_map``; reverse-mode autodiff of the scanned schedule yields the
backward pipeline (reverse ``ppermute`` hops) automatically.

Composition: ``pipe x data`` (each data shard runs its own microbatch
stream through the stages).  The loss-side machinery (masked-position
packing, chunked CE) is inherited.

Memory schedule: GPipe stores ~M microbatch boundary activations for the
backward pipeline.  The 1F1B peak of O(P) in-flight activations is obtained
compositionally: set ``num_microbatches = P`` and use the train step's
``grad_accum`` to scan over microbatch *groups* — each group pipelines P
microbatches (peak O(P) activations, exactly 1F1B's), and groups accumulate
gradients sequentially (pinned by
tests/test_moe_pipeline.py::test_pipeline_with_grad_accum).  The price vs a
hand-interleaved 1F1B is bubble fraction ((P-1)/(2P-1) per group instead of
(P-1)/(M+P-1) overall); ``cfg.remat`` additionally recomputes within-stage
activations in the backward.  TP/SP inside a stage and a hand-interleaved
1F1B schedule remain future work.

No counterpart in the reference (SURVEY.md §2 checklist: PP absent).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mpi_tensorflow_tpu.models import bert as bert_lib
from mpi_tensorflow_tpu.models.bert import _layernorm
from mpi_tensorflow_tpu.parallel import pipeline as pipeline_lib
from mpi_tensorflow_tpu.parallel import ring


def stack_layers(layers: list, num_stages: int):
    """List of L per-layer param dicts -> stacked pytree of
    (num_stages, L/num_stages, ...) arrays (stage-major, layer order
    preserved)."""
    L = len(layers)
    if L % num_stages:
        raise ValueError(f"{L} layers not divisible by {num_stages} stages")
    return jax.tree.map(
        lambda *xs: jnp.stack(xs).reshape(
            (num_stages, L // num_stages) + xs[0].shape), *layers)


@dataclasses.dataclass(frozen=True)
class PipelinedBertMlm(bert_lib.BertMlm):
    """BERT-MLM with the encoder pipelined over the mesh's ``pipe`` axis."""
    num_microbatches: int = 4

    @property
    def _num_stages(self) -> int:
        return self.mesh.shape.get("pipe", 1) if self.mesh is not None else 1

    def init(self, rng):
        params = super().init(rng)
        params["layers"] = stack_layers(params["layers"], self._num_stages)
        return params

    def logical_axes(self):
        axes = super().logical_axes()
        layer0 = axes["layers"][0]
        axes["layers"] = {k: ("stage", "layer") + v
                          for k, v in layer0.items()
                          if not isinstance(v, dict)}
        for k, v in layer0.items():
            if isinstance(v, dict):   # layernorm sub-dicts
                axes["layers"][k] = {kk: ("stage", "layer") + vv
                                     for kk, vv in v.items()}
        return axes

    def _plain_layer(self, lp, h):
        """One encoder layer with no mesh constraints — runs inside the
        pipe ``shard_map`` where GSPMD annotations are unavailable.  Same
        math as BertMlm's layer (dropout-free; see ``_encode_aux``)."""
        dt = self.cfg.dtype
        q = jnp.einsum("bse,ehd->bhsd", h, lp["wq"].astype(dt)) \
            + lp["bq"].astype(dt)[None, :, None, :]
        k = jnp.einsum("bse,ehd->bhsd", h, lp["wk"].astype(dt)) \
            + lp["bk"].astype(dt)[None, :, None, :]
        v = jnp.einsum("bse,ehd->bhsd", h, lp["wv"].astype(dt)) \
            + lp["bv"].astype(dt)[None, :, None, :]
        a = ring.dense_attention(q, k, v)
        a = jnp.einsum("bhsd,hde->bse", a, lp["wo"].astype(dt)) \
            + lp["bo"].astype(dt)
        h = _layernorm(h + a, lp["ln1"]).astype(dt)
        m = jax.nn.gelu(jnp.einsum("bse,ef->bsf", h, lp["w1"].astype(dt))
                        + lp["b1"].astype(dt))
        m = jnp.einsum("bsf,fe->bse", m, lp["w2"].astype(dt)) \
            + lp["b2"].astype(dt)
        return _layernorm(h + m, lp["ln2"]).astype(dt)

    def _stage(self, stage_params, x):
        """Run this stage's L/P layers sequentially (scan over the layer
        dim of the stacked params)."""
        def body(h, lp):
            return self._plain_layer(lp, h), None

        if self.cfg.remat:
            # recompute stage activations in the backward pipeline: the
            # scanned schedule then stores only stage-boundary activations
            # per tick instead of every layer's internals (the GPipe
            # activation-memory story)
            body = jax.checkpoint(body)
        h, _ = lax.scan(body, x, stage_params)
        return h

    def _encode_aux(self, params, tokens, *, train: bool = False, rng=None):
        c = self.cfg
        if train and c.dropout > 0.0:
            raise NotImplementedError(
                "PipelinedBertMlm does not support dropout yet — set "
                "dropout=0.0 in the BertConfig")
        dt = c.dtype
        B, S = tokens.shape
        h = params["tok_emb"][tokens] + params["pos_emb"][None, :S]
        h = _layernorm(h, params["emb_ln"]).astype(dt)
        h = self._constrain(h, ("batch", "seq", "embed"))

        n_stages = self._num_stages
        if n_stages == 1:   # no pipe axis: plain sequential stack
            def body(hh, lp):
                return self._plain_layer(lp, hh), None

            flat = jax.tree.map(
                lambda x: x.reshape((-1,) + x.shape[2:]), params["layers"])
            h, _ = lax.scan(body, h, flat)
            return h, jnp.zeros((), jnp.float32)

        M = self.num_microbatches
        dp = self.mesh.shape.get("data", 1)
        if (B // dp) % M:
            raise ValueError(
                f"per-data-shard batch {B // dp} not divisible by "
                f"{M} microbatches")
        h_spec = P("data" if dp > 1 else None)

        def inner(stacked_local, hl):
            stage_params = jax.tree.map(lambda x: x[0], stacked_local)
            mb = hl.reshape((M, hl.shape[0] // M) + hl.shape[1:])
            out = pipeline_lib.pipeline(
                lambda p, x: self._stage(p, x), stage_params, mb, "pipe")
            return out.reshape(hl.shape)

        h = jax.shard_map(
            inner, mesh=self.mesh,
            in_specs=(P("pipe"), h_spec), out_specs=h_spec,
            check_vma=False)(params["layers"], h)
        h = self._constrain(h, ("batch", "seq", "embed"))
        return h, jnp.zeros((), jnp.float32)
