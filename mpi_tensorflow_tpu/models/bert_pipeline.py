"""Pipeline-parallel BERT-MLM: the encoder stack as GPipe stages.

``PipelinedBertMlm`` is the real-model counterpart of the generic schedule
in parallel/pipeline.py (which round 1 only exercised with toy stage fns):
the L encoder layers are split into ``pipe`` stages of L/P layers whose
parameters carry a leading ``stage`` logical axis sharded over the ``pipe``
mesh axis.  Embeddings and the MLM head stay replicated outside the
pipeline (they are ~1% of encoder FLOPs at BERT-base geometry).  The full
*training* step — loss, backward, optimizer — runs through the schedule:
``train/gspmd.make_gspmd_train_step`` works unchanged because this is just
a ``BertMlm`` whose encoder calls ``parallel.pipeline.pipeline`` inside a
``shard_map``; reverse-mode autodiff of the scanned schedule yields the
backward pipeline (reverse ``ppermute`` hops) automatically.

Composition: ``pipe x model x data`` — each data shard runs its own
microbatch stream through the stages, and when the mesh has a ``model``
axis the per-stage compute is Megatron tensor-parallel (heads/MLP-hidden
column-parallel in, manual row-parallel psums; ``_plain_layer`` tp_axis).  The loss-side machinery (masked-position
packing, chunked CE) is inherited.  Dropout trains unmodified: the
schedule hands each stage the index of the microbatch it is processing
(parallel/pipeline.py ``with_mb_index``), and dropout keys are folded on
(data shard, microbatch, global layer, site) so every microbatch draws
independent masks — including under remat, which replays the same fold
inputs and hence identical masks in the recomputation.

Memory schedules, from cheapest to most capable:
- GPipe (``schedule="gpipe"``, default): the scanned forward pipeline with
  autodiff backward — stores ~M microbatch boundary activations; bubble
  (P-1)/(M+P-1) each way.
- Microbatch groups: ``num_microbatches = P`` + the train step's
  ``grad_accum`` — O(P) activations at bubble (P-1)/(2P-1) per group
  (pinned by tests/test_moe_pipeline.py::test_pipeline_with_grad_accum).
- Interleaved 1F1B (``schedule="1f1b"``): hand-interleaved
  one-forward-one-backward via ``parallel/pipeline.pipeline_1f1b`` — the
  same (P-1)/(M+P-1) bubble as end-to-end GPipe but only O(P) stashed
  activations (each stage's backward recomputes its forward from the
  stashed input).  Loss/grad parity with GPipe is pinned by
  tests/test_moe_pipeline.py::TestOneFOneB.
``cfg.remat`` additionally recomputes within-stage activations in the
backward.  TP inside a stage works with both schedules (the 1F1B path
runs a vocab-parallel CE in-schedule); SP inside a stage works with
BOTH schedules too — activations sequence-sharded over the ``seq`` mesh
axis, stage attention as blockwise ring attention (ppermute neighbor
hops), dropout decorrelated per (data, seq) shard — composing to
``pipe x model x seq x data``.  Under 1F1B the in-schedule CE must be
position-local (``ce_positions="all"``; guarded — masked-position
packing gathers across the sequence), and the schedule runs its stage
bodies unconditionally every tick (collectives inside a slot-gated
``lax.cond`` are unsound — see ``pipeline.pipeline_1f1b``).

No counterpart in the reference (SURVEY.md §2 checklist: PP absent).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mpi_tensorflow_tpu.models import bert as bert_lib
from mpi_tensorflow_tpu.models.bert import _layernorm
from mpi_tensorflow_tpu.parallel import pipeline as pipeline_lib
from mpi_tensorflow_tpu.parallel import ring


def _float0(x):
    """Zero cotangent for a non-differentiable input (ints, prng keys)."""
    import numpy as np

    return np.zeros(jnp.shape(x), jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _sched_loss(run, sp, hp, h, labels, mask, inv, key):
    """Splice the 1F1B schedule's manually accumulated gradients into the
    outer autodiff: the schedule computes loss AND grads in one interleaved
    pass (that is its point), so the VJP just scales the saved grads by the
    upstream cotangent.  ``run`` is the shard_mapped schedule (static)."""
    return run(sp, hp, h, labels, mask, inv, key)[0]


def _sched_fwd(run, sp, hp, h, labels, mask, inv, key):
    loss, gs, gl, dmb = run(sp, hp, h, labels, mask, inv, key)
    return loss, (gs, gl, dmb.astype(h.dtype), labels, mask, inv, key)


def _sched_bwd(run, res, ct):
    gs, gl, dmb, labels, mask, inv, key = res
    scale = lambda tree: jax.tree.map(lambda x: x * ct, tree)  # noqa: E731
    return (scale(gs), scale(gl), (dmb * ct).astype(dmb.dtype),
            _float0(labels), _float0(mask),
            jnp.zeros_like(inv), _float0(key))


_sched_loss.defvjp(_sched_fwd, _sched_bwd)


def stack_layers(layers: list, num_stages: int):
    """List of L per-layer param dicts -> stacked pytree of
    (num_stages, L/num_stages, ...) arrays (stage-major, layer order
    preserved)."""
    L = len(layers)
    if L % num_stages:
        raise ValueError(f"{L} layers not divisible by {num_stages} stages")
    return jax.tree.map(
        lambda *xs: jnp.stack(xs).reshape(
            (num_stages, L // num_stages) + xs[0].shape), *layers)


def stack_layers_interleaved(layers: list, num_stages: int, v: int):
    """Interleaved chunk stacking: (P, v, L/(vP), ...) where
    ``stacked[d, j]`` holds GLOBAL chunk ``k = j * P + d`` (layers
    ``k*Lc .. (k+1)*Lc``) — chunks ascend round-robin over devices so
    every pipeline hop is the +1 ring neighbor
    (parallel/pipeline.pipeline_1f1b_interleaved)."""
    L, P_ = len(layers), num_stages
    V = v * P_
    if L % V:
        raise ValueError(f"{L} layers not divisible by {V} chunks "
                         f"({P_} stages x {v} virtual)")
    Lc = L // V

    def stack(*xs):
        flat = jnp.stack(xs)                       # (L, ...)
        ch = flat.reshape((V, Lc) + xs[0].shape)   # chunk-major
        # [k] -> [d, j] with k = j*P + d
        return jnp.moveaxis(ch.reshape((v, P_, Lc) + xs[0].shape), 0, 1)

    return jax.tree.map(stack, *layers)


def unstack_interleaved(stacked, num_stages: int, v: int):
    """Inverse layout map: (P, v, Lc, ...) -> GPipe's (P, v*Lc, ...)
    stage-major order (stage s = chunks s*v .. s*v+v-1 = sequential
    layers).  Pure jnp reshuffle — at the GSPMD level the compiler
    inserts the pipe-axis data movement; used for the forward-only
    (eval/encode) paths, which keep the GPipe scan."""
    P_ = num_stages

    def un(x):
        Lc = x.shape[2]
        ch = jnp.moveaxis(x, 0, 1).reshape((v * P_ * Lc,) + x.shape[3:])
        return ch.reshape((P_, v * Lc) + x.shape[3:])

    return jax.tree.map(un, stacked)


@dataclasses.dataclass(frozen=True)
class PipelinedBertMlm(bert_lib.BertMlm):
    """BERT-MLM with the encoder pipelined over the mesh's ``pipe`` axis.

    ``schedule``: "gpipe" (the scanned forward pipeline; backward derived
    by autodiff — stores M microbatch boundary activations) or "1f1b"
    (interleaved one-forward-one-backward, parallel/pipeline.py
    ``pipeline_1f1b`` — same (P-1)/(M+P-1) bubble, but only O(P) stashed
    activations, the pod-scale memory schedule).  "1f1b" applies to the
    training loss; forward-only encode/apply always use the GPipe scan
    (there is no backward to interleave with)."""
    num_microbatches: int = 4
    schedule: str = "gpipe"
    virtual_stages: int = 1     # v chunks/device for "1f1b_interleaved"

    @property
    def _num_stages(self) -> int:
        return self.mesh.shape.get("pipe", 1) if self.mesh is not None else 1

    @property
    def _interleaved(self) -> bool:
        return self.schedule == "1f1b_interleaved" and self.virtual_stages > 1

    def __post_init__(self):
        if self.schedule not in ("gpipe", "1f1b", "1f1b_interleaved"):
            raise ValueError(f"unknown schedule {self.schedule!r}")
        if self.schedule == "1f1b_interleaved" and self.virtual_stages < 1:
            raise ValueError("virtual_stages must be >= 1")
        if self._interleaved and self.mesh is not None:
            V = self._num_stages * self.virtual_stages
            if self.cfg.layers % max(V, 1):
                raise ValueError(
                    f"{self.cfg.layers} layers not divisible by "
                    f"{V} chunks ({self._num_stages} stages x "
                    f"{self.virtual_stages} virtual)")
        if self.cfg.pos_kind != "learned":
            # the pipelined stage fn replicates the plain layer math
            # WITHOUT the rope rotation; guarding at CONSTRUCTION covers
            # every entry point (incl. checkpoint restore that skips
            # init()) — failing loudly beats training a silently
            # position-blind model
            raise ValueError(
                f"pipelined BERT supports pos_kind='learned' only "
                f"(got {self.cfg.pos_kind!r})")
        if self.schedule in ("1f1b", "1f1b_interleaved") \
                and self.mesh is not None \
                and self.mesh.shape.get("seq", 1) > 1 \
                and self.cfg.ce_positions != "all":
            # the 1F1B path computes the head/CE INSIDE the schedule:
            # with ce_positions="all" that math is position-local (the
            # tied decoder + CE act per position) and composes with
            # sequence sharding via local sums + a seq psum — but the
            # "masked" packing gathers rows ACROSS the sequence and is
            # not sequence-parallel; fail rather than silently unpack
            raise ValueError(
                "schedule='1f1b' under a 'seq' mesh axis needs "
                "ce_positions='all' (masked-position packing gathers "
                "across the sequence and is not sequence-parallel); "
                "use ce_positions='all' or the gpipe schedule")

    def init(self, rng):
        params = super().init(rng)
        if self._interleaved:
            params["layers"] = stack_layers_interleaved(
                params["layers"], self._num_stages, self.virtual_stages)
        else:
            params["layers"] = stack_layers(params["layers"],
                                            self._num_stages)
        return params

    def logical_axes(self):
        axes = super().logical_axes()
        layer0 = axes["layers"][0]
        lead = ("stage", "vchunk", "layer") if self._interleaved \
            else ("stage", "layer")
        axes["layers"] = {k: lead + v
                          for k, v in layer0.items()
                          if not isinstance(v, dict)}
        for k, v in layer0.items():
            if isinstance(v, dict):   # layernorm sub-dicts
                axes["layers"][k] = {kk: lead + vv
                                     for kk, vv in v.items()}
        return axes

    def _plain_layer(self, lp, h, drop=None, tp_axis=None, seq_axis=None):
        """One encoder layer with no mesh constraints — runs inside the
        pipe ``shard_map`` where GSPMD annotations are unavailable.  Same
        math as BertMlm's layer.  ``drop``: ``None`` (eval / dropout off) or
        a ``site -> key`` function yielding this layer's per-site dropout
        keys (already folded on microbatch and global layer index).

        ``tp_axis``: Megatron tensor parallelism INSIDE the stage — the
        stage's heads/MLP-hidden arrive sharded over that mesh axis
        (column-parallel in), and the two row-parallel output projections
        are manually ``psum``'d; biases of the row-parallel outputs are
        added once, after the reduction.

        ``seq_axis``: sequence parallelism INSIDE the stage — ``h``
        arrives sequence-sharded over that mesh axis and attention runs
        as blockwise ring attention (``parallel/ring.ring_attention``,
        ppermute neighbor hops); everything else in the layer is
        position-local and needs no change.  Composes with ``tp_axis``
        (attention is independent per local head subset)."""
        dt = self.cfg.dtype

        def dropout(x, site):
            if drop is None:
                return x
            return bert_lib.dropout_mask(x, self.cfg.dropout, drop(site))

        reduce = None if tp_axis is None else \
            (lambda x: lax.psum(x, tp_axis))
        q, k, v = bert_lib.qkv_proj(lp, h, dt,   # local head subset if TP
                                    fused=self.cfg.fused_qkv)
        # self.causal: False for the MLM family, True for the pipelined
        # causal LM (models/gpt.PipelinedCausalLm) — the mask is the only
        # attention difference, exactly as on the non-pipelined path
        if seq_axis is not None:
            a = ring.ring_attention(q, k, v, seq_axis, causal=self.causal)
        else:
            a = ring.dense_attention(q, k, v, causal=self.causal)
        a = bert_lib.attn_out_proj(lp, a, dt, reduce=reduce)
        h = _layernorm(h + dropout(a, 0), lp["ln1"]).astype(dt)
        m = self._plain_mlp(lp, h, reduce)
        return _layernorm(h + dropout(m, 1), lp["ln2"]).astype(dt)

    def _plain_mlp(self, lp, h, reduce):
        """Stage-interior MLP hook — dense GELU here; the pipelined MoE
        variant (models/moe.PipelinedMoeBertMlm) swaps in the routed
        expert dispatch."""
        return bert_lib.gelu_mlp(lp, h, self.cfg.dtype, reduce=reduce)

    def _dropping(self, train: bool, rng) -> bool:
        if not (train and self.cfg.dropout > 0.0):
            return False
        if rng is None:
            raise ValueError("dropout needs an rng in train mode")
        return True

    def _stage(self, stage_params, x, rng=None, mb_idx=None,
               stage_idx=None, tp_axis=None, seq_axis=None):
        """Run this stage's L/P layers sequentially (scan over the layer
        dim of the stacked params).  When ``rng`` is set, dropout keys are
        folded on (microbatch, global layer, site) so every microbatch at
        every layer draws an independent mask — and a remat recomputation
        replays the identical mask (keys are pure functions of the fold
        inputs)."""
        Lp = jax.tree.leaves(stage_params)[0].shape[0]

        def body(h, inp):
            lp, li = inp
            drop = None
            if rng is not None:
                gl = stage_idx * Lp + li      # global layer index
                kb = jax.random.fold_in(jax.random.fold_in(rng, mb_idx), gl)
                drop = lambda site: jax.random.fold_in(kb, site)  # noqa: E731
            return self._plain_layer(lp, h, drop=drop, tp_axis=tp_axis,
                                     seq_axis=seq_axis), None

        if self.cfg.remat:
            # recompute stage activations in the backward pipeline: the
            # scanned schedule then stores only stage-boundary activations
            # per tick instead of every layer's internals (the GPipe
            # activation-memory story).  The remat_policy mapping is the
            # shared one (bert.remat_policy_fn) — "dots" keeps matmul
            # outputs here exactly as on the non-pipelined path
            body = jax.checkpoint(
                body, policy=bert_lib.remat_policy_fn(self.cfg))
        h, _ = lax.scan(body, x, (stage_params, jnp.arange(Lp)))
        return h

    def _embed(self, params, tokens, dropping: bool, rng):
        """Token+position embeddings (+LN, + the first dropout site) — the
        replicated front section shared by both pipeline schedules."""
        c = self.cfg
        S = tokens.shape[1]
        h = params["tok_emb"][tokens] + params["pos_emb"][None, :S]
        h = _layernorm(h, params["emb_ln"])
        if dropping:
            # embedding dropout (BertMlm's first site), on a stream index
            # no in-stage fold chain can collide with
            h = bert_lib.dropout_mask(h, c.dropout,
                                      jax.random.fold_in(rng, 2 ** 30))
        h = h.astype(c.dtype)
        return self._constrain(h, ("batch", "seq", "embed"))

    def _encode_aux(self, params, tokens, *, train: bool = False, rng=None):
        if self._interleaved:
            # forward-only paths keep the GPipe scan: fold the (P, v, Lc)
            # chunk layout back to stage-major (P, v*Lc) — a pure jnp
            # reshuffle whose pipe-axis data movement GSPMD inserts
            params = dict(params, layers=unstack_interleaved(
                params["layers"], self._num_stages, self.virtual_stages))
        dropping = self._dropping(train, rng)
        B, S = tokens.shape
        h = self._embed(params, tokens, dropping, rng)

        n_stages = self._num_stages
        if n_stages == 1:   # no pipe axis: plain sequential stack
            flat = jax.tree.map(
                lambda x: x.reshape((-1,) + x.shape[2:]), params["layers"])
            h = self._stage(flat, h, rng=rng if dropping else None,
                            mb_idx=jnp.int32(0), stage_idx=jnp.int32(0))
            return h, jnp.zeros((), jnp.float32)

        M = self.num_microbatches
        dp = self.mesh.shape.get("data", 1)
        sp = self.mesh.shape.get("seq", 1)
        if (B // dp) % M:
            raise ValueError(
                f"per-data-shard batch {B // dp} not divisible by "
                f"{M} microbatches")
        if S % sp:
            raise ValueError(
                f"sequence length {S} not divisible by the seq axis {sp}")
        h_spec = P("data" if dp > 1 else None, "seq" if sp > 1 else None)
        tp_axis = "model" if self.mesh.shape.get("model", 1) > 1 else None
        seq_axis = "seq" if sp > 1 else None

        def inner(stacked_local, hl, key):
            stage_params = jax.tree.map(lambda x: x[0], stacked_local)
            mb = hl.reshape((M, hl.shape[0] // M) + hl.shape[1:])
            if dropping:
                # decorrelate the data AND seq shards' masks (each holds
                # a different slice of the global (B, S) activation
                # grid); model shards keep the SAME key — their outputs
                # are replicated.  sp==1 reduces to the data-only fold.
                shard_id = (lax.axis_index("data") if dp > 1 else 0) * sp \
                    + (lax.axis_index("seq") if sp > 1 else 0)
                key = jax.random.fold_in(key, shard_id)
                sidx = lax.axis_index("pipe")
                out = pipeline_lib.pipeline(
                    lambda p, x, mi: self._stage(p, x, rng=key, mb_idx=mi,
                                                 stage_idx=sidx,
                                                 tp_axis=tp_axis,
                                                 seq_axis=seq_axis),
                    stage_params, mb, "pipe", with_mb_index=True)
            else:
                out = pipeline_lib.pipeline(
                    lambda p, x: self._stage(p, x, tp_axis=tp_axis,
                                             seq_axis=seq_axis),
                    stage_params, mb, "pipe")
            return out.reshape(hl.shape)

        key = rng if dropping else jax.random.key(0)
        h = jax.shard_map(
            inner, mesh=self.mesh,
            in_specs=(self._stage_param_specs(gpipe_layout=True), h_spec,
                      P()),
            out_specs=h_spec,
            check_vma=False)(params["layers"], h, key)
        h = self._constrain(h, ("batch", "seq", "embed"))
        return h, jnp.zeros((), jnp.float32)

    def _stage_param_specs(self, gpipe_layout: bool = False):
        """Per-leaf shard_map in_specs for the stacked stage params: the
        rule-table layout (stage -> pipe, heads/mlp -> model when the mesh
        has a model axis) — the specs must tell shard_map the truth about
        how ``shard_tree``/GSPMD placed the parameters, or TP-inside-stage
        would silently gather.

        ``gpipe_layout``: specs for the stage-major (P, v*Lc, ...) view
        ``unstack_interleaved`` produces (the vchunk dim folded away);
        no-op unless the model is interleaved."""
        from mpi_tensorflow_tpu.parallel import sharding_rules

        axes = self.logical_axes()["layers"]
        if gpipe_layout and self._interleaved:
            strip = lambda t: tuple(a for a in t if a != "vchunk")
            axes = jax.tree.map(
                strip, axes, is_leaf=lambda x: isinstance(x, tuple))
        return sharding_rules.tree_specs(axes, self.mesh, self.rules)

    # ------------------------------------------------------------------
    # interleaved 1F1B training path
    # ------------------------------------------------------------------

    def _mb_loss(self, head_params, y, labels_i, mask_i, inv,
                 tp_axis=None):
        """Microbatch loss contribution (already globally normalized by
        ``inv`` = 1/total masked count, so contributions SUM to the same
        loss the GPipe path computes).  Runs on the last stage only.

        ``tp_axis``: the vocab decoder (``tok_emb``/``out_b``) arrives
        vocab-sharded over that axis — CE then goes through the sharded
        logsumexp in ``_vocab_parallel_ce``."""
        c = self.cfg
        if c.ce_positions == "masked":
            from mpi_tensorflow_tpu.ops import mlm_head

            bert_lib.engagement.record("ce_positions", "masked_packed")
            packed, plab, w = mlm_head.gather_masked_rows(
                y, labels_i, mask_i.astype(jnp.bool_),
                bert_lib.ce_capacity(c, y.shape[1]))
            t = self.head_hidden(head_params, packed)
            ce = self._vocab_parallel_ce(head_params, t, plab, tp_axis) \
                if tp_axis is not None else self._ce(head_params, t, plab)
            weights = w
        else:
            bert_lib.engagement.record("ce_positions", "all")
            t = self.head_hidden(head_params, y)
            ce = self._vocab_parallel_ce(head_params, t, labels_i, tp_axis) \
                if tp_axis is not None \
                else self._ce(head_params, t, labels_i)
            weights = mask_i.astype(jnp.float32)
        return jnp.sum(ce * weights) * inv

    def _vocab_parallel_ce(self, head_params, t, labels, tp_axis):
        """Tied-decoder CE with the vocab axis sharded over ``tp_axis``
        (manual collectives — runs inside the 1F1B shard_map where GSPMD
        is unavailable).  Each shard scores its local vocab slice; the
        softmax statistics and the gold logit are reduced across shards:
        logz = log(psum(sum(exp(l - pmax)))) + pmax, and the gold logit is
        psum of the one shard that owns the label's row."""
        dt = self.cfg.dtype
        logits = jnp.einsum("bse,ve->bsv", t,
                            head_params["tok_emb"].astype(dt)) \
            + head_params["mlm"]["out_b"]
        logits = logits.astype(jnp.float32)
        v_loc = logits.shape[-1]
        lo = lax.axis_index(tp_axis) * v_loc
        # the max is numerical stabilization only (it cancels exactly in
        # logz's gradient) — detached; pmax has no differentiation rule,
        # so the cross-shard max goes through all_gather (which has one)
        m = lax.stop_gradient(jnp.max(
            lax.all_gather(jnp.max(logits, axis=-1), tp_axis, axis=0),
            axis=0))
        se = lax.psum(jnp.sum(jnp.exp(logits - m[..., None]), axis=-1),
                      tp_axis)
        logz = jnp.log(se) + m
        in_range = (labels >= lo) & (labels < lo + v_loc)
        loc = jnp.clip(labels - lo, 0, v_loc - 1)
        gold_loc = jnp.take_along_axis(logits, loc[..., None], axis=-1)[..., 0]
        gold = lax.psum(jnp.where(in_range, gold_loc, 0.0), tp_axis)
        return logz - gold

    def loss(self, params, model_state, batch, labels, *, rng=None,
             train: bool = False):
        if self.schedule not in ("1f1b", "1f1b_interleaved") \
                or self._num_stages == 1 or not train:
            bert_lib.engagement.record("pp_schedule", "gpipe")
            return super().loss(params, model_state, batch, labels,
                                rng=rng, train=train)
        bert_lib.engagement.record(
            "pp_schedule",
            "1f1b_interleaved" if self._interleaved else "1f1b")

        c = self.cfg
        tokens, mask = batch["tokens"], batch["mask"]
        B, S = tokens.shape
        dropping = self._dropping(train, rng)
        M = self.num_microbatches
        dp = self.mesh.shape.get("data", 1)
        sp = self.mesh.shape.get("seq", 1)
        if (B // dp) % M:
            raise ValueError(
                f"per-data-shard batch {B // dp} not divisible by "
                f"{M} microbatches")
        if S % sp:
            raise ValueError(
                f"sequence length {S} not divisible by the seq axis {sp}")
        h = self._embed(params, tokens, dropping, rng)
        # global normalizer, fixed before the schedule (data-only, no
        # grad): per-microbatch SUMS scaled by it add up to exactly the
        # GPipe path's globally normalized mean — and, under sequence
        # sharding, per-(data, seq)-shard partial sums scaled by it add
        # up the same way (the "all" CE is position-local)
        inv = 1.0 / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
        head_params = {"mlm": params["mlm"], "tok_emb": params["tok_emb"]}
        key = rng if dropping else jax.random.key(0)
        h_spec = P("data" if dp > 1 else None, "seq" if sp > 1 else None)
        tp_axis = "model" if self.mesh.shape.get("model", 1) > 1 else None
        seq_axis = "seq" if sp > 1 else None
        # the in-schedule head/CE math runs INSIDE shard_map, where GSPMD
        # sharding constraints are illegal — a mesh-free view of this model
        # computes the same math without annotations
        plain = dataclasses.replace(self, mesh=None)
        from mpi_tensorflow_tpu.parallel import sharding_rules

        axes = self.logical_axes()
        hp_specs = sharding_rules.tree_specs(
            {"mlm": axes["mlm"], "tok_emb": axes["tok_emb"]}, self.mesh,
            self.rules)
        sp_specs = self._stage_param_specs()

        def _reduce_partials(grads, specs):
            """Under manual vjp inside shard_map, a REPLICATED parameter's
            cotangent comes back as per-model-shard partials whose sum is
            the true grad; model-sharded leaves are already local-true.
            Sum exactly the leaves whose spec does not mention the axis."""
            if tp_axis is None:
                return grads
            return jax.tree.map(
                lambda g, spec: g if tp_axis in spec
                else lax.psum(g, tp_axis), grads, specs)

        def inner(stacked_local, hp, hl, labels_l, mask_l, inv, key):
            sp_params = jax.tree.map(lambda x: x[0], stacked_local)
            mbsz = hl.shape[0] // M
            mb = hl.reshape((M, mbsz) + hl.shape[1:])
            lab = labels_l.reshape((M, mbsz) + labels_l.shape[1:])
            msk = mask_l.reshape((M, mbsz) + mask_l.shape[1:])
            if dropping:
                # same (data, seq) shard fold as the GPipe path — the
                # cross-schedule mask-identity pin depends on it
                shard_id = (lax.axis_index("data") if dp > 1 else 0) \
                    * sp + (lax.axis_index("seq") if sp > 1 else 0)
                key = jax.random.fold_in(key, shard_id)
            sidx = lax.axis_index("pipe")

            def stage_fn(p, x, mi):
                return self._stage(p, x, rng=key if dropping else None,
                                   mb_idx=mi, stage_idx=sidx,
                                   tp_axis=tp_axis, seq_axis=seq_axis)

            def last_fn(hp, y, aux):
                # ce_positions="all" under seq sharding: the tied
                # decoder + CE act per position, so the local slice's
                # sum * inv is this shard's partial of the global mean
                labels_i, mask_i = aux
                return plain._mb_loss(hp, y, labels_i, mask_i, inv,
                                      tp_axis=tp_axis)

            # stage bodies carry collectives whenever TP or SP is inside
            # them — those meshes need uniform (unconditional) stage
            # execution; plain pipe x data keeps the slot-gated fast path
            uniform = tp_axis is not None or seq_axis is not None
            if self._interleaved:
                def chunk_fn(p, x, mi, kg):
                    # kg = GLOBAL chunk index: _stage derives the global
                    # layer as stage_idx * Lp + li, and the chunk's Lp is
                    # L/(vP) — masks match the gpipe/1f1b schedules
                    return self._stage(p, x,
                                       rng=key if dropping else None,
                                       mb_idx=mi, stage_idx=kg,
                                       tp_axis=tp_axis, seq_axis=seq_axis)

                loss, gs, gl, dmb = pipeline_lib.pipeline_1f1b_interleaved(
                    chunk_fn, last_fn, sp_params, hp, mb, (lab, msk),
                    "pipe", v=self.virtual_stages,
                    n_stages=self._num_stages, uniform_stages=uniform)
            else:
                loss, gs, gl, dmb = pipeline_lib.pipeline_1f1b(
                    stage_fn, last_fn, sp_params, hp, mb, (lab, msk),
                    "pipe", uniform_stages=uniform)
            gl = _reduce_partials(gl, hp_specs)
            gs = _reduce_partials(gs, sp_specs)
            if tp_axis is not None:
                dmb = lax.psum(dmb, tp_axis)   # h is model-replicated
                # the microbatch loss is computed REPLICATED across model
                # shards, so last_fn's vjp seeds the cotangent once per
                # shard — every accumulated gradient carries a factor of
                # tp; normalize once here (the loss VALUE is replicated,
                # not summed, and needs no correction)
                tp = self.mesh.shape["model"]
                gs, gl, dmb = jax.tree.map(lambda x: x / tp,
                                           (gs, gl, dmb))
            # sum loss/replicated-param grads over the data shards (each
            # saw a different batch slice of the global mean) AND the seq
            # shards (each saw a different position slice; params are
            # seq-replicated, so their cotangents are partials — dmb is
            # seq-SHARDED and already local-true)
            red = tuple(a for a, n in (("data", dp), ("seq", sp))
                        if n > 1)
            if red:
                loss = lax.psum(loss, red)
                gl = jax.tree.map(lambda x: lax.psum(x, red), gl)
                gs = jax.tree.map(lambda x: lax.psum(x, red), gs)
            # restore the stacked leading stage axis for the out_spec
            gs = jax.tree.map(lambda x: x[None], gs)
            return loss, gs, gl, dmb.reshape(hl.shape)

        run = jax.shard_map(
            inner, mesh=self.mesh,
            in_specs=(sp_specs, hp_specs, h_spec, h_spec, h_spec,
                      P(), P()),
            out_specs=(P(), sp_specs, hp_specs, h_spec),
            check_vma=False)

        loss = _sched_loss(run, params["layers"], head_params, h, labels,
                           mask, inv, key)
        return loss, model_state
