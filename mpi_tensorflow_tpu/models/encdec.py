"""Encoder-decoder LM (T5-shaped) — the cross-attention family.

The framework's transformer families cover bidirectional encoding (BERT),
autoregressive decoding (GPT), routed experts (MoE), pipeline stages, and
patches (ViT) — all built from self-attention blocks.  This family adds
the one block type missing from that set: CROSS-attention, composed from
the same primitives (``bert.qkv_proj`` / ``attn_out_proj`` /
``gelu_mlp`` / ``_layernorm``) so the math has one definition.

Shape: token encoder (the SHARED ``bert._run_layers`` stack,
bidirectional) -> decoder layers of [causal self-attn, cross-attn over
the encoder output, GELU MLP], post-LN residuals like the sibling
families, tied token embedding for encoder input, decoder input, and the
output head.  Positions are learned absolute embeddings (the framework
convention) rather than T5's relative bias — a documented divergence;
the family is named EncDecLm, not T5.

Loss: teacher-forced next-token CE on the decoder side.  Inference:
``generate`` encodes once, then runs the KV-cache decoder loop
(self-attn cache per layer; the cross-attn K/V are computed once from
the encoder output and reused every step — the standard enc-dec serving
shape).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from mpi_tensorflow_tpu.models import bert as bert_lib
from mpi_tensorflow_tpu.models.bert import (_layernorm, _norm_init,
                                            attn_out_proj, gelu_mlp,
                                            qkv_proj)
from mpi_tensorflow_tpu.parallel import ring


@dataclasses.dataclass(frozen=True)
class EncDecLm:
    """Encoder-decoder LM on the shared transformer primitives.

    ``cfg`` is a ``bert.BertConfig``; ``dec_layers`` defaults to
    ``cfg.layers`` (symmetric stacks, the T5 convention)."""
    cfg: bert_lib.BertConfig = bert_lib.BERT_TINY
    dec_layers: Optional[int] = None

    @property
    def n_dec(self) -> int:
        return self.dec_layers or self.cfg.layers

    def _encoder(self) -> bert_lib.BertMlm:
        return bert_lib.BertMlm(self.cfg)

    # ---------------- init ----------------

    def __post_init__(self):
        if self.cfg.pos_kind != "learned":
            # the decoder embeds learned positions; mixing a rope encoder
            # with a learned decoder would be a silent semantic fork —
            # guard at construction so checkpoint-restore paths that skip
            # init() are covered too
            raise ValueError(
                f"the encoder-decoder family supports pos_kind='learned' "
                f"only (got {self.cfg.pos_kind!r})")

    def init(self, rng):
        c = self.cfg
        # key budget: 3 embeddings + 6 per encoder layer + 10 per decoder
        # layer (init_encoder_layer's 6 + xq/xk/xv/xo); over-allocating is
        # harmless, running out raises StopIteration mid-init
        k = iter(jax.random.split(rng, 4 + 6 * c.layers + 10 * self.n_dec))
        params = {
            "tok_emb": _norm_init(next(k), (c.vocab_size, c.hidden)),
            "pos_emb": _norm_init(next(k), (c.max_positions, c.hidden)),
            "emb_ln": {"scale": jnp.ones((c.hidden,)),
                       "bias": jnp.zeros((c.hidden,))},
            "layers": [bert_lib.init_encoder_layer(k, c)
                       for _ in range(c.layers)],
            "dec_pos_emb": _norm_init(next(k), (c.max_positions, c.hidden)),
            "dec_emb_ln": {"scale": jnp.ones((c.hidden,)),
                           "bias": jnp.zeros((c.hidden,))},
            "dec_layers": [self._init_dec_layer(k, c)
                           for _ in range(self.n_dec)],
            "out_b": jnp.zeros((c.vocab_size,)),
        }
        return params

    @staticmethod
    def _init_dec_layer(k, c) -> dict:
        """Self-attn block + cross-attn block + MLP (9 keys)."""
        lp = bert_lib.init_encoder_layer(k, c)     # self-attn + MLP (6)
        lp["xq"] = _norm_init(next(k), (c.hidden, c.heads, c.head_dim))
        lp["xk"] = _norm_init(next(k), (c.hidden, c.heads, c.head_dim))
        lp["xv"] = _norm_init(next(k), (c.hidden, c.heads, c.head_dim))
        lp["xbq"] = jnp.zeros((c.heads, c.head_dim))
        lp["xbk"] = jnp.zeros((c.heads, c.head_dim))
        lp["xbv"] = jnp.zeros((c.heads, c.head_dim))
        lp["xo"] = _norm_init(next(k), (c.heads, c.head_dim, c.hidden))
        lp["xbo"] = jnp.zeros((c.hidden,))
        lp["lnx"] = {"scale": jnp.ones((c.hidden,)),
                     "bias": jnp.zeros((c.hidden,))}
        return lp

    def logical_axes(self):
        """Logical sharding axes (parallel/sharding_rules.py): the
        encoder layers reuse BertMlm's table; decoder cross-attention
        projections follow the same column/row-parallel layout (heads
        over ``model``)."""
        enc = bert_lib.BertMlm(self.cfg)
        layer = enc.logical_axes()["layers"][0]
        ln = {"scale": ("embed",), "bias": ("embed",)}
        dec_layer = dict(layer)
        dec_layer.update({
            "xq": ("embed", "heads", "head_dim"),
            "xk": ("embed", "heads", "head_dim"),
            "xv": ("embed", "heads", "head_dim"),
            "xbq": ("heads", "head_dim"), "xbk": ("heads", "head_dim"),
            "xbv": ("heads", "head_dim"),
            "xo": ("heads", "head_dim", "embed"), "xbo": ("embed",),
            "lnx": ln,
        })
        return {
            "tok_emb": ("vocab", "embed"),
            "pos_emb": ("pos", "embed"),
            "emb_ln": ln,
            "layers": [dict(layer) for _ in range(self.cfg.layers)],
            "dec_pos_emb": ("pos", "embed"),
            "dec_emb_ln": ln,
            "dec_layers": [dict(dec_layer) for _ in range(self.n_dec)],
            "out_b": ("vocab",),
        }

    # ---------------- forward ----------------

    def encode(self, params, src, *, train: bool = False, rng=None):
        """Bidirectional encoding of ``src`` (B, S) ids -> (B, S, E)."""
        c = self.cfg
        S = src.shape[1]
        h = params["tok_emb"][src] + params["pos_emb"][None, :S]
        h = _layernorm(h, params["emb_ln"])
        # embedding-site dropout on stream index 1, exactly as BertMlm
        # applies it (ADVICE r3: this site was silently skipped, quietly
        # diverging the family's regularization from its siblings)
        if train and c.dropout > 0.0:
            if rng is None:
                raise ValueError("dropout needs an rng in train mode")
            h = bert_lib.dropout_mask(h, c.dropout,
                                      jax.random.fold_in(rng, 1))
        h = h.astype(c.dtype)
        enc = self._encoder()
        h, _ = enc._run_layers({"layers": params["layers"]}, h,
                               train=train, rng=rng, drop_start=1)
        return h

    def _dec_embed(self, params, tgt_in, offset=0):
        c = self.cfg
        S = tgt_in.shape[1]
        pos = lax.dynamic_slice(params["dec_pos_emb"],
                                (offset, 0), (S, c.hidden))
        h = params["tok_emb"][tgt_in] + pos[None]
        return _layernorm(h, params["dec_emb_ln"]).astype(c.dtype)

    def _cross_kv(self, params, enc_out):
        """Per-decoder-layer cross-attention K/V from the encoder output —
        computed ONCE per source (prefill and every decode step reuse
        them)."""
        dt = self.cfg.dtype
        kv = []
        for lp in params["dec_layers"]:
            k = jnp.einsum("bse,ehd->bhsd", enc_out,
                           lp["xk"].astype(dt)) \
                + lp["xbk"].astype(dt)[None, :, None, :]
            v = jnp.einsum("bse,ehd->bhsd", enc_out,
                           lp["xv"].astype(dt)) \
                + lp["xbv"].astype(dt)[None, :, None, :]
            kv.append({"k": k, "v": v})
        return kv

    def _dec_layer(self, lp, h, xkv, *, self_attn, drop=None):
        """One decoder layer: residual self-attn (impl injected — dense
        causal for training, cache-backed for decoding), residual
        cross-attn, residual MLP.  Post-LN like the sibling families.
        ``drop``: ``drop(site_idx, x)`` dropout hook (None = eval)."""
        dt = self.cfg.dtype
        d = drop if drop is not None else (lambda i, x: x)
        a = d(0, self_attn(lp, h))
        h = _layernorm(h + a, lp["ln1"]).astype(dt)
        # cross-attention: queries from the decoder, K/V from the encoder
        q = jnp.einsum("bse,ehd->bhsd", h, lp["xq"].astype(dt)) \
            + lp["xbq"].astype(dt)[None, :, None, :]
        x = ring.dense_attention(q, xkv["k"], xkv["v"], causal=False)
        x = jnp.einsum("bhsd,hde->bse", x, lp["xo"].astype(dt)) \
            + lp["xbo"].astype(dt)
        h = _layernorm(h + d(1, x), lp["lnx"]).astype(dt)
        m = gelu_mlp(lp, h, dt)
        return _layernorm(h + d(2, m), lp["ln2"]).astype(dt)

    def _dec_self_attn_impl(self):
        """Decoder self-attention dispatch: the SHARED BertMlm._attention
        with causal=True — flash engages above cfg.flash_min_seq exactly
        as on the GPT path, and engagement records the choice.  Cross-
        attention stays XLA dense by design: its (T, S) score block is
        rectangular and the flash kernels are square-block; dense is the
        measured-correct choice at rectangular shapes."""
        return bert_lib.BertMlm(self.cfg, causal=True)._attention

    def _dec_drop(self, li: int, train: bool, rng):
        """Decoder dropout hook for layer ``li``: stream indices continue
        AFTER the encoder's (embed site 1 + 2 per encoder layer) and the
        decoder-embed site (index 2 + 2*enc_layers), 3 sites per decoder
        layer — disjoint fold_in keys across the model."""
        c = self.cfg
        if not train or c.dropout == 0.0:
            return None
        if rng is None:
            raise ValueError("dropout needs an rng in train mode")
        base = 3 + 2 * c.layers + 3 * li

        def drop(site, x):
            return bert_lib.dropout_mask(
                x, c.dropout, jax.random.fold_in(rng, base + site))
        return drop

    def decode_hidden(self, params, enc_out, tgt_in, *,
                      train: bool = False, rng=None):
        """Teacher-forced decoder pass -> hidden states (B, T, E) in the
        compute dtype (the input to the tied vocab head)."""
        c = self.cfg
        dt = c.dtype
        h = self._dec_embed(params, tgt_in)
        # decoder embedding-site dropout on the reserved stream index
        # right after the encoder's (see _dec_drop); generate() never
        # trains, so the site lives here rather than in _dec_embed
        if train and c.dropout > 0.0:
            if rng is None:
                raise ValueError("dropout needs an rng in train mode")
            h = bert_lib.dropout_mask(
                h, c.dropout,
                jax.random.fold_in(rng, 2 + 2 * c.layers)).astype(dt)
        xkvs = self._cross_kv(params, enc_out)
        attn = self._dec_self_attn_impl()

        def self_attn(lp, h):
            q, k, v = qkv_proj(lp, h, dt, fused=self.cfg.fused_qkv)
            return attn_out_proj(lp, attn(q, k, v), dt)

        def layer(h, lp, xkv, li):
            return self._dec_layer(lp, h, xkv, self_attn=self_attn,
                                   drop=self._dec_drop(li, train, rng))

        if self.cfg.remat:
            # same remat semantics as the encoder stack (the dropout keys
            # fold deterministically, so recomputation replays identical
            # masks); the policy mapping is the shared one
            layer = jax.checkpoint(
                layer, static_argnums=(3,),
                policy=bert_lib.remat_policy_fn(self.cfg))
        for li, (lp, xkv) in enumerate(zip(params["dec_layers"], xkvs)):
            h = layer(h, lp, xkv, li)
        return h

    def _head_logits(self, params, h):
        dt = self.cfg.dtype
        logits = jnp.einsum("bse,ve->bsv", h,
                            params["tok_emb"].astype(dt)) + params["out_b"]
        return logits.astype(jnp.float32)

    def decode_train(self, params, enc_out, tgt_in, *,
                     train: bool = False, rng=None):
        """Teacher-forced decoder pass -> fp32 logits (B, T, V)."""
        return self._head_logits(params, self.decode_hidden(
            params, enc_out, tgt_in, train=train, rng=rng))

    def apply(self, params, batch, *, train: bool = False, rng=None):
        """``batch``: {"src": (B, S), "tgt": (B, T)} int ids.  Returns
        decoder logits (B, T, V) (position t predicts tgt[t+1])."""
        enc_out = self.encode(params, batch["src"], train=train, rng=rng)
        return self.decode_train(params, enc_out, batch["tgt"],
                                 train=train, rng=rng)

    def loss(self, params, model_state, batch, labels=None, *, rng=None,
             train: bool = False):
        """Teacher-forced next-token CE over the target side: position t
        is supervised by tgt[t+1]; the final position is unsupervised.
        Matches CausalLm's loss shape so the gspmd step drives it
        unchanged.  The CE follows ``cfg.ce_impl`` like the sibling
        families: chunked online-logsumexp by default (every position
        carries loss — (B, T, V) fp32 logits would cost ~1 GB at the
        bench shape), dense on request."""
        from mpi_tensorflow_tpu.utils import engagement

        tgt = batch["tgt"]
        enc_out = self.encode(params, batch["src"], train=train, rng=rng)
        h = self.decode_hidden(params, enc_out, tgt, train=train, rng=rng)
        targets = jnp.concatenate(
            [tgt[:, 1:], jnp.zeros_like(tgt[:, :1])], axis=1)
        if self.cfg.ce_impl != "dense":
            from mpi_tensorflow_tpu.ops import mlm_head

            engagement.record("ce", f"chunked:{self.cfg.ce_chunk}")
            ce = mlm_head.tied_softmax_ce(
                h, params["tok_emb"], params["out_b"], targets,
                chunk=self.cfg.ce_chunk)
        else:
            engagement.record("ce", "dense")
            logits = self._head_logits(params, h)
            logz = jax.nn.logsumexp(logits, axis=-1)
            ce = logz - jnp.take_along_axis(
                logits, targets[..., None], axis=-1)[..., 0]
        w = jnp.ones_like(ce).at[:, -1].set(0.0)
        return jnp.sum(ce * w) / jnp.maximum(jnp.sum(w), 1.0), model_state

    def l2_params(self, params) -> list:
        return []

    # ---------------- inference ----------------

    def generate(self, params, src, max_new_tokens: int, *,
                 bos_id: int = 0):
        """Greedy decode conditioned on ``src``: encode once, then a
        KV-cache decoder loop (static (B, H, L, D) self-attn cache per
        layer; the cross K/V are computed once).  Returns (B,
        max_new_tokens) generated ids, starting AFTER the BOS seed."""
        if max_new_tokens < 1:
            raise ValueError("generate needs max_new_tokens >= 1")
        c = self.cfg
        if max_new_tokens > c.max_positions:
            # _dec_embed's dynamic_slice clamps its start index, so
            # decoding past the learned dec_pos_emb table would silently
            # reuse the last row's embedding — mirror CausalLm.init_cache
            # and raise instead (ADVICE r3)
            raise ValueError(
                f"max_new_tokens {max_new_tokens} exceeds max_positions "
                f"{c.max_positions}")
        dt = c.dtype
        B = src.shape[0]
        L = max_new_tokens
        enc_out = self.encode(params, src)
        xkvs = self._cross_kv(params, enc_out)
        z = jnp.zeros((B, c.heads, L, c.head_dim), dt)
        cache0 = [{"k": z, "v": z} for _ in range(self.n_dec)]
        col = jnp.arange(L)

        def step_token(carry, i):
            cache, token = carry
            h = self._dec_embed(params, token[:, None], offset=i)
            new_cache = []

            def self_attn_factory(li):
                def self_attn(lp, hq):
                    q, k, v = qkv_proj(lp, hq, dt, fused=c.fused_qkv)
                    cc = cache[li]
                    ck = lax.dynamic_update_slice(cc["k"], k,
                                                  (0, 0, i, 0))
                    cv = lax.dynamic_update_slice(cc["v"], v,
                                                  (0, 0, i, 0))
                    new_cache.append({"k": ck, "v": cv})
                    s = jnp.einsum("bhsd,bhld->bhsl", q, ck) \
                        .astype(jnp.float32)
                    vis = (col <= i)[None, None, None, :]
                    s = jnp.where(vis, s * c.head_dim ** -0.5,
                                  jnp.finfo(jnp.float32).min)
                    p = jax.nn.softmax(s, axis=-1).astype(dt)
                    a = jnp.einsum("bhsl,bhld->bhsd", p, cv)
                    return attn_out_proj(lp, a, dt)
                return self_attn

            for li, (lp, xkv) in enumerate(zip(params["dec_layers"],
                                               xkvs)):
                h = self._dec_layer(lp, h, xkv,
                                    self_attn=self_attn_factory(li))
            logits = jnp.einsum("bse,ve->bsv", h,
                                params["tok_emb"].astype(dt)) \
                + params["out_b"]
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            return (new_cache, nxt), nxt

        bos = jnp.full((B,), bos_id, jnp.int32)
        _, toks = lax.scan(step_token, (cache0, bos), jnp.arange(L))
        return toks.T    # (B, L)
