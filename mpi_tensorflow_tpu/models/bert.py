"""BERT-base masked-LM — BASELINE.json config 5 ("stress allreduce bandwidth").

The reference has no transformer, no attention, no sequence axis (SURVEY.md
§2 checklist); BERT-MLM is the directed scale-out family that exercises the
framework's transformer stack: multi-axis sharding (DP x TP x SP) and ring
attention for long sequences.

Architecture: original BERT-base encoder (post-LN): token+position
embeddings -> 12 x [MHA + residual/LN, GELU-MLP + residual/LN] -> tied-weight
MLM head over the vocab.  Hyperparameters configurable; ``BERT_BASE`` is the
canonical 110M-param config.

Sharding (parallel/sharding_rules.py, Megatron layout):
- attention QKV column-parallel over ``model`` (heads sharded), output
  projection row-parallel;
- MLP in column-parallel / out row-parallel over ``model``;
- embedding + LM head vocab-parallel over ``model``;
- activations batch-sharded over ``data``, sequence-sharded over ``seq``;
- attention runs as ring attention (parallel/ring.py) via an inner
  ``shard_map`` when the mesh has a ``seq`` axis >1, dense otherwise.
All other collectives are inserted by XLA GSPMD from the constraints.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mpi_tensorflow_tpu.parallel import ring, sharding_rules as rules_lib
from mpi_tensorflow_tpu.utils import engagement


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    mlp: int = 3072
    max_positions: int = 512
    dropout: float = 0.1
    dtype: Any = jnp.float32      # compute dtype; bfloat16 for TPU throughput
    sp_impl: str = "ring"         # sequence-parallel attention: "ring"
                                  # (ppermute K/V hops, any head count) or
                                  # "ulysses" (2 all-to-alls, needs heads
                                  # divisible by the seq axis) —
                                  # parallel/ring.py vs parallel/ulysses.py
    remat: bool = False           # jax.checkpoint each encoder layer:
                                  # recompute activations in the backward
                                  # pass — peak activation HBM drops from
                                  # O(layers) to O(1) residual streams
    remat_policy: str = "full"    # what a rematted layer SAVES: "full"
                                  # = nothing (maximum recompute, minimum
                                  # HBM); "dots" = keep matmul outputs
                                  # (jax.checkpoint_policies.
                                  # dots_with_no_batch_dims_saveable) and
                                  # recompute only the cheap elementwise —
                                  # the usual TPU sweet spot: the MXU work
                                  # is not repeated, and saved dot outputs
                                  # are the activations XLA would keep
                                  # anyway at ~half the HBM of no-remat
    ce_impl: str = "auto"         # MLM loss: "chunked" = online-logsumexp
                                  # over vocab tiles, never materializing
                                  # (B,S,V) fp32 logits (ops/mlm_head.py);
                                  # "dense" = full logits; "auto" = chunked
                                  # unless the vocab is tensor-parallel
                                  # sharded (then GSPMD's sharded dense
                                  # logits are already memory-bounded)
    ce_chunk: int = 2048          # vocab tile width for the chunked CE
    ce_positions: str = "masked"  # "masked": pack each row's masked
                                  # positions (<= ce_capacity_frac * S of
                                  # them) before the MLM head, so the head
                                  # transform + vocab decoder run on ~15-25%
                                  # of tokens (BERT's
                                  # max_predictions_per_seq, TPU-shaped);
                                  # "all": head over every position
    ce_capacity_frac: float = 0.25  # per-row packed-buffer width / S
    fused_qkv: bool = False       # compute q,k,v via ONE (E, 3HD) matmul
                                  # on stacked weights instead of three
                                  # (E, HD) matmuls — fewer, larger MXU
                                  # dispatches; parameters stay separate
                                  # (checkpoints/sharding rules unchanged)
    pos_kind: str = "learned"     # position encoding: "learned" absolute
                                  # embeddings (the BERT convention) or
                                  # "rope" rotary (applied to q/k right
                                  # before the attention dispatch, so
                                  # dense/flash/ring/Ulysses and the
                                  # KV-cache decode all inherit it; the
                                  # pos_emb table stays in the pytree
                                  # unused, keeping checkpoint layout
                                  # stable across the knob)
    flash_min_seq: int = 4096     # engage the Pallas flash kernel only at
                                  # sequence length >= this; below it XLA's
                                  # fused dense attention wins on measured
                                  # hardware (TPU v5e, BASELINE.md round 3:
                                  # XLA beats flash 121.3k vs 100.3k tok/s
                                  # at S=128 and 30.7k vs 27.5k at S=2048
                                  # — the kernel's unfused epilogue + lse
                                  # round-trips cost more than the (S, S)
                                  # score materialization saves until the
                                  # scores stop fitting in VMEM-friendly
                                  # tiles).  0 = always engage (kernel
                                  # A/B measurement arms)

    def __post_init__(self):
        # a misspelled value ("rotary", "Rope") would silently fall back
        # to learned positions at one site and skip rotation at another;
        # fail at construction instead
        if self.pos_kind not in ("learned", "rope"):
            raise ValueError(f"pos_kind must be 'learned' or 'rope', "
                             f"got {self.pos_kind!r}")

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads


BERT_BASE = BertConfig()
BERT_TINY = BertConfig(vocab_size=1024, hidden=64, layers=2, heads=4, mlp=128,
                       max_positions=128, dropout=0.0)


def _norm_init(key, shape, stddev=0.02):
    return jax.random.normal(key, shape) * stddev


def init_encoder_layer(k, c) -> dict:
    """One encoder layer's parameters (``k``: a key iterator, 6 keys
    consumed).  Shared by BertMlm.init and the ViT family so the layer
    pytree structure — which ``_run_layers``, the pipeline stages, and
    the sharding rules all assume — has exactly one definition."""
    return {
        "wq": _norm_init(next(k), (c.hidden, c.heads, c.head_dim)),
        "wk": _norm_init(next(k), (c.hidden, c.heads, c.head_dim)),
        "wv": _norm_init(next(k), (c.hidden, c.heads, c.head_dim)),
        "bq": jnp.zeros((c.heads, c.head_dim)),
        "bk": jnp.zeros((c.heads, c.head_dim)),
        "bv": jnp.zeros((c.heads, c.head_dim)),
        "wo": _norm_init(next(k), (c.heads, c.head_dim, c.hidden)),
        "bo": jnp.zeros((c.hidden,)),
        "ln1": {"scale": jnp.ones((c.hidden,)),
                "bias": jnp.zeros((c.hidden,))},
        "w1": _norm_init(next(k), (c.hidden, c.mlp)),
        "b1": jnp.zeros((c.mlp,)),
        "w2": _norm_init(next(k), (c.mlp, c.hidden)),
        "b2": jnp.zeros((c.hidden,)),
        "ln2": {"scale": jnp.ones((c.hidden,)),
                "bias": jnp.zeros((c.hidden,))},
    }


def ce_capacity(cfg, S: int) -> int:
    """Packed-buffer width for the masked-position head: per-row capacity
    ``ce_capacity_frac * S`` rounded up to a multiple of 8 (lane-friendly),
    floored at 8, capped at S.  The ONE definition shared by BertMlm.loss
    and the pipelined 1F1B microbatch loss — the schedules' loss parity
    depends on both computing the identical cap."""
    return min(S, max(8, -(-int(cfg.ce_capacity_frac * S) // 8) * 8))


def rope(x, positions, base: float = 10000.0):
    """Rotary position embedding: rotate each (even, odd-half) feature
    pair of ``x`` (B, H, S, D) by an angle proportional to its ABSOLUTE
    position, so dot products depend only on RELATIVE offsets
    (rope(q,p1)·rope(k,p2) == rope(q,p1+d)·rope(k,p2+d) — pinned by
    test).  ``positions``: (S,) int/float absolute positions, or (B, S)
    per-row positions (the paged decode path, where every sequence sits
    at its own offset).  Angles in fp32, output in x.dtype; D must be
    even."""
    D = x.shape[-1]
    half = D // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    pos = jnp.asarray(positions, jnp.float32)
    ang = pos[..., None] * freqs                    # (..., S, half)
    if pos.ndim == 2:
        cos = jnp.cos(ang)[:, None]                 # (B, 1, S, half)
        sin = jnp.sin(ang)[:, None]
    else:
        cos = jnp.cos(ang)[None, None]              # (1, 1, S, half)
        sin = jnp.sin(ang)[None, None]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], -1).astype(x.dtype)


def remat_policy_fn(cfg):
    """Resolve ``cfg.remat_policy`` to a ``jax.checkpoint`` policy —
    the ONE mapping shared by the encoder stack and the pipeline
    schedules (a policy honored on one path and silently ignored on
    another would make ``remat_policy`` a per-path lie).  ``None`` =
    save nothing (the "full" recompute)."""
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if cfg.remat_policy == "full":
        return None
    raise ValueError(f"unknown remat_policy {cfg.remat_policy!r}")


def dropout_mask(x, rate: float, key):
    """Inverted dropout: zero with prob ``rate``, scale survivors by
    1/keep.  The single implementation shared by BertMlm's keyed streams
    and the pipelined model's fold-derived keys."""
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


def _layernorm(x, p, eps=1e-12):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]


# -- shared per-layer math -------------------------------------------------
# One definition serves the GSPMD encoder (BertMlm._encode_aux), the
# pipelined stage (bert_pipeline._plain_layer), and the KV-cache decode
# path (gpt.forward_with_cache): a change to the block cannot silently
# diverge one of them.

def qkv_proj(lp, h, dt, fused: bool = False):
    """(B, S, E) -> per-head q, k, v, each (B, H, S, D).

    ``fused``: stack the three weights at trace time and run one
    (E, 3HD) matmul — one MXU dispatch instead of three.  The stack is a
    3.5 MB bf16 copy per layer that XLA typically folds into the matmul
    operand layout; parameters remain separate leaves either way."""
    if fused:
        w = jnp.stack([lp["wq"], lp["wk"], lp["wv"]]).astype(dt)
        b = jnp.stack([lp["bq"], lp["bk"], lp["bv"]]).astype(dt)
        qkv = jnp.einsum("bse,cehd->cbhsd", h, w) \
            + b[:, None, :, None, :]
        return qkv[0], qkv[1], qkv[2]
    q = jnp.einsum("bse,ehd->bhsd", h, lp["wq"].astype(dt)) \
        + lp["bq"].astype(dt)[None, :, None, :]
    k = jnp.einsum("bse,ehd->bhsd", h, lp["wk"].astype(dt)) \
        + lp["bk"].astype(dt)[None, :, None, :]
    v = jnp.einsum("bse,ehd->bhsd", h, lp["wv"].astype(dt)) \
        + lp["bv"].astype(dt)[None, :, None, :]
    return q, k, v


def attn_out_proj(lp, a, dt, reduce=None):
    """Row-parallel attention output projection: (B, H, S, D) -> (B, S, E).
    ``reduce``: applied to the partial product BEFORE the bias — the
    manual-TP psum hook (the bias must be added exactly once)."""
    out = jnp.einsum("bhsd,hde->bse", a, lp["wo"].astype(dt))
    if reduce is not None:
        out = reduce(out)
    return out + lp["bo"].astype(dt)


def gelu_mlp(lp, h, dt, constrain=None, reduce=None):
    """Position-wise GELU MLP; ``constrain`` optionally annotates the
    (B, S, mlp) intermediate with sharding; ``reduce`` is the manual-TP
    psum hook on the row-parallel output (pre-bias)."""
    m = jax.nn.gelu(jnp.einsum("bse,ef->bsf", h, lp["w1"].astype(dt))
                    + lp["b1"].astype(dt))
    if constrain is not None:
        m = constrain(m)
    out = jnp.einsum("bsf,fe->bse", m, lp["w2"].astype(dt))
    if reduce is not None:
        out = reduce(out)
    return out + lp["b2"].astype(dt)


@dataclasses.dataclass(frozen=True)
class BertMlm:
    cfg: BertConfig = BERT_BASE
    mesh: Optional[Any] = None            # when set, activations/attention are
    rules: Optional[dict] = None          # sharded per the rule table
    use_flash: bool = True                # Pallas flash kernel on TPU
    causal: bool = False                  # autoregressive mask everywhere
                                          # (models/gpt.py sets True) —
                                          # threaded through dense/ring/
                                          # Ulysses/flash alike

    # ---------------- init ----------------

    def init(self, rng):
        c = self.cfg
        k = iter(jax.random.split(rng, 16 + 16 * c.layers))
        params = {
            "tok_emb": _norm_init(next(k), (c.vocab_size, c.hidden)),
            "pos_emb": _norm_init(next(k), (c.max_positions, c.hidden)),
            "emb_ln": {"scale": jnp.ones((c.hidden,)),
                       "bias": jnp.zeros((c.hidden,))},
            "layers": [],
            "mlm": {
                "w": _norm_init(next(k), (c.hidden, c.hidden)),
                "b": jnp.zeros((c.hidden,)),
                "ln": {"scale": jnp.ones((c.hidden,)),
                       "bias": jnp.zeros((c.hidden,))},
                "out_b": jnp.zeros((c.vocab_size,)),
            },
        }
        for _ in range(c.layers):
            params["layers"].append(init_encoder_layer(k, c))
        return params

    def logical_axes(self):
        """Pytree (matching ``init``) of logical axis tuples for the rules."""
        ln = {"scale": ("embed",), "bias": ("embed",)}
        layer = {
            "wq": ("embed", "heads", "head_dim"),
            "wk": ("embed", "heads", "head_dim"),
            "wv": ("embed", "heads", "head_dim"),
            "bq": ("heads", "head_dim"), "bk": ("heads", "head_dim"),
            "bv": ("heads", "head_dim"),
            "wo": ("heads", "head_dim", "embed"), "bo": ("embed",),
            "ln1": ln,
            "w1": ("embed", "mlp"), "b1": ("mlp",),
            "w2": ("mlp", "embed"), "b2": ("embed",),
            "ln2": ln,
        }
        return {
            "tok_emb": ("vocab", "embed"),
            "pos_emb": ("pos", "embed"),
            "emb_ln": ln,
            "layers": [dict(layer) for _ in range(self.cfg.layers)],
            "mlm": {"w": ("embed", "embed"), "b": ("embed",), "ln": ln,
                    "out_b": ("vocab",)},
        }

    # ---------------- forward ----------------

    def _constrain(self, x, axes):
        if self.mesh is None:
            return x
        return rules_lib.constrain(x, axes, self.mesh, self.rules)

    def _attention(self, q, k, v):
        """q,k,v: (B, H, S, D).  Sequence-parallel attention (ring or
        Ulysses per ``cfg.sp_impl``) over the seq axis when the mesh shards
        it; otherwise the Pallas flash kernel on TPU for sequences at or
        above ``cfg.flash_min_seq``, XLA's fused dense attention below it
        (the measured winner at short/medium S — see flash_min_seq)."""
        on_tpu = jax.devices()[0].platform == "tpu"
        causal = self.causal
        # captured OUTSIDE shard_map: the threshold compares the FULL
        # sequence length, not a shard's slice of it
        S_full = q.shape[2]
        flash_ok = self.use_flash and on_tpu \
            and S_full >= self.cfg.flash_min_seq
        if self.mesh is not None and self.mesh.shape.get("seq", 1) > 1:
            specs = P("data" if self.mesh.shape.get("data", 1) > 1 else None,
                      "model" if self.mesh.shape.get("model", 1) > 1 else None,
                      "seq")

            def inner(q, k, v):
                if self.cfg.sp_impl == "ulysses":
                    from mpi_tensorflow_tpu.parallel import ulysses

                    inner_attn = None
                    if flash_ok:
                        # post-all-to-all each shard sees the FULL sequence
                        # for its head slice — S_full is the right length
                        # for the kernel threshold
                        from mpi_tensorflow_tpu.ops import \
                            flash_attention as fa

                        if fa.kernel_supported(jnp.dtype(q.dtype).name,
                                               causal):
                            def inner_attn(q, k, v, causal=False,
                                           scale=None):
                                return fa.flash_attention(q, k, v, causal,
                                                          scale)
                    engagement.record(
                        "attention", "ulysses+flash" if inner_attn is not None
                        else "ulysses+xla")
                    return ulysses.ulysses_attention(q, k, v, "seq",
                                                     causal=causal,
                                                     inner=inner_attn)
                engagement.record("attention", "ring")
                return ring.ring_attention(q, k, v, "seq", causal=causal)

            # check_vma=False: pallas_call (the flash inner) cannot declare
            # varying-mesh-axes metadata on its outputs
            return jax.shard_map(inner, mesh=self.mesh,
                                 in_specs=(specs, specs, specs),
                                 out_specs=specs, check_vma=False)(q, k, v)
        if flash_ok:
            # any S: the kernel pads/masks to the block size internally;
            # kernel_supported() guards against a Mosaic regression (falls
            # back to XLA attention instead of failing the train step)
            from mpi_tensorflow_tpu.ops import flash_attention as fa

            if fa.kernel_supported(jnp.dtype(q.dtype).name, causal):
                engagement.record("attention", "flash")
                return fa.flash_attention(q, k, v, causal)
        engagement.record("attention", "xla_dense")
        return ring.dense_attention(q, k, v, causal=causal)

    def _mlp_block(self, lp, h, idx: int):
        """Position-wise MLP for layer ``idx`` -> (out, aux_loss).  The
        dense column/row-parallel MLP; MoE (models/moe.py) overrides this
        with routed experts on its MoE layers."""
        m = gelu_mlp(lp, h, self.cfg.dtype,
                     constrain=lambda m: self._constrain(
                         m, ("batch", "seq", "mlp")))
        return m, jnp.zeros((), jnp.float32)

    def _aux_weight(self) -> float:
        """Weight of the auxiliary loss accumulated by ``_mlp_block`` (0 for
        the dense model; the MoE load-balance weight in models/moe.py)."""
        return 0.0

    def encode(self, params, tokens, *, train: bool = False, rng=None):
        """Embeddings + encoder stack.  ``tokens``: int ids (B, S).
        Returns hidden states (B, S, E) in the compute dtype."""
        return self._encode_aux(params, tokens, train=train, rng=rng)[0]

    def _encode_aux(self, params, tokens, *, train: bool = False, rng=None):
        """Encoder returning ``(hidden, summed aux loss)``."""
        c = self.cfg
        B, S = tokens.shape
        h = params["tok_emb"][tokens]
        if c.pos_kind != "rope":
            h = h + params["pos_emb"][None, :S]
        h = _layernorm(h, params["emb_ln"])
        if train and c.dropout > 0.0:
            if rng is None:
                raise ValueError("dropout needs an rng in train mode")
            h = dropout_mask(h, c.dropout, jax.random.fold_in(rng, 1))
        h = h.astype(c.dtype)
        h = self._constrain(h, ("batch", "seq", "embed"))
        # layer dropout streams continue from index 1 (the embedding site)
        return self._run_layers(params, h, train=train, rng=rng,
                                drop_start=1)

    def _run_layers(self, params, h, *, train: bool = False, rng=None,
                    drop_start: int = 0):
        """The encoder layer stack on an already-embedded ``h`` (B, S, E)
        in the compute dtype.  Shared by the token path above and the
        ViT patch path (models/vit.py).  ``drop_start``: first unused
        dropout stream index — layer sites fold rng on drop_start+1, ...
        (stable across a remat recomputation)."""
        import functools

        c = self.cfg
        dt = c.dtype
        drop_i = drop_start

        def drop_with(i, x):
            """Dropout keyed by an explicit stream index (stable across a
            remat recomputation)."""
            if not train or c.dropout == 0.0:
                return x
            if rng is None:
                raise ValueError("dropout needs an rng in train mode")
            return dropout_mask(x, c.dropout, jax.random.fold_in(rng, i))

        def layer(h, lp, keys, mlp_fn):
            # --- attention (column-parallel QKV, row-parallel out) ---
            q, k, v = qkv_proj(lp, h, dt, fused=c.fused_qkv)
            if c.pos_kind == "rope":
                # before the attention dispatch AND before shard_map, so
                # every impl (dense/flash/ring/Ulysses) sees rotated q/k
                pos = jnp.arange(q.shape[2])
                q, k = rope(q, pos), rope(k, pos)
            q = self._constrain(q, ("batch", "heads", "seq", "head_dim"))
            k = self._constrain(k, ("batch", "heads", "seq", "head_dim"))
            v = self._constrain(v, ("batch", "heads", "seq", "head_dim"))
            a = self._attention(q, k, v)
            a = attn_out_proj(lp, a, dt)
            h = _layernorm(h + drop_with(keys[0], a), lp["ln1"]).astype(dt)
            h = self._constrain(h, ("batch", "seq", "embed"))
            # --- MLP (dense column/row parallel, or routed experts) ---
            m, aux = mlp_fn(lp, h)
            h = _layernorm(h + drop_with(keys[1], m), lp["ln2"]).astype(dt)
            return self._constrain(h, ("batch", "seq", "embed")), aux

        if c.remat:
            # trade FLOPs for HBM: drop each layer's activations after the
            # forward pass and recompute them during the backward pass —
            # peak activation memory goes from O(layers) to O(1) residuals
            # (plus saved dot outputs under the "dots" policy)
            layer = jax.checkpoint(layer, static_argnums=(3,),
                                   policy=remat_policy_fn(c))
        aux_total = jnp.zeros((), jnp.float32)
        for i, lp in enumerate(params["layers"]):
            # dropout keys derived OUTSIDE the (possibly rematted) layer so
            # the recomputation replays identical masks
            drop_i += 2
            h, aux = layer(h, lp, (drop_i - 1, drop_i),
                           functools.partial(self._mlp_block, idx=i))
            aux_total = aux_total + aux
        return h, aux_total

    def head_hidden(self, params, h):
        """MLM head transform (dense + GELU + LN) — the (B, S, E) input to
        the tied vocab decoder."""
        dt = self.cfg.dtype
        t = jax.nn.gelu(h @ params["mlm"]["w"].astype(dt)
                        + params["mlm"]["b"].astype(dt))
        return _layernorm(t, params["mlm"]["ln"]).astype(dt)

    def apply(self, params, batch, *, train: bool = False, rng=None):
        """``batch``: int token ids (B, S) (already masked for MLM).
        Returns vocab logits (B, S, V)."""
        dt = self.cfg.dtype
        h = self.encode(params, batch, train=train, rng=rng)
        t = self.head_hidden(params, h)
        logits = jnp.einsum("bse,ve->bsv", t, params["tok_emb"].astype(dt)) \
            + params["mlm"]["out_b"]
        logits = self._constrain(logits, ("batch", "seq", "vocab"))
        return logits.astype(jnp.float32)

    # ---------------- loss ----------------

    def _packs_positions(self) -> bool:
        """Whether the loss packs masked positions before the head (the MLM
        families).  The causal family computes CE at every position and
        overrides this to False."""
        return self.cfg.ce_positions == "masked"

    def _use_chunked_ce(self) -> bool:
        if self.cfg.ce_impl == "dense":
            return False
        if self.cfg.ce_impl == "chunked":
            return True
        # auto: with masked-position packing the logits are (B, S/4, V) —
        # small enough that XLA's dense path wins; chunking is the rescue
        # for full-position logits, unless the vocab axis is TP-sharded
        # (then dense logits are already sharded V/tp per device and GSPMD
        # places the logsumexp collectives)
        if self._packs_positions():
            return False
        return self.mesh is None or self.mesh.shape.get("model", 1) == 1

    def _ce(self, params, t, labels):
        """Per-position CE (B, S) fp32 from head hidden ``t``."""
        dt = self.cfg.dtype
        if self._use_chunked_ce():
            from mpi_tensorflow_tpu.ops import mlm_head

            engagement.record("ce", f"chunked:{self.cfg.ce_chunk}")
            return mlm_head.tied_softmax_ce(
                t, params["tok_emb"], params["mlm"]["out_b"], labels,
                chunk=self.cfg.ce_chunk)
        engagement.record("ce", "dense")
        logits = jnp.einsum("bse,ve->bsv", t, params["tok_emb"].astype(dt)) \
            + params["mlm"]["out_b"]
        logits = self._constrain(
            logits, ("batch", "seq", "vocab")).astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        return logz - gold

    def loss(self, params, model_state, batch, labels, *, rng=None,
             train: bool = False):
        """Masked-LM loss: mean CE over masked positions only.

        ``batch``: dict with ``tokens`` (B,S) int32 (mask token substituted)
        and ``mask`` (B,S) bool; ``labels``: (B,S) int32 original ids.
        """
        h, aux = self._encode_aux(params, batch["tokens"], train=train,
                                  rng=rng)
        mask = batch["mask"]
        if self.cfg.ce_positions == "masked":
            from mpi_tensorflow_tpu.ops import mlm_head

            engagement.record("ce_positions", "masked_packed")
            packed, plabels, w = mlm_head.gather_masked_rows(
                h, labels, mask.astype(jnp.bool_),
                ce_capacity(self.cfg, h.shape[1]))
            t = self.head_hidden(params, packed)
            ce = self._ce(params, t, plabels)
            weights = w
        else:
            engagement.record("ce_positions", "all")
            t = self.head_hidden(params, h)
            ce = self._ce(params, t, labels)
            weights = mask.astype(jnp.float32)
        # denominator = ALL masked positions (overflow-dropped ones count),
        # so the two ce_positions modes agree exactly when nothing overflows
        loss = jnp.sum(ce * weights) \
            / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
        return loss + self._aux_weight() * aux, model_state

    def l2_params(self, params) -> list:
        return []   # transformer runs use decoupled weight decay (adamw)
