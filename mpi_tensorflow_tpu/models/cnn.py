"""The reference MNIST CNN, re-built as a pure-JAX function.

Architecture (mpipy.py:38-53, 155-167):
  [conv 5x5 SAME -> bias -> relu -> maxpool 2x2 SAME] x2 (32 then 64 channels)
  -> flatten (NHWC row-major, matching TF's reshape at mpipy.py:163)
  -> fc 512 + relu -> dropout 0.5 (train only; the reference applies dropout
     in eval too — deliberate fix, see models/base.py) -> fc num_classes.

Init (mpipy.py:38-53): weights truncated-normal stddev 0.1 (TF
``truncated_normal``: resample outside 2 sigma); biases: conv1 zeros, the rest
constant 0.1.  The reference reuses seed 1 for every weight — giving conv1 and
conv2 *correlated* values; we derive per-parameter keys from one seed instead
(documented divergence, statistically equivalent init scale).

TPU notes: convolutions run NHWC through ``lax.conv_general_dilated`` (XLA
lowers to MXU); arithmetic is float32 by default with optional bfloat16
compute (``compute_dtype``) for MXU throughput, keeping params in float32.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


def truncated_normal(key, shape, stddev=0.1, dtype=jnp.float32):
    """TF ``tf.truncated_normal`` semantics: N(0,1) truncated to [-2, 2],
    scaled by ``stddev`` (init at mpipy.py:38-53)."""
    return jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype) * stddev


def max_pool_2x2_same(x):
    """``tf.nn.max_pool`` ksize 2x2 stride 2 padding SAME (mpipy.py:158, 161)."""
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 2, 2, 1),
        window_strides=(1, 2, 2, 1),
        padding="SAME",
    )


def conv2d_same(x, w):
    """``tf.nn.conv2d`` stride 1 padding SAME, NHWC/HWIO (mpipy.py:156, 159)."""
    return lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


@dataclasses.dataclass(frozen=True)
class MnistCnn:
    image_size: int = 28
    num_channels: int = 1
    num_classes: int = 10
    hidden: int = 512
    dropout_rate: float = 0.5
    compute_dtype: Any = jnp.float32

    @property
    def flat_dim(self) -> int:
        # image_size//4 * image_size//4 * 64 (mpipy.py:46)
        return (self.image_size // 4) ** 2 * 64

    def init(self, rng):
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        s, c = self.image_size, self.num_channels
        return {
            "conv1_w": truncated_normal(k1, (5, 5, c, 32)),
            "conv1_b": jnp.zeros((32,)),                       # mpipy.py:41
            "conv2_w": truncated_normal(k2, (5, 5, 32, 64)),
            "conv2_b": jnp.full((64,), 0.1),                   # mpipy.py:45
            "fc1_w": truncated_normal(k3, (self.flat_dim, self.hidden)),
            "fc1_b": jnp.full((self.hidden,), 0.1),            # mpipy.py:49
            "fc2_w": truncated_normal(k4, (self.hidden, self.num_classes)),
            "fc2_b": jnp.full((self.num_classes,), 0.1),       # mpipy.py:53
        }

    def apply(self, params, inputs, *, train: bool = False, rng=None):
        dt = self.compute_dtype
        x = inputs.astype(dt)
        x = jax.nn.relu(conv2d_same(x, params["conv1_w"].astype(dt))
                        + params["conv1_b"].astype(dt))
        x = max_pool_2x2_same(x)
        x = jax.nn.relu(conv2d_same(x, params["conv2_w"].astype(dt))
                        + params["conv2_b"].astype(dt))
        x = max_pool_2x2_same(x)
        x = x.reshape(x.shape[0], -1)  # NHWC row-major flatten (mpipy.py:163)
        x = jax.nn.relu(x @ params["fc1_w"].astype(dt) + params["fc1_b"].astype(dt))
        if train:
            if rng is None:
                raise ValueError("dropout needs an rng in train mode")
            keep = 1.0 - self.dropout_rate
            mask = jax.random.bernoulli(rng, keep, x.shape)
            x = jnp.where(mask, x / keep, 0.0)  # tf.nn.dropout scaling (mpipy.py:166)
        logits = x @ params["fc2_w"].astype(dt) + params["fc2_b"].astype(dt)
        return logits.astype(jnp.float32)

    def l2_params(self, params) -> list:
        # fc weights AND biases only (mpipy.py:57-58)
        return [params["fc1_w"], params["fc1_b"],
                params["fc2_w"], params["fc2_b"]]
