"""ResNet family — BASELINE.json scale-out configs 3 and 4.

The reference has exactly one model (the 2-conv MNIST CNN, mpipy.py:155-167);
BASELINE.json directs scaling the *identical* train loop to CIFAR-10
ResNet-20 and ImageNet ResNet-50 — "same train-step/loop, bigger models
(stressing allreduce payload)" (SURVEY.md §7 capability 6).  These models
plug into the framework's ``Model`` protocol unchanged: the loop and step
code do not know which model they run.

Variants:
- ``resnet20``: the CIFAR ResNet (He et al. 2016, section 4.2): 3x3 stem,
  3 stages x 3 basic blocks, widths 16/32/64, identity shortcuts with
  stride-2 projections.
- ``resnet50``: ImageNet bottleneck ResNet: 7x7/2 stem + 3x3/2 maxpool,
  stages [3, 4, 6, 3] of bottleneck blocks, widths 256/512/1024/2048.

TPU notes: NHWC throughout; He-normal init; BN running stats in
``model_state`` (averaged across data shards by the train step); weight
decay applies to conv/fc weights (standard ResNet practice) via
``l2_params``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from mpi_tensorflow_tpu.ops import nn


def _he_normal(key, shape):
    """He/Kaiming normal for relu nets: std = sqrt(2 / fan_in)."""
    fan_in = int(jnp.prod(jnp.asarray(shape[:-1])))
    return jax.random.normal(key, shape) * jnp.sqrt(2.0 / fan_in)


@dataclasses.dataclass(frozen=True)
class ResNet:
    stage_sizes: Sequence[int]
    widths: Sequence[int]
    bottleneck: bool
    num_classes: int = 10
    cifar_stem: bool = True          # 3x3/1 stem (CIFAR) vs 7x7/2 + pool
    bn_momentum: float = 0.9
    compute_dtype: Any = jnp.float32  # bf16: convs/matmuls on the MXU in
                                      # bfloat16; BN statistics, params and
                                      # logits stay float32
    remat: bool = False               # jax.checkpoint each residual block:
                                      # recompute block activations in the
                                      # backward pass, freeing HBM for
                                      # larger batches (MFU lever for
                                      # ResNet-50 at batch >= 128)

    def _conv(self, x, w, stride: int = 1):
        dt = self.compute_dtype
        return nn.conv2d(x.astype(dt), w.astype(dt), stride=stride)

    # ---- init ----

    def init(self, rng):
        keys = iter(jax.random.split(rng, 4096))
        params = {"stem": {"w": _he_normal(next(keys), self._stem_shape()),
                           "bn": nn.bn_init(self._stem_width())}}
        in_w = self._stem_width()
        stages = []
        for s, (n_blocks, width) in enumerate(zip(self.stage_sizes, self.widths)):
            blocks = []
            for b in range(n_blocks):
                stride = 2 if (b == 0 and s > 0) else 1
                blocks.append(self._block_init(keys, in_w, width, stride))
                in_w = width
            stages.append(blocks)
        params["stages"] = stages
        params["fc"] = {
            "w": jax.random.normal(next(keys), (in_w, self.num_classes)) * 0.01,
            "b": jnp.zeros((self.num_classes,)),
        }
        return params

    def init_state(self):
        state = {"stem": nn.bn_state_init(self._stem_width())}
        in_w = self._stem_width()
        stages = []
        for s, (n_blocks, width) in enumerate(zip(self.stage_sizes, self.widths)):
            blocks = []
            for b in range(n_blocks):
                stride = 2 if (b == 0 and s > 0) else 1
                blocks.append(self._block_state(in_w, width, stride))
                in_w = width
            stages.append(blocks)
        state["stages"] = stages
        return state

    def _stem_shape(self):
        return (3, 3, 3, 16) if self.cifar_stem else (7, 7, 3, 64)

    def _stem_width(self):
        return 16 if self.cifar_stem else 64

    def _mid(self, width):
        return width // 4 if self.bottleneck else width

    def _block_init(self, keys, in_w, width, stride):
        mid = self._mid(width)
        if self.bottleneck:
            p = {
                "conv1": _he_normal(next(keys), (1, 1, in_w, mid)),
                "bn1": nn.bn_init(mid),
                "conv2": _he_normal(next(keys), (3, 3, mid, mid)),
                "bn2": nn.bn_init(mid),
                "conv3": _he_normal(next(keys), (1, 1, mid, width)),
                "bn3": nn.bn_init(width),
            }
        else:
            p = {
                "conv1": _he_normal(next(keys), (3, 3, in_w, mid)),
                "bn1": nn.bn_init(mid),
                "conv2": _he_normal(next(keys), (3, 3, mid, width)),
                "bn2": nn.bn_init(width),
            }
        if stride != 1 or in_w != width:
            p["proj"] = _he_normal(next(keys), (1, 1, in_w, width))
            p["bn_proj"] = nn.bn_init(width)
        return p

    def _block_state(self, in_w, width, stride):
        mid = self._mid(width)
        if self.bottleneck:
            s = {"bn1": nn.bn_state_init(mid), "bn2": nn.bn_state_init(mid),
                 "bn3": nn.bn_state_init(width)}
        else:
            s = {"bn1": nn.bn_state_init(mid), "bn2": nn.bn_state_init(width)}
        if stride != 1 or in_w != width:
            s["bn_proj"] = nn.bn_state_init(width)
        return s

    # ---- forward ----

    def apply_with_state(self, params, state, x, *, train: bool = False,
                         rng=None):
        mom = self.bn_momentum
        new_state = {"stages": []}
        stride = 1 if self.cifar_stem else 2
        h = self._conv(x, params["stem"]["w"], stride=stride)
        h, new_state["stem"] = nn.batch_norm(
            h, params["stem"]["bn"], state["stem"], train=train, momentum=mom)
        h = jax.nn.relu(h)
        if not self.cifar_stem:
            h = nn.max_pool(h, window=3, stride=2)

        block_apply = self._block_apply
        if self.remat:
            # static args (stride/train/mom) via static_argnums so the
            # checkpointed trace keeps python-level branching
            block_apply = jax.checkpoint(self._block_apply,
                                         static_argnums=(3, 4, 5))
        for s, blocks in enumerate(params["stages"]):
            st_out = []
            for b, bp in enumerate(blocks):
                stride = 2 if (b == 0 and s > 0) else 1
                h, bs = block_apply(bp, state["stages"][s][b], h,
                                    stride, train, mom)
                st_out.append(bs)
            new_state["stages"].append(st_out)

        h = nn.global_avg_pool(h)
        dt = self.compute_dtype
        logits = (h.astype(dt) @ params["fc"]["w"].astype(dt)).astype(
            jnp.float32) + params["fc"]["b"]
        return logits, new_state

    def _block_apply(self, p, s, x, stride, train, mom):
        ns = {}
        shortcut = x
        if "proj" in p:
            shortcut = self._conv(x, p["proj"], stride=stride)
            shortcut, ns["bn_proj"] = nn.batch_norm(
                shortcut, p["bn_proj"], s["bn_proj"], train=train, momentum=mom)
        if self.bottleneck:
            h = self._conv(x, p["conv1"], stride=1)
            h, ns["bn1"] = nn.batch_norm(h, p["bn1"], s["bn1"], train=train,
                                         momentum=mom)
            h = jax.nn.relu(h)
            h = self._conv(h, p["conv2"], stride=stride)
            h, ns["bn2"] = nn.batch_norm(h, p["bn2"], s["bn2"], train=train,
                                         momentum=mom)
            h = jax.nn.relu(h)
            h = self._conv(h, p["conv3"], stride=1)
            h, ns["bn3"] = nn.batch_norm(h, p["bn3"], s["bn3"], train=train,
                                         momentum=mom)
        else:
            h = self._conv(x, p["conv1"], stride=stride)
            h, ns["bn1"] = nn.batch_norm(h, p["bn1"], s["bn1"], train=train,
                                         momentum=mom)
            h = jax.nn.relu(h)
            h = self._conv(h, p["conv2"], stride=1)
            h, ns["bn2"] = nn.batch_norm(h, p["bn2"], s["bn2"], train=train,
                                         momentum=mom)
        return jax.nn.relu(h + shortcut), ns

    # ---- regularization ----

    def l2_params(self, params) -> list:
        """Conv + fc weights (not BN scales/offsets) — standard ResNet WD."""
        out = [params["stem"]["w"], params["fc"]["w"]]
        for blocks in params["stages"]:
            for p in blocks:
                out.extend(v for k, v in p.items()
                           if k.startswith("conv") or k == "proj")
        return out


def build(name: str, num_classes: int | None = None,
          compute_dtype: Any = jnp.float32, remat: bool = False) -> ResNet:
    if name == "resnet20":
        return ResNet(stage_sizes=(3, 3, 3), widths=(16, 32, 64),
                      bottleneck=False, num_classes=num_classes or 10,
                      cifar_stem=True, compute_dtype=compute_dtype,
                      remat=remat)
    if name == "resnet50":
        return ResNet(stage_sizes=(3, 4, 6, 3),
                      widths=(256, 512, 1024, 2048), bottleneck=True,
                      num_classes=num_classes or 1000, cifar_stem=False,
                      compute_dtype=compute_dtype, remat=remat)
    raise ValueError(f"unknown resnet variant {name!r}")
