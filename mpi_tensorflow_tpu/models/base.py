"""Model protocol shared by every model family.

The reference hard-wires its single model into the trainer (graph built inside
``Cnn.__init__``, mpipy.py:24-71).  Here a model is a small stateless object
and the train step is model-agnostic — swapping MNIST-CNN for ResNet-50 or
BERT changes only which ``Model`` is constructed (SURVEY.md §7 build order #7:
"the proof the design is a framework, not a script").

Contract:
- ``init(rng) -> params``: a pytree of ``jnp`` arrays.
- ``apply(params, inputs, *, train, rng=None) -> logits``: pure forward.
  ``train`` gates dropout; ``rng`` is required iff the model uses dropout and
  ``train`` is True.  (This deliberately fixes the reference's eval-dropout
  bug — mpipy.py:68 reuses the dropout-bearing ``model()`` for eval.)
- ``l2_params(params) -> list``: the sub-set of parameters subject to L2
  regularization (the reference penalizes fc weights AND biases only,
  mpipy.py:57-58).
- ``logical_axes(params) -> pytree of PartitionSpec-like tuples`` (optional):
  logical sharding axes per parameter, consumed by ``parallel.sharding_rules``.
"""

from __future__ import annotations

from typing import Any, Protocol, runtime_checkable

Params = Any


@runtime_checkable
class Model(Protocol):
    num_classes: int

    def init(self, rng) -> Params: ...

    def apply(self, params: Params, inputs, *, train: bool = False,
              rng=None) -> Any: ...

    def l2_params(self, params: Params) -> list: ...


def l2_loss(x) -> Any:
    """``tf.nn.l2_loss`` semantics: ``sum(x**2) / 2`` (used at mpipy.py:57-58)."""
    import jax.numpy as jnp

    return jnp.sum(jnp.square(x)) / 2.0


def init_model_state(model) -> Any:
    """Mutable (non-trained) model state — e.g. BatchNorm running statistics.

    Stateless models (the reference CNN, BERT) return ``{}``; models that
    track statistics define ``init_state()``.
    """
    if hasattr(model, "init_state"):
        return model.init_state()
    return {}


def run_model(model, params, model_state, inputs, *, train: bool,
              rng=None):
    """Uniform forward entry: returns ``(outputs, new_model_state)`` whether
    or not the model carries state."""
    if hasattr(model, "apply_with_state"):
        return model.apply_with_state(params, model_state, inputs,
                                      train=train, rng=rng)
    return model.apply(params, inputs, train=train, rng=rng), model_state
