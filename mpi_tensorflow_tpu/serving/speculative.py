"""Speculative decoding drafters: propose k tokens, verify in ONE forward.

PR 3's roofline block showed the decode hot path is BANDWIDTH-bound:
per emitted token the engine streams every live KV block past the MXU
once, and the matmuls on one query token nowhere near cover the read.
Speculative decoding (Leviathan et al., arXiv:2211.17192; Chen et al.,
arXiv:2302.01318) converts that idle compute into throughput: a cheap
DRAFTER proposes ``k`` tokens, the target model verifies all of them in
one batched forward (the chunked-prefill machinery already computes
logits at every position of a multi-token dispatch for free), and the
engine accepts the longest prefix whose greedy argmax chain matches the
draft — then emits the model's OWN token at the first mismatch.  Under
greedy decode the accepted stream is therefore token-identical to
vanilla one-token decoding BY CONSTRUCTION: every emitted token is an
argmax of target-model logits over exactly the context vanilla decode
would have used.  One KV-streaming pass is amortized over up to ``k+1``
emitted tokens; the engine-side accounting reports the win as
``accept_rate`` / ``mean_accepted_len`` / ``steps_saved``.

Two drafter backends behind one protocol (``--serve-speculative``):

- ``NgramDrafter``   — n-gram SELF-draft: match the sequence's current
                       suffix against its own earlier prompt+generated
                       tokens and propose the continuation that followed
                       last time.  Zero extra model, zero device state;
                       strong on the templated / shared-prefix / looping
                       traffic the radix prefix cache already targets.
- ``DraftModelDrafter`` — a tiny ``CausalLm`` (BERT_TINY geometry by
                       default) running ahead of the target through its
                       OWN small paged pool, reusing the same bucketed
                       forward_paged dispatch discipline as the engine
                       (pow2 chunk buckets, fixed table width, zero
                       steady-state recompiles).

Both are HOST-side policy objects: the engine asks ``draft(rid, ctx,
k)`` for up to ``k`` proposals, reports lifecycle with ``release(rid)``
(request terminal) and ``reset()`` (engine pools rebuilt), and audits
``check_quiescent()`` at end of run.  A drafter may always return fewer
than ``k`` tokens — or none, in which case the verify dispatch
degenerates to an exact one-token decode step for that row, so a cold
or unlucky drafter can never change emitted tokens, only the speedup.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

from mpi_tensorflow_tpu.serving.paged_cache import (BlockAllocator,
                                                    blocks_for, init_pools)


class Drafter:
    """The drafter protocol (default = stateless no-op lifecycle).

    ``draft(rid, ctx, k)`` returns UP TO ``k`` proposed continuation
    tokens for request ``rid`` whose verified context (prompt + all
    accepted tokens, INCLUDING the still-pending one) is ``ctx``.
    Proposals are hints, never promises: the engine verifies every one
    through the target model and discards the rejected tail, so a
    drafter cannot affect correctness — only the accept rate.
    """

    def draft(self, rid: int, ctx: List[int], k: int) -> List[int]:
        raise NotImplementedError

    def release(self, rid: int) -> None:
        """Request ``rid`` left the engine (any terminal status)."""

    def reset(self) -> None:
        """The engine rebuilt its pools (reset / crash recovery)."""

    def check_quiescent(self) -> None:
        """End-of-run leak audit (pairs with Scheduler.check_quiescent)."""

    def compile_counts(self) -> Dict[str, object]:
        """Jit-cache entry counts for the drafter's own dispatches,
        merged into ``engine.compile_counts()`` — any drafter that jits
        device work must report it here or its recompiles escape the
        zero-recompile probe.  Host-only drafters report nothing."""
        return {}


class NgramDrafter(Drafter):
    """Suffix-match self-draft: propose the continuation that followed
    the current suffix the LAST time it occurred in this sequence's own
    prompt+generated stream.

    For n from ``max_ngram`` down to ``min_ngram``: find the most recent
    earlier occurrence of the context's final n-gram and propose the
    tokens that followed it.  Occurrences with a full ``k``-token
    continuation window are preferred (a repeating template yields the
    whole window); otherwise the longest partial continuation wins.
    Repetitive streams — templated answers, copy-from-prompt spans, the
    token loops small greedy models fall into — hit at high rates;
    novel text simply returns no draft and costs one ordinary decode.

    Linear scan per call (O(len(ctx) * max_ngram)): context is bounded
    by ``max_seq_len`` and the scan is host-side python, far from the
    device dispatch critical path at test scale.  A production port
    would keep a rolling hash index per sequence.
    """

    def __init__(self, max_ngram: int = 4, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(f"need 1 <= min_ngram <= max_ngram, got "
                             f"[{min_ngram}, {max_ngram}]")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def draft(self, rid: int, ctx: List[int], k: int) -> List[int]:
        L = len(ctx)
        if k < 1 or L < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            suffix = ctx[L - n:]
            best: List[int] = []
            for i in range(L - n - 1, -1, -1):
                if ctx[i:i + n] != suffix:
                    continue
                cont = ctx[i + n:i + n + k]
                if len(cont) == k:
                    return cont          # most recent FULL window
                if len(cont) > len(best):
                    best = cont
            if best:
                return best
        return []


@dataclasses.dataclass
class _DraftState:
    """One request's footprint in the draft pool: its block table and
    how many VERIFIED context tokens have KV in it.  Drafted tokens'
    KV is written during drafting but never counted as cached —
    ``cached`` only ever covers tokens the target model accepted, so
    the next sync pass overwrites any stale speculative entries."""
    blocks: List[int] = dataclasses.field(default_factory=list)
    cached: int = 0
    last_used: int = 0


class DraftModelDrafter(Drafter):
    """Tiny-model drafter over its own paged KV pool.

    The draft model runs the SAME ``forward_paged`` path as the target
    engine, against a private pool sized for the same contexts: per
    call it syncs the unseen context tokens through pow2-bucketed
    chunk dispatches (the engine's prefill discipline — at most
    ``log2(chunk)+1`` compiled shapes, fixed full-width table), then
    autoregressively extends ``k`` tokens taking the argmax each step.
    Because context prefixes never change for a request id (greedy
    decode is deterministic, and an evicted request regenerates the
    exact same stream), cached draft KV stays valid across calls and
    even across target-engine evictions — the sync pass only ever
    appends or overwrites stale speculative positions.

    Pool pressure: when the draft pool cannot cover ``ctx + k``, other
    requests' draft state is dropped LRU-first (their KV is a pure
    cache — dropping it costs a re-sync, never correctness), and ``k``
    shrinks to whatever coverage remains.  A request's state is
    released the moment the engine reports it terminal.
    """

    def __init__(self, model, params, *, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int, chunk: int = 16,
                 kernel: str = "xla", kv_dtype: str = "fp32",
                 kv_group: int = 32):
        import jax

        if chunk < 1:
            raise ValueError(f"draft chunk must be >= 1, got {chunk}")
        self.model = model
        self.params = params
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.chunk = chunk
        self.kernel = kernel
        # the draft pool inherits the fleet kv_dtype (the PR 12 marked
        # extension): with the target pool quantized, an fp32 shadow
        # pool would dominate the drafter's HBM footprint.  Draft
        # tokens are verified by the target model before emission, so
        # draft-side quantization can only change WHICH tokens get
        # drafted, never correctness
        self.kv_dtype = kv_dtype
        self.kv_group = kv_group
        donate = (1,) if jax.default_backend() == "tpu" else ()
        self._feed_fn = jax.jit(self._feed_impl, donate_argnums=donate)
        self._clock = 0
        self.reset()

    def reset(self) -> None:
        self.pools = init_pools(self.model.cfg, self.num_blocks,
                                self.block_size, self.kv_dtype,
                                self.kv_group)
        self.allocator = BlockAllocator(self.num_blocks)
        self._state: Dict[int, _DraftState] = {}

    # ---------------- jitted feed ----------------

    def _feed_impl(self, params, pools, tokens, length, n_real, tables):
        """One (1, chunk-bucket) dispatch through the draft model: write
        the chunk's KV, return the greedy token after the last REAL
        lane — the engine's ``_prefill_impl`` shape discipline, reused
        for both the context sync and each 1-token draft extension."""
        import jax.numpy as jnp

        S = tokens.shape[1]
        valid = jnp.arange(S)[None] < n_real
        logits, pools = self.model.forward_paged(
            params, tokens, pools, tables, length[None], valid=valid,
            kernel=self.kernel)
        nxt = jnp.argmax(logits[0, jnp.maximum(n_real - 1, 0)], axis=-1)
        return nxt.astype(jnp.int32), pools

    def warmup(self) -> None:
        """Pre-pay every chunk-bucket compile with all-null-table
        dispatches (n_real=0: every lane scatters into the null block,
        the returned token is discarded) so a draft inside a timed
        steady-state window can never register as a recompile."""
        import jax.numpy as jnp
        import numpy as np

        tables = jnp.zeros((1, self.max_blocks_per_seq), jnp.int32)
        c = 1
        while True:
            self._feed(np.zeros((c,), np.int32), 0, 0, tables, bucket=c)
            if c >= self.chunk:
                break
            c *= 2

    def _feed(self, toks, length: int, n_real: int, tables, *,
              bucket: int):
        import jax.numpy as jnp
        import numpy as np

        buf = np.zeros((1, bucket), np.int32)
        buf[0, :len(toks)] = toks
        nxt, self.pools = self._feed_fn(
            self.params, self.pools, jnp.asarray(buf),
            jnp.asarray(length, jnp.int32),
            jnp.asarray(n_real, jnp.int32), tables)
        return int(nxt)

    # ---------------- pool management ----------------

    def _evict_lru(self, protect: int) -> bool:
        """Drop the least-recently-used OTHER request's draft state —
        pure cache, so the only cost is that request's next re-sync."""
        victims = [(st.last_used, rid) for rid, st in self._state.items()
                   if rid != protect and st.blocks]
        if not victims:
            return False
        _, rid = min(victims)
        self.release(rid)
        return True

    def release(self, rid: int) -> None:
        st = self._state.pop(rid, None)
        if st is not None and st.blocks:
            self.allocator.release(st.blocks)

    def check_quiescent(self) -> None:
        assert self.allocator.num_used == 0, (
            f"draft pool leak: {self.allocator.num_used} blocks still "
            f"referenced after every request terminated")
        self.allocator.check()

    def compile_counts(self) -> Dict[str, object]:
        try:
            return {"draft": int(self._feed_fn._cache_size())}
        except Exception:
            return {"draft": None}

    # ---------------- the draft call ----------------

    def draft(self, rid: int, ctx: List[int], k: int) -> List[int]:
        import jax.numpy as jnp
        import numpy as np

        st = self._state.setdefault(rid, _DraftState())
        self._clock += 1
        st.last_used = self._clock
        if st.cached >= len(ctx):
            # the target restarted this request (eviction replay): the
            # regenerated stream is identical (greedy determinism), so
            # the cached prefix stays valid — just re-feed the tail to
            # recover the logits cursor
            st.cached = len(ctx) - 1
        # never draft past the table capacity the pool can address
        k = min(k, self.max_blocks_per_seq * self.block_size - len(ctx))
        if k < 1:
            return []
        need = blocks_for(len(ctx) + k, self.block_size)
        while len(st.blocks) < need:
            # a successful LRU eviction always frees at least one block
            # (draft blocks are never shared), so one retry suffices
            if not self.allocator.can_alloc(1) and not self._evict_lru(rid):
                break
            st.blocks.extend(self.allocator.alloc(1))
        k = min(k, len(st.blocks) * self.block_size - len(ctx))
        if k < 1:
            return []
        tables = np.zeros((1, self.max_blocks_per_seq), np.int32)
        tables[0, :len(st.blocks)] = st.blocks
        tables = jnp.asarray(tables)
        # sync the unseen verified context through chunk buckets
        last = None
        pos = st.cached
        while pos < len(ctx):
            part = ctx[pos:pos + self.chunk]
            b = 1
            while b < len(part):
                b *= 2
            last = self._feed(part, pos, len(part), tables, bucket=b)
            pos += len(part)
        st.cached = len(ctx)
        # autoregressive extension: each drafted token is fed back at
        # the next position (its KV entry is speculative — ``cached``
        # stays at len(ctx), so the next sync overwrites it)
        out = [last]
        for i in range(k - 1):
            out.append(self._feed([out[-1]], len(ctx) + i, 1, tables,
                                  bucket=1))
        return out


def make_drafter(mode: str, serve, target_model, *, draft_model=None,
                 draft_params=None):
    """Build the drafter the ``--serve-speculative`` mode names.

    ``draft-model`` uses the supplied ``draft_model``/``draft_params``
    when given (the parity tests inject the TARGET model to pin the
    all-accept path); otherwise it builds a BERT_TINY-geometry
    ``CausalLm`` on the target's vocab with deterministically seeded
    fresh parameters — the zero-training stand-in that exercises the
    full draft/verify machinery until a distilled drafter checkpoint
    exists.  Rope positions so draft capacity never hits a learned
    position-table bound the target does not share.
    """
    if mode == "off":
        return None
    if mode == "ngram":
        return NgramDrafter()
    if mode != "draft-model":
        raise ValueError(
            f"speculative mode must be off|ngram|draft-model, got {mode!r}")
    if draft_model is None:
        import jax

        from mpi_tensorflow_tpu.models import bert as bert_lib
        from mpi_tensorflow_tpu.models import gpt as gpt_lib

        cfg = dataclasses.replace(
            bert_lib.BERT_TINY, vocab_size=target_model.cfg.vocab_size,
            dtype=target_model.cfg.dtype, pos_kind="rope",
            ce_positions="all", dropout=0.0)
        draft_model = gpt_lib.CausalLm(cfg)
        draft_params = draft_model.init(jax.random.key(7))
    elif draft_params is None:
        raise ValueError("draft_model given without draft_params")
    from mpi_tensorflow_tpu.ops import paged_attention as paged_ops

    return DraftModelDrafter(
        draft_model, draft_params,
        num_blocks=serve.num_blocks, block_size=serve.block_size,
        max_blocks_per_seq=serve.max_blocks_per_seq,
        chunk=min(16, serve.prefill_chunk),
        kernel=paged_ops.resolve_kernel(
            serve.kernel, draft_model.cfg, serve.block_size,
            min(16, serve.prefill_chunk), serve.kv_dtype,
            serve.kv_group),
        kv_dtype=serve.kv_dtype, kv_group=serve.kv_group)
