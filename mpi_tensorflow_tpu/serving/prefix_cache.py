"""Radix prefix cache: cross-request reuse of shared-prefix KV blocks.

Production serving traffic is dominated by shared prefixes — system
prompts, few-shot templates, multi-turn history.  The block table
already gives every sequence per-block indirection into one physical
pool (serving/paged_cache), which is exactly the machinery
PagedAttention (Kwon et al., arXiv:2309.06180) identifies as enabling
PHYSICAL block sharing; SGLang's RadixAttention (Zheng et al.,
arXiv:2312.07104) extends it to automatic cross-request prefix reuse
through a radix tree over token sequences.  This module is that tree at
BLOCK granularity:

- A trie node represents one FULL block of prompt tokens in context —
  its key is the block's token tuple, its path from the root is the
  whole prefix, and it pins one physical pool block holding that
  prefix's KV.  (Token-exact keys, so a hash collision can never alias
  two different prefixes to one block.)
- ``match_and_share`` walks a new prompt's full blocks down the trie
  and maps every hit to the EXISTING physical block (one ``share`` ref
  each) instead of recomputing it: prefill is charged only for the
  unique suffix, and pool occupancy drops by one block per hit.
- ``insert`` runs when a sequence finishes prefill: the trie adopts the
  sequence's full-prompt blocks it has not seen before (its own
  ``share`` ref per node), making them matchable by later requests.
  With generated-block caching on (--serve-prefix-gen, prefix v2) the
  scheduler ALSO inserts a finished sequence's full blocks spanning
  prompt + generated output, so a follow-up turn that embeds the prior
  answer maps those blocks instead of re-prefilling them
  (RadixAttention's generation-caching rule).  Either way only FULL,
  fully-written blocks enter the trie — a partial tail block that may
  still receive writes never does, so a cached block's content is
  immutable by construction and writes into shared blocks happen only
  on the engine's explicit copy-on-write path.
- ``match_partial`` (prefix v2) extends a full-block match into the
  tail: when the walk ends mid-block, the best-matching child's block
  donates its matched row prefix via the engine's one-compile
  partial-copy dispatch into the sequence's private tail block, so up
  to ``block_size - 1`` tokens per miss stop being recomputed.
- ``evict`` frees least-recently-used UNREFERENCED leaves (refcount 1:
  only the trie holds the block) under pool pressure, so sharing never
  starves admission.  Leaves only: an interior node's children encode
  prefixes that run THROUGH it, and evicting it would strand their
  references behind an unmatchable path.

Pure host Python, no jax import — the scheduler consumes it and the
unit tests exercise it without a device.  Determinism contract: a
matched block holds KV bit-identical to what re-prefilling those
positions would write (same tokens, same absolute positions, same
deterministic forward), so greedy decode with the cache on is
token-identical to cache-off (pinned by tests/test_serving.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from mpi_tensorflow_tpu.serving.paged_cache import BlockAllocator


class _Node:
    """One full token-block of prefix context pinning one pool block."""

    __slots__ = ("key", "block", "children", "parent", "last_used")

    def __init__(self, key: Tuple[int, ...], block: int,
                 parent: Optional["_Node"], last_used: int):
        self.key = key
        self.block = block
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.last_used = last_used


class PrefixCache:
    """Block-granularity radix trie over prompt prefixes.

    Refcount model: the trie holds exactly ONE allocator reference per
    node (taken at ``insert``, dropped at eviction); every sequence
    whose block table maps a cached block holds its own.  So
    ``refcount == 1`` means "trie only" — evictable; ``> 1`` means live
    sequences read it — protected.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.allocator = allocator
        self.block_size = block_size
        self._root = _Node((), 0, None, 0)
        self._clock = 0              # monotone LRU stamp source
        self.num_blocks = 0          # nodes == distinct pool blocks held
        self.inserted = 0            # nodes ever adopted
        self.evicted = 0             # nodes LRU-evicted
        # Observer for ROOT-child membership (leading full-block keys):
        # called as root_hook(key, True) when a first-block node is
        # adopted and root_hook(key, False) when one is evicted.  The
        # replica router's prefix-aware placement feeds its owner map
        # from this digest; None (the default) costs nothing.
        self.root_hook = None
        # Host-RAM block tier (--serve-kv-tier host): the engine wires
        # all three or none.  ``tier`` is a paged_cache.HostBlockStore;
        # ``demote_fetch(block) -> host leaves`` copies a pool block's
        # bytes to host (called just before eviction releases it);
        # ``promote_put(leaves, block)`` writes stored bytes into a
        # freshly allocated device block (called during match walks,
        # BEFORE the sequence's first dispatch).  Keys are full trie
        # token paths, so a promoted block is byte-identical to what
        # re-prefilling its positions would write — tier entries can
        # never go stale (same path => same bytes, the determinism
        # contract).  None (the default) keeps eviction pure-free.
        self.tier = None
        self.demote_fetch = None
        self.promote_put = None
        self.promoted = 0            # nodes re-admitted from the tier

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # ---------------- lookup ----------------

    def match_and_share(self, prompt: List[int]) -> Tuple[List[int], int]:
        """Longest cached block-prefix of ``prompt``: returns the
        physical block ids (one ``share`` reference taken on each — the
        caller owns them like freshly allocated blocks and must
        ``release`` on any failure path) and the number of prompt
        tokens they serve.

        The served-token count is capped at ``len(prompt) - 1``: the
        prefill must recompute at least the final prompt position to
        emit the first output token (its argmax IS the first generated
        token).  When every full block hits and the prompt length is an
        exact block multiple, that recompute lands INSIDE the last
        shared block — the engine's copy-on-write path detects the
        shared write and gives the sequence a private copy.
        """
        node, ids, path = self._root, [], []
        bs = self.block_size
        for j in range(len(prompt) // bs):
            key = tuple(prompt[j * bs:(j + 1) * bs])
            child = node.children.get(key)
            if child is None:
                break
            child.last_used = self._tick()
            ids.append(child.block)
            path.append(key)
            node = child
        self.allocator.share(ids)
        if self.tier is not None and self.promote_put is not None:
            node = self._promote_walk(node, prompt, ids, path)
        cached = len(ids) * bs
        if cached >= len(prompt):
            cached = len(prompt) - 1
        return ids, cached

    def _promote_walk(self, node: "_Node", prompt: List[int],
                      ids: List[int], path: List[Tuple[int, ...]]):
        """Extend a trie walk through the host tier: where the device
        trie ran out, demoted blocks whose token path continues the
        prompt are promoted back — a fresh device block is allocated,
        the host bytes land in it (``promote_put``, before the
        sequence's first dispatch), and a trie node is rebuilt in place.
        The re-admitted node takes the trie's own reference (the alloc)
        PLUS the sequence's share, exactly the accounting a normal hit
        leaves, so ``check``/quiescent invariants hold unchanged.
        ``ids``/``path`` are extended in place; promotion stops at the
        first tier miss, allocation failure, or prompt end."""
        bs = self.block_size
        for j in range(len(ids), len(prompt) // bs):
            key = tuple(prompt[j * bs:(j + 1) * bs])
            full = tuple(path) + (key,)
            # peek before pop: on allocation failure the entry must
            # survive for a later, less-pressured walk
            if full not in self.tier or not self.allocator.can_alloc(1):
                break
            bid = self.allocator.alloc(1)[0]        # the trie's own ref
            self.promote_put(self.tier.pop(full), bid)
            child = _Node(key, bid, node, self._tick())
            node.children[key] = child
            self.num_blocks += 1
            self.promoted += 1
            if node is self._root and self.root_hook is not None:
                self.root_hook(key, True)
            self.allocator.share([bid])             # the sequence's ref
            ids.append(bid)
            path.append(key)
            node = child
        return node

    def match_partial(self, prompt: List[int],
                      matched_blocks: int) -> Optional[Tuple[int, int]]:
        """Best mid-block extension of a full-block match: re-walks the
        trie to depth ``matched_blocks`` and, among that node's
        children, finds the block whose token key shares the longest
        ROW PREFIX with the prompt's tail.  Returns ``(block, rows)``
        with one ``share`` reference taken on ``block`` — the PIN that
        keeps trie eviction from freeing (and the allocator from
        recycling) the source before the engine's partial-copy dispatch
        reads it; the caller releases it after the copy.  None when no
        child shares at least one usable row.

        ``rows`` is capped at ``len(tail) - 1`` so the final prompt
        position always recomputes (the ``match_and_share`` rule: its
        argmax IS the first output token).  When the tail spans a full
        block a whole-key match is impossible here — the main walk
        would have taken it — so ``rows < block_size`` always holds and
        the copy never substitutes for a full-block share."""
        node, bs = self._root, self.block_size
        for j in range(matched_blocks):
            node = node.children.get(tuple(prompt[j * bs:(j + 1) * bs]))
            if node is None:          # concurrent eviction below a match
                return None
        tail = prompt[matched_blocks * bs:]
        limit = min(len(tail) - 1, bs)
        if limit <= 0:
            return None
        best, best_rows = None, 0
        for key, child in node.children.items():
            r = 0
            while r < limit and r < len(key) and key[r] == tail[r]:
                r += 1
            if r > best_rows:
                best, best_rows = child, r
        if best is None:
            return None
        best.last_used = self._tick()
        self.allocator.share([best.block])
        return best.block, best_rows

    # ---------------- registration ----------------

    def insert(self, prompt: List[int], block_ids: List[int]) -> int:
        """Register a FULLY PREFILLED prompt's full blocks; the trie
        adopts (one ``share`` ref) each block it has no node for yet.
        Blocks already cached keep their existing node — a sequence
        that recomputed a cached block privately (CoW, or an unaligned
        suffix) simply keeps its private copy.  Returns nodes added."""
        node, added = self._root, 0
        bs = self.block_size
        for j in range(len(prompt) // bs):
            key = tuple(prompt[j * bs:(j + 1) * bs])
            child = node.children.get(key)
            if child is None:
                self.allocator.share([block_ids[j]])
                child = _Node(key, block_ids[j], node, 0)
                node.children[key] = child
                self.num_blocks += 1
                self.inserted += 1
                added += 1
                if node is self._root and self.root_hook is not None:
                    self.root_hook(key, True)
            child.last_used = self._tick()
            node = child
        return added

    # ---------------- eviction ----------------

    def _path_key(self, node: "_Node") -> tuple:
        """Full trie token path of ``node`` (root -> node, one token
        tuple per block) — the host-tier key: token-exact, so a tier
        entry can only re-admit for the one prefix that produced it."""
        keys = []
        while node is not self._root:
            keys.append(node.key)
            node = node.parent
        return tuple(reversed(keys))

    def _leaves(self) -> List[_Node]:
        out, stack = [], list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        return out

    def evict(self, want_blocks: int) -> int:
        """Release up to ``want_blocks`` pool blocks by evicting
        least-recently-used UNREFERENCED leaves (allocator refcount 1).
        Evicting a leaf can expose its parent as the next candidate.
        Returns blocks actually freed — the caller falls back to
        sequence eviction for the remainder.  (Linear leaf scan per
        freed block: trie size is bounded by the pool, and eviction
        only runs under pool pressure.)"""
        freed = 0
        while freed < want_blocks:
            victims = [n for n in self._leaves()
                       if self.allocator.refcount(n.block) == 1]
            if not victims:
                break
            victim = min(victims, key=lambda n: n.last_used)
            assert not victim.children
            del victim.parent.children[victim.key]
            if self.tier is not None and self.demote_fetch is not None:
                # demote instead of discard: copy the block's bytes to
                # the host store under its full token path BEFORE the
                # release recycles the device block.  Children demote
                # before parents (leaves-only eviction), and promotion
                # walks parent-first, so chains round-trip intact.
                self.tier.put(self._path_key(victim),
                              self.demote_fetch(victim.block))
            self.allocator.release([victim.block])
            self.num_blocks -= 1
            self.evicted += 1
            freed += 1
            if victim.parent is self._root and self.root_hook is not None:
                self.root_hook(victim.key, False)
        return freed

    # ---------------- invariants / stats ----------------

    def check(self) -> None:
        """Every node pins a live, distinct pool block."""
        seen, stack = set(), list(self._root.children.values())
        while stack:
            n = stack.pop()
            assert self.allocator.refcount(n.block) >= 1, \
                f"trie node holds freed block {n.block}"
            assert n.block not in seen, \
                f"two trie nodes share physical block {n.block}"
            seen.add(n.block)
            stack.extend(n.children.values())
        assert len(seen) == self.num_blocks

    def stats(self) -> dict:
        return {"blocks": self.num_blocks, "inserted": self.inserted,
                "evicted": self.evicted, "promoted": self.promoted}
