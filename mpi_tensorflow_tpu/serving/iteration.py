"""The ONE per-iteration serving body engine.run and the replica router
share.

Before this module, ``ReplicaRouter.tick()`` MIRRORED the body of
``PagedDecodeEngine.run``'s loop (submit stamping, deadline sweep,
latency cadence, eviction sample-discard) without the guard/journal/
drain wiring — the ROADMAP item-1 drift hazard: two copies of the same
accounting that could only age apart, and a fleet whose replicas had
strictly weaker failure semantics than a single engine.  Now both
callers drive an ``EngineLoop`` per engine:

- ``submit``  stamps the default per-request TTL, journals the submit
  (with any replayed ``pre`` prefix), and runs admission — recording
  the latency-clock start only for accepted requests;
- ``iterate`` sweeps deadlines, steps the engine once, and does the
  emit/eviction accounting: a token's latency is the wall time since
  the SAME sequence's previous token (first token: since arrival,
  queueing included), and an eviction voids the samples delivered so
  far (they are regenerated; only the final delivered stream counts)
  while journaling the void so a replayed run forgets them too.

``DrainTracker`` is the graceful-drain state machine both loops run
against a ``PreemptionGuard``: SIGTERM stops admission, sheds queued
work, lets in-flight sequences finish inside ``drain_ms``, and cuts
the rest as ``drained`` at the budget's hard edge.

One-body-two-callers is also what keeps the prefix-cache token-identity
contract (v1 AND the v2 generated-block/partial-copy extensions) a
single proof: cache effects live entirely inside ``engine.step()`` /
the scheduler's admission+terminal paths, so a trace replayed through
``engine.run`` and through the fleet router crosses the SAME
accounting here and emits the same tokens.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple


class EngineLoop:
    """Per-engine iteration state + the shared per-iteration body.

    Owns the latency bookkeeping for one engine and wires the engine's
    token stream into ``journal`` (``engine.step()`` journals each token
    at emission, BEFORE the terminal hook can fire — the durable order
    is tok-then-end).  Single-owner like the scheduler: only the thread
    driving the engine may touch a loop.
    """

    def __init__(self, engine, journal=None):
        self.engine = engine
        self.journal = journal
        engine._journal = journal
        # the engine owns the tracer (rebuilt at engine.reset, like the
        # pools); None means tracing off and every stamp site below is
        # a skipped branch — off is byte-for-byte the untraced loop
        self.tracer = getattr(engine, "tracer", None)
        self.token_times: Dict[int, List[float]] = {}
        self.last_emit: Dict[int, float] = {}
        # first-token emit stamp per request (TTFT = stamp - arrival):
        # set once at the request's first delivered token; an eviction
        # clears it — the pre-eviction first token is regenerated, and
        # only the final delivered stream's timing counts (the same
        # rule as token_times)
        self.first_emit: Dict[int, float] = {}
        self.tokens = 0
        self.peak_queue = 0

    def submit(self, req, *, pre: Optional[List[int]] = None,
               front: bool = False):
        """Admit ``req``: stamp the default TTL (an explicit deadline
        wins), journal the submit, run admission control.  ``pre`` is a
        replayed request's already-delivered prefix (staged into the
        journal so the durable stream stays whole across engines);
        ``front`` queues ahead of earlier arrivals — migrated/replayed
        work already waited its turn once.  Returns the scheduler's
        ``RejectedRequest`` (terminal status recorded) or None."""
        eng = self.engine
        if eng.serve.deadline_ms is not None and req.deadline is None:
            req = dataclasses.replace(
                req, deadline=req.arrival + eng.serve.deadline_ms / 1e3)
        if self.journal is not None:
            self.journal.record_submit(req, pre=pre)
        tr = self.tracer
        if tr is not None:
            tr.on_submit(req, replay=req.replayed)
        rej = eng.sched.submit(req, front=front)
        if rej is not None:
            if tr is not None:
                # synchronous rejection: the terminal hook already
                # queued the transition; land it at arrival (zero
                # queue time — the request never waited)
                tr.flush_terminals(req.arrival)
            return rej
        self.last_emit[req.id] = req.arrival
        self.token_times[req.id] = []
        self.peak_queue = max(self.peak_queue, len(eng.sched.waiting))
        return None

    def iterate(self, now: float, time_fn, t0: float) \
            -> List[Tuple[int, int]]:
        """One engine iteration: deadline sweep BEFORE the step (expired
        work must not buy another dispatch's worth of pool time), one
        ``engine.step()``, then the emit/eviction accounting.  Returns
        the ``(request id, token)`` pairs emitted."""
        eng = self.engine
        tr = self.tracer
        if tr is not None:
            step_t0 = now
            tr.begin_step()
            _m0 = time.monotonic()
        eng.sched.expire_deadlines(now)
        if tr is not None:
            tr.sweep_s += time.monotonic() - _m0
        emitted = eng.step()
        now = time_fn() - t0
        for rid, _tok in emitted:
            if rid in self.last_emit:
                self.token_times[rid].append(now - self.last_emit[rid])
                self.last_emit[rid] = now
                self.first_emit.setdefault(rid, now)
        self.tokens += len(emitted)
        if tr is not None:
            # span stamping uses the SAME post-step ``now`` as the
            # latency clock above, so span TTFT == stamped TTFT exactly
            tr.observe(eng.sched.occupied_view(),
                       {rid for rid, _tok in emitted}, now)
        # AFTER the emit accounting: an eviction discards the request's
        # samples so far — including a token emitted this very step
        # (prefill-final then evicted by a later slot's ensure_block);
        # only the final delivered stream counts, and the journal must
        # forget the voided tokens exactly like the latency clock does
        for rid in eng.sched.evicted_ids:
            if self.journal is not None:
                self.journal.record_evict(rid)
            self.token_times[rid] = []
            self.last_emit[rid] = now
            self.first_emit.pop(rid, None)
            if tr is not None:
                tr.on_evict(rid, now)
        eng.sched.evicted_ids.clear()
        if tr is not None:
            # terminals land AFTER first-token stamping (same ``now``),
            # so ``terminal >= first_token`` holds within every span
            tr.flush_terminals(now)
            tr.end_step(step_t0, now, len(emitted), eng.load_signals())
        return emitted

    def latencies(self) -> List[float]:
        return [x for ts in self.token_times.values() for x in ts]


class DrainTracker:
    """Graceful-drain state shared by the engine loop and the fleet
    router: ``start`` marks the SIGTERM moment (admission stops, queued
    work sheds), ``expired`` is the budget's hard edge past which
    in-flight work is cut as ``drained``.  ``drain_ms`` None = no
    budget (finish everything in flight)."""

    def __init__(self, drain_ms: Optional[float]):
        self.drain_ms = drain_ms
        self.draining = False
        self.t0 = 0.0
        self.shed = 0            # queued/pending work shed at drain start
        self.fin_at_start = 0    # completions before the stop request

    def start(self, now: float, finished_now: int = 0) -> None:
        self.draining = True
        self.t0 = now
        self.fin_at_start = finished_now

    def expired(self, now: float) -> bool:
        return (self.draining and self.drain_ms is not None
                and (now - self.t0) * 1e3 > self.drain_ms)

    def result(self, finished_total: int, cut: int) -> dict:
        """The canonical ``drain`` result block (requested / drained-to-
        completion / shed / cut / budget) both run loops emit."""
        return {
            "requested": self.draining,
            # finished after the stop request = drained to completion
            "drained": (finished_total - self.fin_at_start
                        if self.draining else 0),
            "shed": self.shed if self.draining else 0,
            "cut": int(cut),
            "budget_ms": self.drain_ms,
        }

    def result_counts(self, counts) -> dict:
        """The SAME canonical block, computed from per-status terminal
        counts recorded while draining — the fleet router's accounting
        (it observes terminals as hook notifications rather than one
        scheduler's finished-list delta).  Defined here, next to
        ``result``, so the block's shape lives in exactly one module."""
        return {
            "requested": self.draining,
            "drained": int(counts.get("ok", 0)) if self.draining else 0,
            "shed": int(counts.get("shed", 0)) if self.draining else 0,
            "cut": int(counts.get("drained", 0)),
            "budget_ms": self.drain_ms,
        }
