"""Continuous-batching scheduler: request queue + decode-slot state.

Orca-style iteration-level scheduling (Yu et al., OSDI 2022): the unit
of work is ONE decode step over whatever sequences are live, not one
request batch end-to-end.  A sequence joins as soon as a slot AND the
blocks for its prompt are free (admit-on-free-blocks), and its slot is
recycled the step it finishes (EOS or token budget) — a long request no
longer holds a whole batch hostage, and finished rows stop burning MXU
cycles on masked steps.

Failure handling is structured, never an engine crash: every request
leaves the system with exactly one terminal status —

- ``ok``                 finished (EOS or token budget);
- ``rejected``           infeasible at submit (can never fit the pool /
                         malformed), or — defensively — a live sequence
                         the pool can no longer grow with nothing left
                         to evict;
- ``shed``               dropped by load shedding: the bounded waiting
                         queue was full (reject-newest, ``queue_full``),
                         or admission stopped for a drain;
- ``deadline_exceeded``  its deadline passed before completion;
- ``evicted_too_often``  preempted more than ``max_evictions`` times
                         (livelock guard: requeue-at-head forever is a
                         starvation engine, not progress);
- ``drained``            in flight when a graceful drain's budget
                         expired (the engine cut it off incomplete).

All state here is host-side Python; the engine turns the live slot set
into bucketed device dispatches.  Pure-Python on purpose: the
admit/evict invariant tests run without a device.
"""

from __future__ import annotations

import dataclasses
from collections import Counter, deque
from typing import Callable, Dict, List, Optional

from mpi_tensorflow_tpu.serving.paged_cache import (BlockAllocator,
                                                    blocks_for)
from mpi_tensorflow_tpu.serving.prefix_cache import PrefixCache

#: every terminal status a request can leave the scheduler with
TERMINAL_STATUSES = ("ok", "rejected", "shed", "deadline_exceeded",
                     "evicted_too_often", "drained")


@dataclasses.dataclass
class Request:
    """One generation request.  ``arrival`` is in seconds on the caller's
    clock; the engine admits a request only once the clock passes it
    (the bench harness replays Poisson traces through this).
    ``deadline`` is an absolute stamp on the same clock: a request not
    COMPLETE by then fails with ``deadline_exceeded`` instead of
    occupying a slot (None = no deadline)."""
    id: int
    prompt: List[int]
    max_new_tokens: int
    arrival: float = 0.0
    deadline: Optional[float] = None
    replayed: bool = False        # crash-recovery resubmission: it
                                  # passed admission control once and
                                  # carries delivered tokens, so load
                                  # shedding must not drop it (the
                                  # feasibility check still applies)
    session: Optional[object] = None  # conversation/session key for the
                                  # replica router (serving/router):
                                  # requests sharing a session stick to
                                  # one replica, where their prefix
                                  # blocks and drafter state live.
                                  # None = no affinity (each request
                                  # places independently by load)


@dataclasses.dataclass(frozen=True)
class RejectedRequest:
    """Structured admission refusal — the submit() result that replaces
    the engine-killing exception.  ``reason`` is the machine-readable
    cause (``infeasible`` | ``bad_request`` | ``queue_full``); ``status``
    is the terminal status recorded for the request."""
    request: Request
    reason: str
    status: str

    def __bool__(self) -> bool:          # `if sched.submit(req):` reads
        return True                      # as "was it rejected"


@dataclasses.dataclass
class Sequence:
    """A live (admitted) sequence: its pool blocks + progress.

    ``prefix_cached`` prompt tokens were served by the radix prefix
    cache at admission: their blocks are SHARED physical blocks mapped
    straight into ``block_ids`` and ``prefilled`` starts there, so the
    prefill dispatches only ever compute the unique suffix."""
    request: Request
    block_ids: List[int]
    prefilled: int = 0            # prompt tokens already through prefill
    generated: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    prefix_cached: int = 0        # prompt tokens served by cache hits
    # Partial tail-block sharing (prefix v2): admission matched
    # ``partial_rows`` leading tokens of this sequence's tail block
    # against cached block ``partial_src`` (pinned: one share ref held
    # until the engine's partial-copy dispatch lands or the sequence
    # leaves the slot) to be copied into its private ``partial_dst``.
    # ``prefilled`` already counts those rows — the engine MUST apply
    # the copy before the first prefill chunk touches the slot.
    partial_src: Optional[int] = None
    partial_dst: int = 0
    partial_rows: int = 0

    @property
    def length(self) -> int:
        """Prompt tokens prefilled + tokens generated.  The LAST
        generated token is pending — emitted but not yet written to the
        cache (the next decode step writes it at position length-1 as it
        reads it), so the cache holds ``length - 1`` entries between
        steps."""
        return self.prefilled + len(self.generated)


class Scheduler:
    """Slots + queue + the block-accounting policy.

    ``max_slots`` bounds concurrent sequences (the decode batch
    dimension); ``max_blocks_per_seq`` bounds one sequence's table (the
    gathered attention capacity).  Admission requires a free slot AND
    enough free blocks for the whole prompt plus one decode block — a
    sequence that prefills must be able to emit at least one token.

    Under pool pressure (a decode step needs a new block and none is
    free) the YOUNGEST sequence is evicted back to the queue head —
    restart-from-scratch preemption, blocks freed, FIFO fairness for the
    oldest.  Invariants (pinned by tests): a block belongs to at most
    one live sequence; evicted/finished/failed sequences return every
    block; free+used always partitions the pool.

    Robustness knobs (all optional; None keeps the unguarded behavior):

    - ``queue_depth``     bounds ``waiting``; a submit that finds it full
                          is load-shed (reject-newest, ``queue_full``) —
                          backpressure instead of unbounded buildup.
    - ``max_evictions``   a request may be evicted-and-requeued at most
                          this many times; the next eviction fails it
                          with ``evicted_too_often``.
    - ``starvation_steps``  aging guard: when the HEAD of the queue (the
                          oldest request, including evicted requeues)
                          has been block-starved for this many admit
                          calls, sequences YOUNGER than it are preempted
                          to free blocks for it — a hot arrival stream
                          cannot park old work forever.
    - ``on_terminal(request, status)``  fired exactly once per request
                          as it leaves the system (journal hook).
    - ``prefix_cache``    radix prefix cache (serving/prefix_cache);
                          admission maps cached full prompt blocks into
                          the new sequence's table (shared, refcounted)
                          and charges it only for the unique suffix.
                          Under pool pressure, unreferenced cached
                          blocks are LRU-evicted BEFORE any live
                          sequence is preempted.  None = sharing off —
                          byte-for-byte today's behavior.
    - ``prefix_gen``      prefix sharing v2 (--serve-prefix-gen): a
                          finishing sequence inserts its full blocks
                          spanning prompt + generated output into the
                          trie (before its own release, so the blocks
                          survive by the trie's share ref), and
                          admission extends a mid-block miss with a
                          partial tail-block copy.  Off = the trie
                          holds full PROMPT blocks only, byte-for-byte
                          the v1 behavior.
    """

    def __init__(self, allocator: BlockAllocator, max_slots: int,
                 block_size: int, max_blocks_per_seq: int, *,
                 queue_depth: Optional[int] = None,
                 max_evictions: Optional[int] = None,
                 starvation_steps: Optional[int] = 64,
                 on_terminal: Optional[Callable[[Request, str],
                                                None]] = None,
                 prefix_cache: Optional[PrefixCache] = None,
                 prefix_gen: bool = False):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.allocator = allocator
        self.max_slots = max_slots
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.queue_depth = queue_depth
        self.max_evictions = max_evictions
        self.starvation_steps = starvation_steps
        self.on_terminal = on_terminal
        self.prefix_cache = prefix_cache
        self.prefix_gen = prefix_gen
        self.waiting: deque = deque()
        self.slots: List[Optional[Sequence]] = [None] * max_slots
        self.finished: List[Sequence] = []
        self.failed: List[Request] = []
        self.statuses: Dict[int, str] = {}     # request id -> terminal
        self.counters: Counter = Counter()     # faults_block feeds off this
        self.evictions = 0
        self.evicted_ids: List[int] = []   # request ids, drained by the
                                           # engine's latency accounting
        self.evict_counts: Counter = Counter()  # per-request preemptions
        self._head_blocked = 0             # admit calls the queue head has
                                           # been starved of blocks
        self._head_blocked_id = None       # ...and WHICH head: credit must
                                           # not transfer to a successor

    # ---------------- terminal bookkeeping ----------------

    def _terminal(self, req: Request, status: str) -> None:
        """Record a request's one terminal status (+ journal hook)."""
        self.statuses[req.id] = status
        if status != "ok":
            self.counters[status] += 1
            self.failed.append(req)
        if self.on_terminal is not None:
            self.on_terminal(req, status)

    # ---------------- queue / admission ----------------

    def submit(self, req: Request,
               front: bool = False) -> Optional[RejectedRequest]:
        """Feasibility-checked admission to the waiting queue.  Returns
        None on accept, a structured ``RejectedRequest`` otherwise — an
        infeasible or malformed request terminates with a status; it
        never raises into (and never crashes) the engine.

        ``front`` queues ahead of already-waiting work: a request
        migrated off a failed replica (or replayed after a crash)
        already waited its turn once — arriving behind this replica's
        newer arrivals would double-charge it the queueing delay."""
        if not req.prompt or req.max_new_tokens < 1:
            return self._reject(req, "bad_request", "rejected")
        total = len(req.prompt) + req.max_new_tokens
        cap = self.max_blocks_per_seq * self.block_size
        pool_cap = (self.allocator.num_blocks - 1) * self.block_size
        if total > cap or total > pool_cap:
            # can NEVER fit, even with every other sequence evicted —
            # admitting it would guarantee a mid-stream dead end
            return self._reject(req, "infeasible", "rejected")
        if self.queue_depth is not None and not req.replayed \
                and len(self.waiting) >= self.queue_depth:
            # bounded queue: reject-newest load shedding (the oldest
            # waiting work keeps its place; backpressure lands on the
            # arrival stream, where the client can retry elsewhere).
            # Replayed requests are exempt: shedding recovered work
            # would orphan its already-delivered prefix
            return self._reject(req, "queue_full", "shed")
        if front:
            self.waiting.appendleft(req)
        else:
            self.waiting.append(req)
        return None

    def _reject(self, req: Request, reason: str,
                status: str) -> RejectedRequest:
        self._terminal(req, status)
        return RejectedRequest(req, reason, status)

    def free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def admit(self) -> List[int]:
        """Admit queued requests while a slot and blocks are free.
        Returns the slot indices admitted this call (they need prefill).
        FIFO head-of-line: if the oldest request does not fit, nothing
        behind it jumps the queue — admission order stays arrival order
        (the latency numbers the bench reports depend on it).

        Aging guard: a head blocked on blocks for ``starvation_steps``
        consecutive admit calls preempts sequences YOUNGER than itself
        to free the blocks it needs — requeued (evicted) old work makes
        progress even under a hot stream of later arrivals.

        Prefix sharing: the head's prompt is first walked through the
        radix cache — every cached full block is mapped (shared) into
        the new table and the admission is charged only for the unique
        suffix, so a hot system prompt costs its blocks ONCE across the
        whole pool.  The matched blocks are pinned (one reference) for
        the duration of the attempt, so the trie eviction that reclaim
        may trigger can never free them out from under the admit.

        Hit-aware admission: ONLY when the head is block-starved (the
        aging guard included could not unblock it), the rest of the
        queue is scanned for the closest request whose cached prefix
        makes it fit in the blocks actually free — its cached blocks
        cost nothing and only its unique suffix takes free blocks, so
        the pool does useful work instead of idling.  The suffix DOES
        delay the head, which is why the bypass runs only while the
        aging guard is armed (``starvation_steps`` not None): the
        guard bounds how long the head can be bypassed before younger
        live work is preempted for it.  With no pressure, admission
        order stays strict FIFO (pinned by tests)."""
        admitted = []
        while self.waiting:
            slot = self.free_slot()
            if slot is None:
                break
            req = self.waiting[0]
            cached_ids: List[int] = []
            cached_tokens = 0
            if self.prefix_cache is not None:
                cached_ids, cached_tokens = \
                    self.prefix_cache.match_and_share(req.prompt)
            need = blocks_for(len(req.prompt) + 1, self.block_size) \
                - len(cached_ids)
            if not self._reclaim(need):
                if cached_ids:
                    # un-pin this attempt's matched blocks; the trie
                    # keeps them and the next attempt re-matches
                    self.allocator.release(cached_ids)
                if self._head_blocked_id != req.id:
                    # a different head (the old one admitted/expired):
                    # starvation credit starts over
                    self._head_blocked_id = req.id
                    self._head_blocked = 0
                self._head_blocked += 1
                if self.starvation_steps is not None \
                        and self._head_blocked > self.starvation_steps \
                        and self._evict_youngest(
                            protect=None, younger_than=req.arrival,
                            requeue_pos=1):
                    # victim requeues BEHIND the aged head (position 1):
                    # appendleft would put younger work back in front of
                    # the very request the guard exists to unblock
                    continue
                if self._admit_hit_aware(slot):
                    # a cached-prefix request from behind the starved
                    # head fit in the FREE blocks: keep admitting (the
                    # head's starvation credit above keeps aging — the
                    # bypass must not reset it)
                    admitted.append(slot)
                    continue
                break
            self._head_blocked = 0
            self.waiting.popleft()
            self._admit_to(slot, req, cached_ids, cached_tokens, need)
            admitted.append(slot)
        return admitted

    def _admit_to(self, slot: int, req: Request, cached_ids: List[int],
                  cached_tokens: int, need: int) -> None:
        """Install ``req`` into ``slot`` with its matched prefix blocks
        plus ``need`` fresh ones — the one admission tail shared by the
        FIFO path and the hit-aware bypass."""
        if self.prefix_cache is not None:
            self.counters["prefix_prompt_tokens"] += len(req.prompt)
            self.counters["prefix_hit_tokens"] += cached_tokens
            self.counters["prefix_shared_blocks"] += len(cached_ids)
        partial = None
        if (self.prefix_gen and self.prefix_cache is not None
                and cached_tokens == len(cached_ids) * self.block_size):
            # the full-block walk ended on a real miss (an uncapped
            # match — a capped one means the whole prompt is cached and
            # the tail recompute is the match_and_share rule, not a
            # miss): try to serve the tail block's leading rows from
            # the best-matching cached sibling.  ``need >= 1`` is
            # guaranteed here (the uncached suffix is non-empty), so
            # the first fresh block below IS the copy destination.
            partial = self.prefix_cache.match_partial(
                req.prompt, len(cached_ids))
        blocks = cached_ids + self.allocator.alloc(need)
        seq = Sequence(req, blocks, prefilled=cached_tokens,
                       prefix_cached=cached_tokens)
        if partial is not None:
            src, rows = partial
            seq.partial_src = src
            seq.partial_dst = blocks[len(cached_ids)]
            seq.partial_rows = rows
            seq.prefilled = seq.prefix_cached = cached_tokens + rows
            self.counters["prefix_partial_copy_tokens"] += rows
        self.slots[slot] = seq

    def _admit_hit_aware(self, slot: int) -> bool:
        """The block-starved bypass: admit the closest queued request
        whose cached prefix lets it fit in the blocks FREE RIGHT NOW
        (``can_alloc``, not ``_reclaim`` — the bypass must neither
        evict live work nor shrink the trie on behalf of younger
        arrivals, and a candidate with no hits at all has no claim to
        jump FIFO).  Disabled when the aging guard is off: the
        jumper's unique suffix consumes free blocks the head is
        waiting on, and without ``starvation_steps`` bounding the
        head's wait that would be an unbounded-bypass liveness hole.
        The scan is WINDOWED (closest 16 queued requests): admit() runs
        every engine step, and each candidate costs a radix-trie walk
        plus share/release refcount churn — an O(whole-queue) rescan
        per step under sustained pressure would make admission itself
        the hot path.  Returns whether a request was admitted."""
        if self.prefix_cache is None or self.starvation_steps is None:
            return False
        for qi in range(1, min(len(self.waiting), 17)):
            req = self.waiting[qi]
            cached_ids, cached_tokens = \
                self.prefix_cache.match_and_share(req.prompt)
            if not cached_ids:
                continue
            need = blocks_for(len(req.prompt) + 1, self.block_size) \
                - len(cached_ids)
            if self.allocator.can_alloc(need):
                del self.waiting[qi]
                self.counters["prefix_hit_admissions"] += 1
                self._admit_to(slot, req, cached_ids, cached_tokens,
                               need)
                return True
            self.allocator.release(cached_ids)
        return False

    # ---------------- per-step bookkeeping ----------------

    def _release_partial(self, seq: Sequence) -> None:
        """Drop the partial-copy source pin (if any) — called by the
        engine once its copy dispatch lands, and by every path that
        removes the sequence from its slot first (eviction, failure,
        finish) so the pin can never outlive the sequence."""
        if seq.partial_src is not None:
            self.allocator.release([seq.partial_src])
            seq.partial_src = None
            seq.partial_rows = 0

    def live_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots)
                if s is not None and s.prefilled > 0]

    def occupied_view(self) -> List[tuple]:
        """Observation snapshot for the tracing layer: ``(request id,
        prefilled, generated)`` for every occupied slot — including
        admitted-but-unprefilled sequences ``live_slots`` skips, which
        is exactly the admission transition the tracer stamps.  Plain
        ints, no sequence references escape."""
        return [(s.request.id, s.prefilled, len(s.generated))
                for s in self.slots if s is not None]

    @property
    def prefill_backlog_tokens(self) -> int:
        """Prompt tokens of admitted sequences not yet prefilled — the
        head-of-line work queue depth misses: these sequences hold
        slots (and pool blocks) but emit nothing until their prefill
        lands, so load signals counting only the waiting queue
        under-report pressure exactly when prompts are long.  The
        autoscale load signal folds this in (engine.load_signals /
        ScaleAdvisor), and mixed batching drains it under the per-step
        token budget."""
        return sum(len(s.request.prompt) - s.prefilled
                   for s in self.slots
                   if s is not None
                   and s.prefilled < len(s.request.prompt))

    def _reclaim(self, n: int) -> bool:
        """``can_alloc`` with prefix-cache backpressure: under pool
        pressure, LRU-evict unreferenced cached blocks from the trie
        before reporting failure — sharing must never starve admission
        or decode growth.  Sequence eviction stays the CALLER'S
        fallback (and is re-followed by a reclaim: a preempted victim's
        release can leave blocks pinned only by the trie)."""
        if self.allocator.can_alloc(n):
            return True
        if self.prefix_cache is not None:
            freed = self.prefix_cache.evict(n - self.allocator.num_free)
            if freed:
                self.counters["prefix_trie_evictions"] += freed
        return self.allocator.can_alloc(n)

    def alloc_for(self, slot: int) -> Optional[int]:
        """One fresh exclusive block for ``slot`` (table growth or a
        copy-on-write target), evicting trie entries then younger
        sequences under pressure.  None = pool exhausted with nothing
        left to evict — the caller fails this one request."""
        while not self._reclaim(1):
            if not self._evict_youngest(protect=slot):
                return None
        return self.allocator.alloc(1)[0]

    def ensure_block(self, slot: int) -> bool:
        """Make sure the slot's table covers cache position ``length-1``
        (where this step writes the pending token, growing the cache to
        ``length`` entries).  Returns False when the pool is exhausted
        AND eviction could not free a block for this slot."""
        seq = self.slots[slot]
        need = blocks_for(seq.length, self.block_size)
        while len(seq.block_ids) < need:
            b = self.alloc_for(slot)
            if b is None:
                return False
            seq.block_ids.append(b)
        return True

    def extend_for(self, slot: int, total_tokens: int) -> int:
        """Opportunistically grow the slot's table to cover
        ``total_tokens`` cache entries (a speculative draft window
        writes up to k tokens past the pending one) WITHOUT preemption
        or trie eviction: speculation is a bandwidth optimization, and
        letting it evict live sequences or cached prefixes would trade
        real work for guessed work.  Takes only free blocks; returns
        the entries the table now covers — the caller shrinks its draft
        to fit."""
        seq = self.slots[slot]
        want = min(blocks_for(total_tokens, self.block_size),
                   self.max_blocks_per_seq)
        extra = want - len(seq.block_ids)
        if extra > 0 and self.allocator.can_alloc(extra):
            seq.block_ids.extend(self.allocator.alloc(extra))
        return len(seq.block_ids) * self.block_size

    def rollback_blocks(self, slot: int, keep_tokens: int) -> int:
        """Release the slot's trailing blocks beyond what
        ``keep_tokens`` cache entries need — the draft-rollback path: a
        verify step that rejected draft tokens returns the blocks that
        existed only to hold their (phantom) KV writes, so the pool
        never retains entries no accepted token owns.  Safe with prefix
        sharing: trailing blocks past the live length are exclusive by
        construction (admission-mapped shared blocks all precede it),
        and the refcounted release would protect a sharer anyway.
        Returns the number of blocks released."""
        seq = self.slots[slot]
        keep = max(blocks_for(keep_tokens, self.block_size), 1)
        if len(seq.block_ids) <= keep:
            return 0
        victims = seq.block_ids[keep:]
        del seq.block_ids[keep:]
        self.allocator.release(victims)
        return len(victims)

    def _evict_youngest(self, protect: Optional[int],
                        younger_than: Optional[float] = None,
                        requeue_pos: int = 0) -> bool:
        """Preempt the youngest live sequence (restart-from-scratch):
        free its blocks, requeue its request at ``requeue_pos`` in the
        queue (0 = the head, so it re-admits before anything that
        arrived after it).  ``younger_than`` restricts candidates to
        arrivals strictly after that stamp (the aging guard must never
        preempt work older than the request it serves).  A victim past
        its ``max_evictions`` budget is failed with ``evicted_too_often``
        instead of requeued — its blocks still free, so the caller's
        allocation can proceed either way.

        Frees route through the refcounted ``release``: evicting a
        victim that SHARES prefix blocks with live sequences (or the
        trie) only drops its references — the survivors' tables stay
        intact (regression-pinned by tests/test_serving.py)."""
        candidates = [(self.slots[i].request.arrival, i)
                      for i in range(self.max_slots)
                      if self.slots[i] is not None and i != protect
                      and (younger_than is None
                           or self.slots[i].request.arrival > younger_than)]
        if not candidates:
            return False
        _, victim = max(candidates)
        seq = self.slots[victim]
        self.allocator.release(seq.block_ids)
        self._release_partial(seq)
        self.slots[victim] = None
        self.evictions += 1
        self.counters["evictions"] += 1
        self.evicted_ids.append(seq.request.id)
        self.evict_counts[seq.request.id] += 1
        if self.max_evictions is not None \
                and self.evict_counts[seq.request.id] > self.max_evictions:
            # livelock guard: K restarts bought no completion — fail it
            # rather than let requeue-at-head churn the pool forever
            self._terminal(seq.request, "evicted_too_often")
            return True
        if requeue_pos <= 0 or not self.waiting:
            self.waiting.appendleft(seq.request)
        else:
            self.waiting.insert(requeue_pos, seq.request)
        return True

    def record_token(self, slot: int, token: int,
                     eos_id: Optional[int] = None) -> None:
        """Account one generated token; finish + recycle the slot when
        the sequence hits EOS or its budget."""
        seq = self.slots[slot]
        seq.generated.append(token)
        if (len(seq.generated) >= seq.request.max_new_tokens
                or (eos_id is not None and token == eos_id)):
            seq.done = True
            self._release_partial(seq)
            if self.prefix_gen and self.prefix_cache is not None:
                # generated-block insertion (prefix v2): adopt the full
                # blocks spanning prompt + generated BEFORE this
                # sequence's release below, so they survive by the
                # trie's own share refs (check_quiescent's
                # trie-only-refs rule).  Only the ``length - 1`` cache
                # entries actually WRITTEN are insertable — the final
                # token is pending, and under speculation positions
                # past it hold rejected phantom writes.
                stream = list(seq.request.prompt) + seq.generated
                added = self.prefix_cache.insert(
                    stream[:seq.length - 1], seq.block_ids)
                self.counters["prefix_gen_inserted_blocks"] += added
            self.allocator.release(seq.block_ids)
            seq.block_ids = []
            self.finished.append(seq)
            self.slots[slot] = None
            self._terminal(seq.request, "ok")

    def record_tokens(self, slot: int, tokens: List[int],
                      eos_id: Optional[int] = None) -> int:
        """Multi-token append — the speculative-decoding extension of
        the one-token-per-step contract: a verify step emits a VARIABLE
        number of tokens per sequence (accepted draft prefix + the
        model's own correction).  Stops the moment the sequence
        finishes (EOS or budget recycles the slot mid-list); returns
        how many tokens were recorded."""
        seq = self.slots[slot]
        n = 0
        for t in tokens:
            if self.slots[slot] is not seq:
                break
            self.record_token(slot, t, eos_id)
            n += 1
        return n

    # ---------------- failure / drain surface ----------------

    def fail_request(self, req: Request, status: str) -> None:
        """Terminate a request that is NOT in the scheduler (e.g. a
        pending arrival shed at drain start) with ``status``."""
        self._terminal(req, status)

    def fail_live(self, slot: int, status: str) -> None:
        """Terminate ONE live sequence with ``status``: free its blocks,
        recycle the slot — the other in-flight streams keep serving."""
        seq = self.slots[slot]
        self.allocator.release(seq.block_ids)
        self._release_partial(seq)
        seq.block_ids = []
        self.slots[slot] = None
        self._terminal(seq.request, status)

    def expire_deadlines(self, now: float) -> List[int]:
        """Fail every waiting or live request whose deadline has passed
        (``deadline_exceeded``); expired work must stop occupying slots
        and blocks that feasible requests could use.  Returns the
        expired request ids."""
        expired = []
        survivors = deque()
        for req in self.waiting:
            if req.deadline is not None and now >= req.deadline:
                self._terminal(req, "deadline_exceeded")
                expired.append(req.id)
            else:
                survivors.append(req)
        self.waiting = survivors
        for i, seq in enumerate(self.slots):
            if seq is not None and seq.request.deadline is not None \
                    and now >= seq.request.deadline:
                expired.append(seq.request.id)
                self.fail_live(i, "deadline_exceeded")
        return expired

    def shed_waiting(self, status: str = "shed") -> int:
        """Drop the whole waiting queue — drain-start load shedding:
        admission has stopped, and queued work is not in flight."""
        n = len(self.waiting)
        while self.waiting:
            self._terminal(self.waiting.popleft(), status)
        return n

    def abort_live(self, status: str) -> int:
        """Terminate every live sequence AND any residual waiting work
        (eviction victims requeued mid-drain) with ``status`` — the
        drain budget's hard edge."""
        n = self.shed_waiting(status)
        for i, seq in enumerate(self.slots):
            if seq is not None:
                self.fail_live(i, status)
                n += 1
        return n

    def all_done(self) -> bool:
        return not self.waiting and all(s is None for s in self.slots)

    def check_quiescent(self) -> None:
        """Pool-leak invariant at the end of a run: every terminal
        request released its blocks, the free list + refcount map
        partition the pool, and the only references left standing are
        the prefix trie's own (one per cached node)."""
        self.allocator.check()
        held = self.prefix_cache.num_blocks \
            if self.prefix_cache is not None else 0
        assert self.allocator.num_used == held, (
            f"pool leak: {self.allocator.num_used} blocks referenced at "
            f"quiescence, prefix trie accounts for {held}")
        if self.prefix_cache is not None:
            self.prefix_cache.check()
