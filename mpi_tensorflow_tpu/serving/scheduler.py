"""Continuous-batching scheduler: request queue + decode-slot state.

Orca-style iteration-level scheduling (Yu et al., OSDI 2022): the unit
of work is ONE decode step over whatever sequences are live, not one
request batch end-to-end.  A sequence joins as soon as a slot AND the
blocks for its prompt are free (admit-on-free-blocks), and its slot is
recycled the step it finishes (EOS or token budget) — a long request no
longer holds a whole batch hostage, and finished rows stop burning MXU
cycles on masked steps.

All state here is host-side Python; the engine turns the live slot set
into bucketed device dispatches.  Pure-Python on purpose: the
admit/evict invariant tests run without a device.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import List, Optional

from mpi_tensorflow_tpu.serving.paged_cache import (BlockAllocator,
                                                    blocks_for)


@dataclasses.dataclass
class Request:
    """One generation request.  ``arrival`` is in seconds on the caller's
    clock; the engine admits a request only once the clock passes it
    (the bench harness replays Poisson traces through this)."""
    id: int
    prompt: List[int]
    max_new_tokens: int
    arrival: float = 0.0


@dataclasses.dataclass
class Sequence:
    """A live (admitted) sequence: its pool blocks + progress."""
    request: Request
    block_ids: List[int]
    prefilled: int = 0            # prompt tokens already through prefill
    generated: List[int] = dataclasses.field(default_factory=list)
    done: bool = False

    @property
    def length(self) -> int:
        """Prompt tokens prefilled + tokens generated.  The LAST
        generated token is pending — emitted but not yet written to the
        cache (the next decode step writes it at position length-1 as it
        reads it), so the cache holds ``length - 1`` entries between
        steps."""
        return self.prefilled + len(self.generated)


class Scheduler:
    """Slots + queue + the block-accounting policy.

    ``max_slots`` bounds concurrent sequences (the decode batch
    dimension); ``max_blocks_per_seq`` bounds one sequence's table (the
    gathered attention capacity).  Admission requires a free slot AND
    enough free blocks for the whole prompt plus one decode block — a
    sequence that prefills must be able to emit at least one token.

    Under pool pressure (a decode step needs a new block and none is
    free) the YOUNGEST sequence is evicted back to the queue head —
    restart-from-scratch preemption, blocks freed, FIFO fairness for the
    oldest.  Invariants (pinned by tests): a block belongs to at most
    one live sequence; evicted/finished sequences return every block;
    free+used always partitions the pool.
    """

    def __init__(self, allocator: BlockAllocator, max_slots: int,
                 block_size: int, max_blocks_per_seq: int):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.allocator = allocator
        self.max_slots = max_slots
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.waiting: deque = deque()
        self.slots: List[Optional[Sequence]] = [None] * max_slots
        self.finished: List[Sequence] = []
        self.evictions = 0
        self.evicted_ids: List[int] = []   # request ids, drained by the
                                           # engine's latency accounting

    # ---------------- queue / admission ----------------

    def submit(self, req: Request) -> None:
        if not req.prompt or req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.id}: needs a non-empty prompt and "
                f"max_new_tokens >= 1")
        total = len(req.prompt) + req.max_new_tokens
        cap = self.max_blocks_per_seq * self.block_size
        if total > cap:
            raise ValueError(
                f"request {req.id}: prompt+output {total} exceeds the "
                f"per-sequence cache capacity {cap} "
                f"({self.max_blocks_per_seq} blocks x {self.block_size})")
        self.waiting.append(req)

    def free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def admit(self) -> List[int]:
        """Admit queued requests while a slot and blocks are free.
        Returns the slot indices admitted this call (they need prefill).
        FIFO head-of-line: if the oldest request does not fit, nothing
        behind it jumps the queue — admission order stays arrival order
        (the latency numbers the bench reports depend on it)."""
        admitted = []
        while self.waiting:
            slot = self.free_slot()
            if slot is None:
                break
            req = self.waiting[0]
            need = blocks_for(len(req.prompt) + 1, self.block_size)
            if not self.allocator.can_alloc(need):
                break
            self.waiting.popleft()
            self.slots[slot] = Sequence(req, self.allocator.alloc(need))
            admitted.append(slot)
        return admitted

    # ---------------- per-step bookkeeping ----------------

    def live_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots)
                if s is not None and s.prefilled > 0]

    def ensure_block(self, slot: int) -> bool:
        """Make sure the slot's table covers cache position ``length-1``
        (where this step writes the pending token, growing the cache to
        ``length`` entries).  Returns False when the pool is exhausted
        AND eviction could not free a block for this slot."""
        seq = self.slots[slot]
        need = blocks_for(seq.length, self.block_size)
        while len(seq.block_ids) < need:
            if not self.allocator.can_alloc(1):
                if not self._evict_youngest(protect=slot):
                    return False
                continue
            seq.block_ids.extend(self.allocator.alloc(1))
        return True

    def _evict_youngest(self, protect: int) -> bool:
        """Preempt the youngest live sequence (restart-from-scratch):
        free its blocks, requeue its request at the queue HEAD so it
        re-admits before anything that arrived after it."""
        candidates = [(self.slots[i].request.arrival, i)
                      for i in range(self.max_slots)
                      if self.slots[i] is not None and i != protect]
        if not candidates:
            return False
        _, victim = max(candidates)
        seq = self.slots[victim]
        self.allocator.free(seq.block_ids)
        self.waiting.appendleft(seq.request)
        self.slots[victim] = None
        self.evictions += 1
        self.evicted_ids.append(seq.request.id)
        return True

    def record_token(self, slot: int, token: int,
                     eos_id: Optional[int] = None) -> None:
        """Account one generated token; finish + recycle the slot when
        the sequence hits EOS or its budget."""
        seq = self.slots[slot]
        seq.generated.append(token)
        if (len(seq.generated) >= seq.request.max_new_tokens
                or (eos_id is not None and token == eos_id)):
            seq.done = True
            self.allocator.free(seq.block_ids)
            seq.block_ids = []
            self.finished.append(seq)
            self.slots[slot] = None

    def all_done(self) -> bool:
        return not self.waiting and all(s is None for s in self.slots)
