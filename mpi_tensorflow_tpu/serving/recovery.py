"""Crash recovery for serving: host-side replay journal + supervision.

The training side already has a three-layer recovery story (preemption
guard -> durable checkpoint -> elastic restart, train/elastic.py); this
module is the serving equivalent.  The key asset is that greedy decode
is DETERMINISTIC: for a fixed model+params, the tokens following any
prompt are a pure function of the prompt.  So the durable state a
serving process needs is tiny and already on the host — each request's
prompt plus the prefix of tokens generated so far.  After a crash (or
an in-process transient device failure), a live request replays as a
fresh request whose prompt is ``original_prompt + generated_prefix``
and whose budget is the remaining tokens: chunked prefill re-ingests
the concatenation, the prefill-final argmax emits exactly the token the
lost process would have emitted next, and the delivered stream
``prefix + new_tokens`` is token-identical to an unfaulted run (pinned
by tests/test_serving_recovery.py and the SIGKILL bench test).

Layers:

- ``ReplayJournal``   append-only JSONL of submit/token/evict/end
                      records, mirrored in memory.  ``path=None`` keeps
                      it memory-only (in-process retry); a path makes it
                      durable across SIGKILL (line-buffered appends; a
                      torn final line from a mid-write crash is
                      ignored on load).
- ``run_with_replay`` the supervisor: runs an engine over the journal,
                      classifies failures with the SAME status-code-
                      first ``train/elastic.is_transient`` logic the
                      training supervisor uses, rebuilds pools/engine on
                      transient device loss, and replays live sequences.
                      Non-transient errors (shape bugs, OOM) re-raise
                      immediately — a deterministic bug replayed forever
                      is a worse failure mode.

Prefix cache x replay: the radix trie (serving/prefix_cache) indexes
content that lives in the DEVICE pool, so it dies with the engine and
is rebuilt from scratch by the replayed prefills themselves — a
replacement engine's trie starts empty, the re-rooted
``prompt + prefix`` prompts repopulate it as they prefill, and replayed
requests that share prefixes re-share blocks in the new pool.  Nothing
about the trie is journaled (journaling it would pin device state the
crash just lost); the journal's token streams stay the single durable
truth, and the replay is token-identical with the cache on or off.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from collections import Counter
from typing import Callable, Dict, List, Optional

from mpi_tensorflow_tpu.serving.scheduler import Request
from mpi_tensorflow_tpu.train import elastic


@dataclasses.dataclass
class JournalEntry:
    """Replay state of one request: the submitted prompt, any tokens
    already delivered BEFORE this submit (``pre`` — non-empty only on a
    replay submit, whose prompt embeds them), tokens generated since,
    and the terminal status once one is recorded."""
    prompt: List[int]
    max_new_tokens: int
    arrival: float
    pre: List[int] = dataclasses.field(default_factory=list)
    toks: List[int] = dataclasses.field(default_factory=list)
    status: Optional[str] = None

    @property
    def delivered(self) -> List[int]:
        """The output stream as the client sees it so far."""
        return self.pre + self.toks


class ReplayJournal:
    """Append-only request journal, host-side, optionally durable.

    Record kinds (one JSON object per line):
      {"kind": "submit", "id", "prompt", "n", "arrival", "pre"}
      {"kind": "tok",    "id", "t"}
      {"kind": "evict",  "id"}          # restart-from-scratch: tokens
                                        # since the last submit are void
      {"kind": "end",    "id", "status"}

    Constructing with an existing ``path`` LOADS it first — the crash-
    recovery entry point — then appends.  All writes also update the
    in-memory state, so in-process retries need no reload.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.entries: Dict[int, JournalEntry] = {}
        self.statuses: Dict[int, str] = {}
        # delivered-so-far per replayed id, staged by replay_requests so
        # the engine's plain record_submit(req) journals the right "pre"
        self._pending_pre: Dict[int, List[int]] = {}
        self._fh = None
        if path is not None:
            if os.path.exists(path):
                self._load(path)
            self._fh = open(path, "a", buffering=1)   # line-buffered:
            # each record is durable as soon as the line completes

    # ---------------- load ----------------

    def _load(self, path: str) -> None:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue    # torn final line from a mid-write crash
                self._apply(rec)

    def _apply(self, rec: dict) -> None:
        kind, rid = rec.get("kind"), rec.get("id")
        if kind == "submit":
            self.entries[rid] = JournalEntry(
                prompt=list(rec["prompt"]), max_new_tokens=int(rec["n"]),
                arrival=float(rec.get("arrival", 0.0)),
                pre=list(rec.get("pre", ())))
        elif kind == "tok" and rid in self.entries:
            self.entries[rid].toks.append(int(rec["t"]))
        elif kind == "evict" and rid in self.entries:
            # restart-from-scratch preemption: the discarded tokens are
            # regenerated verbatim (greedy determinism), so the journal
            # forgets them exactly like the latency accounting does
            self.entries[rid].toks.clear()
        elif kind == "end":
            self.statuses[rid] = rec["status"]
            if rid in self.entries:
                self.entries[rid].status = rec["status"]

    # ---------------- write ----------------

    def _write(self, rec: dict) -> None:
        self._apply(rec)
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")

    def record_submit(self, req: Request,
                      pre: Optional[List[int]] = None) -> None:
        if pre is None:
            pre = self._pending_pre.pop(req.id, [])
        self._write({"kind": "submit", "id": req.id,
                     "prompt": list(req.prompt), "n": req.max_new_tokens,
                     "arrival": req.arrival, "pre": list(pre)})

    def record_token(self, rid: int, tok: int) -> None:
        self._write({"kind": "tok", "id": rid, "t": int(tok)})

    def record_evict(self, rid: int) -> None:
        self._write({"kind": "evict", "id": rid})

    def record_end(self, req: Request, status: str) -> None:
        self._write({"kind": "end", "id": req.id, "status": status})

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # ---------------- replay assembly ----------------

    def replay_requests(self, requests: List[Request],
                        eos_id: Optional[int] = None) -> List[Request]:
        """The request list a replacement engine run should serve:
        never-journaled requests as-is; live (no terminal status)
        requests re-rooted at ``prompt + delivered`` with the remaining
        budget; terminated requests omitted.  Deadlines are dropped on
        replay — they were stamped on the dead process's clock, and the
        replacement run's clock restarts at zero (honoring stale stamps
        would mass-expire recovered work on arrival)."""
        out = []
        for req in requests:
            ent = self.entries.get(req.id)
            if ent is None:
                if req.id in self.statuses:
                    continue          # rejected/shed before ever admitted
                out.append(req)
                continue
            if ent.status is not None:
                continue
            rep, done = replay_one(ent, req, eos_id)
            if rep is None:
                # crashed between the final token and its end record
                self.record_end(req, "ok")
                continue
            self._pending_pre[req.id] = done
            out.append(rep)
        return out

    def outputs(self) -> Dict[int, List[int]]:
        """Delivered streams of every completed (``ok``) request."""
        return {rid: ent.delivered for rid, ent in self.entries.items()
                if ent.status == "ok"}


def replay_one(ent: JournalEntry, req: Request,
               eos_id: Optional[int] = None,
               arrival: float = 0.0) -> tuple:
    """Re-root ONE live journal entry as a fresh request — THE failover
    primitive shared by the single-engine supervisor (``replay_requests``)
    and the fleet router's replica migration: the replacement request's
    prompt embeds every delivered token with the remaining budget, so
    chunked prefill re-ingests the concatenation and the prefill-final
    argmax emits exactly the token the lost engine would have emitted
    next (greedy determinism).  The re-rooting is built from the ENTRY
    itself — ``ent.prompt`` already embeds the ``pre`` prefix of the
    submit it records, so ``ent.prompt + ent.toks`` is correct whether
    ``req`` is the original request OR an earlier replay's re-rooted
    one (a fault during a journal-resumed run; building from
    ``req.prompt + delivered`` there would double-embed the prefix).
    ``req`` contributes only identity (id, session).

    Returns ``(request, delivered)``; ``request`` is None when the
    stream is already complete (the engine died between the final token
    and its end record) — the caller records the terminal ``ok``.
    Deadlines are dropped (the caller's clock decides any fresh TTL);
    the session key survives so re-homed sticky placement still sees
    it."""
    done = ent.delivered
    if eos_id is not None and eos_id in done:
        done = done[:done.index(eos_id) + 1]
    remaining = ent.max_new_tokens + len(ent.pre) - len(done)
    if remaining <= 0 or (eos_id is not None and done
                          and done[-1] == eos_id):
        return None, done
    # tokens generated SINCE the recorded submit (done minus its pre,
    # after any EOS truncation above)
    since = done[len(ent.pre):]
    return Request(req.id, list(ent.prompt) + since, remaining,
                   arrival=arrival, replayed=True,
                   session=req.session), done


# ---------------- fleet journal assembly (serving/router) ----------------

def _entry_wins(a: JournalEntry, b: JournalEntry) -> bool:
    """Whether ``a`` is the more authoritative view of one request
    across per-replica journals: a terminal status beats a live entry
    (terminals fire exactly once fleet-wide), else the longer delivered
    stream wins (a migrated-to replica's entry embeds the donor's
    delivered prefix as ``pre``, so it strictly extends it)."""
    if (a.status is not None) != (b.status is not None):
        return a.status is not None
    return len(a.delivered) > len(b.delivered)


def merge_fleet_entries(journals) -> Dict[int, tuple]:
    """``{request id: (entry, owning journal)}`` — the authoritative
    per-request view across a fleet's per-replica journals."""
    best: Dict[int, tuple] = {}
    for j in journals:
        for rid, ent in j.entries.items():
            cur = best.get(rid)
            if cur is None or _entry_wins(ent, cur[0]):
                best[rid] = (ent, j)
    return best


def fleet_statuses(journals) -> Dict[int, str]:
    """Union of terminal statuses across per-replica journals (each
    request terminates exactly once fleet-wide, so no key collides)."""
    out: Dict[int, str] = {}
    for j in journals:
        out.update(j.statuses)
    return out


def fleet_outputs(journals) -> Dict[int, List[int]]:
    """Delivered streams of every completed request, fleet-wide —
    ``pre + toks`` of each request's authoritative entry, so a stream
    split across a failover (donor prefix + survivor suffix) comes back
    whole."""
    return {rid: ent.delivered
            for rid, (ent, _j) in merge_fleet_entries(journals).items()
            if ent.status == "ok"}


def fleet_replay_requests(journals, requests: List[Request],
                          eos_id: Optional[int] = None) -> tuple:
    """The request list a replacement FLEET run should serve, plus the
    ``{request id: delivered prefix}`` map the router stages into
    whichever replica's journal each replay lands on (per-replica
    journals can't pre-stage it — placement isn't known until route
    time).  Mirrors ``ReplayJournal.replay_requests`` over the merged
    per-replica view: never-journaled requests as-is, live requests
    re-rooted at ``prompt + delivered``, terminated requests omitted."""
    merged = merge_fleet_entries(journals)
    statuses = fleet_statuses(journals)
    todo: List[Request] = []
    pre: Dict[int, List[int]] = {}
    for req in requests:
        got = merged.get(req.id)
        if got is None:
            if req.id not in statuses:
                todo.append(req)
            continue
        ent, journal = got
        if ent.status is not None or req.id in statuses:
            # a terminal status ANYWHERE in the fleet wins over a stale
            # live entry in another journal (e.g. migrated off a dead
            # donor — whose on-disk entry stays live — then shed during
            # a drain before the survivor ever submitted it: the end
            # record is entry-less in the survivor's journal).  Each
            # request gets exactly ONE terminal status across runs.
            continue
        rep, done = replay_one(ent, req, eos_id)
        if rep is None:
            # crashed between the final token and its end record
            journal.record_end(req, "ok")
            continue
        todo.append(rep)
        pre[req.id] = done
    return todo, pre


def run_with_replay(make_engine: Callable[[], "object"],
                    requests: List[Request], *,
                    journal: Optional[ReplayJournal] = None,
                    journal_path: Optional[str] = None,
                    max_restarts: int = 3,
                    backoff_seconds: float = 0.0,
                    is_transient_fn: Callable[[BaseException],
                                              bool] = elastic.is_transient,
                    guard=None, time_fn=time.perf_counter) -> dict:
    """Serve ``requests`` through a journaled engine, surviving transient
    failures by rebuilding the engine (fresh pools — device state is
    presumed lost) and replaying live sequences through chunked prefill.

    ``make_engine`` is a zero-arg factory returning a fresh
    ``PagedDecodeEngine`` (the serving analogue of elastic's
    idempotent-from-checkpoint ``train_fn``).  Failure classification is
    ``train/elastic.is_transient`` — status-code-first, so a reworded
    device-loss message still replays while a deterministic shape bug
    still raises.  Returns the final run's stats dict with ``outputs``
    and ``statuses`` merged across every attempt (journal-complete) and
    ``faults`` aggregated, including the ``replays`` count.
    """
    if journal is None:
        journal = ReplayJournal(journal_path)
    totals: Counter = Counter()
    crash_harvests: List[dict] = []
    attempt = 0
    while True:
        engine = None
        try:
            # the rebuild itself can hit the still-recovering device —
            # it must be classified and retried like the run
            engine = make_engine()
            todo = journal.replay_requests(requests,
                                           eos_id=engine.serve.eos_id)
            res = engine.run(todo, journal=journal, guard=guard,
                             time_fn=time_fn)
            totals.update(engine.sched.counters)
            break
        except Exception as e:     # noqa: BLE001 — classified right below
            if engine is not None:
                totals.update(engine.sched.counters)
                if getattr(engine, "tracer", None) is not None:
                    # freeze the dying incarnation's spans at the last
                    # stamp its tracer saw; merged below so a replayed
                    # request's phase time accumulates across restarts
                    # instead of resetting (the failover span contract)
                    crash_harvests.append(
                        engine.tracer.harvest(reason="crashed"))
            if not is_transient_fn(e) or attempt >= max_restarts:
                raise
            attempt += 1
            print(f"[serving-recovery] transient failure ({e!r}); "
                  f"rebuilding engine, replay {attempt}/{max_restarts}")
            if backoff_seconds > 0:
                time.sleep(backoff_seconds)
    totals["replays"] += attempt
    if crash_harvests and res.get("trace") is not None:
        # spans survive crash/replay the same way they survive fleet
        # failover: merge every crashed incarnation's harvest with the
        # final attempt's, summing phase accumulators per request
        from mpi_tensorflow_tpu.serving.tracing import merge_spans

        harvests = crash_harvests + [res["trace"]["replicas"][0]]
        spans = merge_spans(harvests)
        steps = [r for h in harvests for r in h["steps"]]
        dropped = sum(h["steps_dropped"] for h in harvests)
        res["trace"] = {
            "enabled": True,
            "replicas": [{"pid": 0, "label": "engine", "spans": spans,
                          "steps": steps, "steps_dropped": dropped}],
            "spans": spans,
            "steps": len(steps),
            "steps_dropped": dropped,
        }
    res["outputs"] = journal.outputs()
    res["statuses"] = dict(journal.statuses)
    # res["tokens"]/elapsed_s/tokens_per_sec stay the FINAL attempt's own
    # (internally consistent throughput); the journal-merged stream total
    # across every attempt gets its own key
    res["delivered_tokens"] = sum(len(v) for v in res["outputs"].values())
    from mpi_tensorflow_tpu.utils.metrics_writer import faults_block

    res["faults"] = faults_block(totals)
    res["replays"] = attempt
    if "prefix" in res:
        # prefix-cache accounting merged across every attempt (each
        # attempt's counters were folded into ``totals`` above) — a
        # replayed prefill that re-hits the rebuilt trie counts, same
        # as the fault counters do.  Same constructor as the engine's
        # own prefix block, so the two shapes cannot drift
        from mpi_tensorflow_tpu.utils.metrics_writer import prefix_block

        res["prefix"] = prefix_block(
            totals, enabled=res["prefix"]["enabled"],
            trie_blocks=res["prefix"]["trie_blocks"])
    if "speculation" in res:
        # speculative-decoding accounting merged across attempts the
        # same way: drafts verified before a crash were real bandwidth
        # savings even though the replay regenerates their tokens
        from mpi_tensorflow_tpu.utils.metrics_writer import \
            speculation_block

        res["speculation"] = speculation_block(
            totals, enabled=res["speculation"]["enabled"],
            mode=res["speculation"]["mode"],
            draft_k=res["speculation"]["draft_k"],
            draft_auto=res["speculation"].get("draft_auto", "off"))
    return res
