"""Data-parallel replica serving: a fault-tolerant router over N engines.

The engine (serving/engine) scales UP with ``--serve-tp`` — one logical
pool, sharded over a mesh.  This layer scales OUT: ``N`` whole engine
replicas, each with its own pool, scheduler, prefix trie, drafter, and
— since fleet fault tolerance landed — its own ``ReplayJournal``,
fronted by one router that owns placement, health, and failover.

Placement policy, in order:

1. **Session affinity** — a request carrying ``Request.session`` sticks
   to the replica that served that session before (prefix-cache blocks,
   draft-model KV, and — in a real deployment — the network hop stay
   local).  The sticky map is LRU-BOUNDED: sessions with no live
   requests are evicted past ``max_sticky`` entries (affinity is a
   locality hint, not durable state), and a session whose replica is
   ejected re-homes on its next request.
2. **Health gate** — only replicas the circuit breaker calls routable
   (``healthy`` or ``probing``) take work.
3. **Prefix hint** (``--serve-prefix-route on``, prefix v2) — a
   router-level map from leading full-block token keys to the replica
   whose trie cached them (fed by each trie's root-child digest via
   ``PrefixCache.root_hook``); a sessionless request whose first block
   is cached somewhere is biased toward that replica WHEN LOAD PERMITS
   (within one waiting request of the least-loaded score).  Placement
   only: it never overrides the health gate and never changes tokens.
4. **Least load** — scored from the schedulers' OWN signals: waiting-
   queue depth (dominant), live-slot fraction, pool occupancy, shed
   rate.

Failure is a first-class event, not a crash.  Each replica runs the
SAME per-iteration body as ``engine.run`` (serving/iteration.EngineLoop
— the shared extraction that replaced the old ``tick()`` mirror), so
guard/journal/drain semantics exist in exactly one place.  When a tick
raises — a real device error or an injected ``FaultPlan`` fault — the
router classifies it with ``train/elastic.is_transient`` (status-code-
first, same as training) and:

- **migrates** the replica's live + queued requests: each journal-live
  entry is re-rooted at ``prompt + delivered`` (recovery.replay_one)
  and re-routed to a surviving replica, where chunked prefill replays
  the prefix token-identically — greedy outputs match an unfaulted run
  exactly (the PR 2 determinism contract, lifted from engine to fleet);
- **ejects** the replica: transient faults arm a capped exponential
  backoff (base ``ServeConfig.failover_backoff_ms``, doubled per
  consecutive fault, capped at 64x) after which the replica is rebuilt
  (``make_engine`` factory, else ``engine.reset()``) and PROBED — it
  takes traffic again and is readmitted after ``probe_ticks`` clean
  iterations.  Permanent faults (a deterministic bug, OOM) mark the
  replica DEAD: it never returns, and a fleet with every replica dead
  re-raises the last error rather than spinning.

SIGTERM drains the WHOLE fleet: admission stops, queued work sheds,
each replica finishes in-flight sequences within ``--serve-drain-ms``,
and the budget's hard edge cuts the rest as ``drained`` — every request
still leaves with exactly one terminal status, and
``Scheduler.check_quiescent`` is asserted on every surviving replica at
the end of ``run`` (the engine-level pool-leak invariant, fleet-wide).

Execution: ``run(parallel=True)`` drives each replica from its own
thread (single-owner scheduler state, locked inboxes, jax dispatch
releases the GIL); ``parallel=False`` interleaves replicas round-robin
on the calling thread — deterministic scheduling for tests.  Failover,
probing, and drain are main-thread decisions in both modes: a worker
that faults hands its exception to the router loop and exits; a rebuilt
replica gets a fresh worker.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import Counter, OrderedDict, deque
from typing import Dict, List, Optional

import numpy as np

from mpi_tensorflow_tpu.serving import recovery as rec_lib
from mpi_tensorflow_tpu.serving import scheduler as sched_lib
from mpi_tensorflow_tpu.serving import tracing
from mpi_tensorflow_tpu.serving.iteration import DrainTracker, EngineLoop
from mpi_tensorflow_tpu.train import elastic

#: replica circuit-breaker states
HEALTHY, EJECTED, PROBING, DEAD = "healthy", "ejected", "probing", "dead"


def default_parallelism() -> bool:
    """Whether threaded replica stepping can actually win on this host:
    only with >1 usable core.  On a single core the GIL's switch
    interval turns the thread ping-pong into pure overhead (measured
    ~10x slower than sequential on a 1-core container), while
    sequential round-robin matches a single engine minus dispatch
    overhead — so 1 core steps sequentially, and the real speedup claim
    belongs to multi-core (or multi-process / multi-chip) deployments."""
    try:
        return len(os.sched_getaffinity(0)) > 1
    except AttributeError:            # platforms without affinity API
        return (os.cpu_count() or 1) > 1


@dataclasses.dataclass
class ReplicaFault:
    """One scheduled injected fault: kill replica ``replica`` when its
    tick counter reaches ``at_step`` (1-based; deterministic under
    ``parallel=False``).  ``kind`` picks the classification the injected
    error carries — ``transient`` raises with an UNAVAILABLE status code
    (eject + backoff + probe), ``permanent`` with FAILED_PRECONDITION
    (dead forever) — so the fault flows through exactly the status-code-
    first ``elastic.is_transient`` path a real PJRT error would."""
    replica: int
    at_step: int
    kind: str = "transient"

    def __post_init__(self):
        if self.kind not in ("transient", "permanent"):
            raise ValueError(f"fault kind must be transient|permanent, "
                             f"got {self.kind!r}")
        if self.at_step < 1 or self.replica < 0:
            raise ValueError(f"bad fault plan entry: {self}")


class FaultPlan:
    """The replica fault-injection seam: a list of ``ReplicaFault``
    entries checked at the TOP of every replica tick (before the inbox
    snapshot, so queued handoffs are never half-consumed).  Each entry
    fires at most once; ``fired`` records what actually went off."""

    def __init__(self, faults: List[ReplicaFault]):
        self.faults = list(faults)
        self.fired: List[ReplicaFault] = []

    def check(self, replica: int, step: int) -> None:
        for f in list(self.faults):
            if f.replica == replica and step >= f.at_step:
                self.faults.remove(f)
                self.fired.append(f)
                code = ("UNAVAILABLE" if f.kind == "transient"
                        else "FAILED_PRECONDITION")
                raise RuntimeError(
                    f"{code}: injected replica fault (FaultPlan: "
                    f"replica {replica} at tick {step}, {f.kind})")


@dataclasses.dataclass
class ReplicaHealth:
    """Circuit-breaker state of one replica."""
    state: str = HEALTHY
    faults: int = 0               # consecutive transient faults (reset
                                  # when a probe readmits the replica)
    backoff_s: float = 0.0        # current probe backoff
    retry_at: float = 0.0         # run-clock stamp when a probe may run
    probe_ticks: int = 0          # clean ticks since the probe started


class ReplicaRouter:
    """Route requests across engine replicas; survive replica failure.

    ``engines``: fully constructed ``PagedDecodeEngine`` replicas (they
    may share model/params arrays — each still owns its pools and jit
    caches).  ``make_engine``: optional zero-arg factory used to rebuild
    an ejected replica at probe time (real device loss needs fresh
    pools); without it the probe calls ``engine.reset()`` — fresh
    host/pool state, warmed jit caches kept, which is exactly right for
    in-process faults and keeps the zero-recompile contract intact.
    ``probe_ticks``: clean iterations a probing replica must complete
    before readmission.  ``max_sticky``: LRU bound on the session
    affinity map (entries for sessions with live requests are never
    evicted).  ``reset()`` resets every replica AND the health/affinity
    state — a fresh fleet for a fresh trace replay.
    """

    #: lock discipline, machine-checked by graft-lint's LOCK-HELD pass:
    #: every access to these attrs must sit inside `with self._lock`
    #: (the PR 7 sticky-map race class — see docs/ANALYSIS.md)
    _GUARDED_BY = {"_lock": ("_sticky", "_session_live", "_outstanding",
                             "fleet_counters", "_drain_counts",
                             "_prefix_owner")}

    def __init__(self, engines: List, *, make_engine=None,
                 probe_ticks: int = 4, max_sticky: int = 1024,
                 prefix_route: Optional[bool] = None):
        if not engines:
            raise ValueError("ReplicaRouter needs >= 1 engine replica")
        if probe_ticks < 1 or max_sticky < 1:
            raise ValueError(f"bad router policy: probe_ticks "
                             f"{probe_ticks} (>= 1), max_sticky "
                             f"{max_sticky} (>= 1)")
        self.engines = list(engines)
        self.make_engine = make_engine
        self.probe_ticks = probe_ticks
        self.max_sticky = max_sticky
        # prefix-aware placement (prefix v2): None resolves through the
        # fleet's ServeConfig (--serve-prefix-route) — the explicit
        # boolean exists for bench's hint-on-vs-off A/B over one fleet
        self._prefix_route = (engines[0].serve.prefix_route == "on"
                              if prefix_route is None
                              else bool(prefix_route))
        base = engines[0].serve.failover_backoff_ms / 1e3
        self.backoff_base_s = base
        self.backoff_cap_s = base * 64
        self._lock = threading.Lock()
        self._running = False
        self._cold_state()

    def _cold_state(self) -> None:
        """Fresh fleet state (construction + ``reset``)."""
        n = len(self.engines)
        # graft-lint: lock-ok(cold init: no worker threads exist yet)
        self._sticky: OrderedDict = OrderedDict()   # session -> replica
        # graft-lint: lock-ok(cold init: no worker threads exist yet)
        self._session_live: Counter = Counter()     # session -> live reqs
        self.placements: Dict[int, int] = {}        # request id -> replica
        self._routed = [0] * n
        self.health = [ReplicaHealth() for _ in range(n)]
        # graft-lint: lock-ok(cold init: no worker threads exist yet)
        self.fleet_counters: Counter = Counter()
        # graft-lint: lock-ok(cold init: no worker threads exist yet)
        self._prefix_owner: Dict = {}   # leading block key -> replica
        self._last_error: Optional[BaseException] = None

    def reset(self) -> None:
        for eng in self.engines:
            eng.reset()
        self._cold_state()

    # ---------------- placement ----------------

    def routable(self) -> List[int]:
        """Replica indices the health gate admits traffic to."""
        return [i for i, h in enumerate(self.health)
                if h.state in (HEALTHY, PROBING)]

    def load_score(self, i: int, inbox_depth: int = 0) -> float:
        """One replica's load, from its scheduler's own signals.  Queue
        depth dominates (integer weight per waiting request); live-slot
        fraction, pool occupancy, and shed rate are sub-1 tie-breakers
        that push new work away from saturated or shedding replicas."""
        eng = self.engines[i]
        sched = eng.sched
        waiting = len(sched.waiting) + inbox_depth
        live = sum(1 for s in sched.slots if s is not None)
        occ = eng.allocator.num_used / max(1, eng.serve.num_blocks - 1)
        shed_rate = sched.counters.get("shed", 0) / max(1, self._routed[i])
        return (waiting
                + live / max(1, eng.serve.max_slots) * 0.5
                + occ * 0.3
                + shed_rate * 0.2)

    def route(self, req: sched_lib.Request,
              inbox_depths: Optional[List[int]] = None) -> Optional[int]:
        """Pick the replica for ``req``: sticky session first (health-
        gated — an ejected home re-homes the session), else least-loaded
        among routable replicas (ties break to the lowest index, so an
        idle fleet fills deterministically).  None = nothing routable
        right now (every replica ejected/dead; the caller holds the
        request until a probe readmits one)."""
        ok = self.routable()
        if not ok:
            return None
        key = req.session
        i = None
        if key is not None:
            # read + health-check + LRU-touch under ONE lock hold: the
            # worker-side terminal hook trims this map concurrently, so
            # a get outside the lock could name a key the trim evicts
            # before the touch
            with self._lock:
                i = self._sticky.get(key)
                if i is not None and self.health[i].state \
                        not in (HEALTHY, PROBING):
                    # stale affinity to an ejected/dead replica (it
                    # re-armed after the failover sweep): re-home now
                    self._sticky.pop(key, None)
                    self.fleet_counters["sticky_rehomed"] += 1
                    i = None
                elif i is not None:
                    self._sticky.move_to_end(key)   # LRU touch
        if i is None and self._prefix_route:
            # prefix-aware hint (prefix v2): if some replica's trie
            # caches this prompt's LEADING full block, send the request
            # there — its expected cached prefix (and everything the
            # radix walk finds below that block) beats a cold replica's
            # full prefill.  Load-bounded: the owner must score within
            # ONE waiting request of the least-loaded routable replica,
            # so the hint can shape placement but never pile work onto
            # a saturated replica; and it is health-gated by the same
            # ``ok`` set as every other placement.  Tokens never change
            # — a mis-hint only costs a cache miss.
            bs = self.engines[0].serve.block_size
            if len(req.prompt) >= bs:
                with self._lock:
                    owner = self._prefix_owner.get(tuple(req.prompt[:bs]))
                if owner is not None and owner in ok:
                    depths = inbox_depths or [0] * len(self.engines)
                    best = min(self.load_score(j, depths[j]) for j in ok)
                    if self.load_score(owner, depths[owner]) <= best + 1.0:
                        i = owner
                        with self._lock:
                            self.fleet_counters["router_prefix_hits"] += 1
                            if key is not None:
                                # hint placements seed affinity too:
                                # the session's later turns should find
                                # the prefix where this one put it
                                self._sticky[key] = i
                                self._sticky.move_to_end(key)
        if i is None:
            depths = inbox_depths or [0] * len(self.engines)
            i = min(ok, key=lambda j: (self.load_score(j, depths[j]), j))
            if key is not None:
                with self._lock:
                    self._sticky[key] = i
                    self._sticky.move_to_end(key)
        with self._lock:
            if key is not None and req.id not in self.placements:
                # first placement of this request pins its session live
                # (a MIGRATED request re-routes without re-pinning — its
                # one terminal notification un-pins exactly once)
                self._session_live[key] += 1
        self._routed[i] += 1
        self.placements[req.id] = i
        return i

    # ---------------- terminal / sticky bookkeeping ----------------

    def _note_prefix(self, i: int, key, present: bool) -> None:
        """Per-replica trie digest sink (``PrefixCache.root_hook``): a
        leading full-block token key entered (``present``) or left
        replica ``i``'s trie.  Last inserter wins on collision — a key
        cached on two replicas routes to the most recent one, which is
        also the most recently used (warmest) copy.  Runs on the
        replica's own worker thread, hence the lock."""
        with self._lock:
            if present:
                self._prefix_owner[key] = i
            elif self._prefix_owner.get(key) == i:
                # only the recorded owner's eviction clears the entry:
                # another replica's eviction must not erase a mapping
                # that still names a live copy elsewhere
                del self._prefix_owner[key]

    def _notify_terminal(self, i: int, req, status: str) -> None:
        """Chained behind each adopted engine's own terminal hook: one
        call per request fleet-wide (terminals fire exactly once)."""
        if not self._running:
            return
        with self._lock:
            self._outstanding.discard(req.id)
            if self._drain.draining:
                self._drain_counts[status] += 1
            s = req.session
            if s is not None and s in self._session_live:
                self._session_live[s] -= 1
                if self._session_live[s] <= 0:
                    del self._session_live[s]
            self._trim_sticky_locked()

    def _trim_sticky_locked(self) -> None:
        """Bound the affinity map: evict LRU sessions with no live
        requests once past ``max_sticky`` — terminal requests must not
        pin map entries forever (the map is a locality hint; an evicted
        session simply re-places by load on its next request)."""
        if len(self._sticky) <= self.max_sticky:
            return
        for k in list(self._sticky):
            if len(self._sticky) <= self.max_sticky:
                break
            if k not in self._session_live:
                del self._sticky[k]
                self.fleet_counters["sticky_evicted"] += 1

    def stats(self) -> dict:
        """Router health/affinity accounting (the fleet_faults block
        plus the sticky-map hygiene counters), plus a per-replica prefix
        trie snapshot — the fleet-level view of where cached prefixes
        live and how hard each trie is working."""
        from mpi_tensorflow_tpu.utils.metrics_writer import \
            fleet_faults_block

        # trie/scheduler reads are worker-owned state: best-effort
        # snapshots (int reads are atomic under the GIL; same contract
        # as _observe_fleet), taken OUTSIDE the router lock
        tries = []
        for i, eng in enumerate(self.engines):
            pc = eng.prefix_cache
            row = {"replica": i, "enabled": pc is not None}
            if pc is not None:
                row.update(pc.stats())       # blocks/inserted/evicted
                row["hit_tokens"] = int(
                    eng.sched.counters.get("prefix_hit_tokens", 0))
                row["gen_inserted_blocks"] = int(
                    eng.sched.counters.get("prefix_gen_inserted_blocks",
                                           0))
                row["occupancy"] = round(
                    pc.num_blocks / max(1, eng.serve.num_blocks - 1), 4)
            tries.append(row)
        # one lock hold for the whole snapshot: stats() is callable
        # mid-run, and an unlocked read races the workers' updates
        with self._lock:
            return {
                "sticky_sessions": len(self._sticky),
                "sticky_live_sessions": len(self._session_live),
                "sticky_capacity": self.max_sticky,
                "sticky_rehomed":
                    int(self.fleet_counters["sticky_rehomed"]),
                "sticky_evicted":
                    int(self.fleet_counters["sticky_evicted"]),
                "prefix_route": self._prefix_route,
                "prefix_owner_keys": len(self._prefix_owner),
                "router_prefix_hits":
                    int(self.fleet_counters["router_prefix_hits"]),
                "replica_tries": tries,
                "health": [dataclasses.asdict(h) for h in self.health],
                "fleet_faults": fleet_faults_block(self.fleet_counters),
            }

    # ---------------- replica binding / failover ----------------

    def _bind(self, i: int, engine) -> None:
        """Adopt ``engine`` as replica ``i``: fresh iteration loop bound
        to the replica's journal, terminal hook chained to the router's
        bookkeeping (the engine's own hook — drafter release + journal
        record_end — still runs first, preserving tok-then-end order)."""
        self.engines[i] = engine

        def hook(req, status, _i=i, _fn=engine._on_terminal):
            _fn(req, status)
            self._notify_terminal(_i, req, status)

        engine.sched.on_terminal = hook
        if self._prefix_route and engine.prefix_cache is not None:
            # feed the router's owner map from this replica's trie
            # digest; installed here (not __init__) because reset() and
            # probe rebuilds create FRESH PrefixCache objects, and every
            # incarnation reaches traffic through _bind
            engine.prefix_cache.root_hook = (
                lambda key, present, _i=i:
                self._note_prefix(_i, key, present))
        self._loops[i] = EngineLoop(engine, self._journals[i])

    def _failover(self, i: int, exc: BaseException, now: float) -> None:
        """Replica ``i`` failed: archive its accounting, eject it
        (backoff or dead), re-home its sticky sessions, and migrate its
        live + queued requests to the router's pending list — each
        journal-live entry re-rooted at ``prompt + delivered`` so a
        surviving replica replays it token-identically through chunked
        prefill."""
        self._last_error = exc
        transient = elastic.is_transient(exc)
        h = self.health[i]
        eng = self.engines[i]
        print(f"[serving-router] replica {i} "
              f"{'transient' if transient else 'PERMANENT'} fault "
              f"({exc!r}); migrating its work")
        # archive the dead incarnation's accounting: latency samples of
        # already-delivered tokens stay valid (the client keeps that
        # prefix — replay regenerates only what follows), and its fault
        # counters must survive the rebuild
        loop = self._loops[i]
        if loop is not None:
            self._lat_archive[i].extend(loop.latencies())
            # finish stamps of the dead incarnation: completions it
            # recorded stay valid; a migrated request's newer stamp on
            # a survivor wins at aggregation (max merge)
            for rid, t in loop.last_emit.items():
                self._finish_archive[rid] = max(
                    self._finish_archive.get(rid, t), t)
            # first-token stamps: the client already HOLDS the donor's
            # delivered prefix (replay only regenerates what follows),
            # so a request's TTFT is its EARLIEST incarnation's first
            # emit (min merge — the mirror of the finish stamps' max)
            for rid, t in loop.first_emit.items():
                self._first_archive[rid] = min(
                    self._first_archive.get(rid, t), t)
            self._tokens_archive[i] += loop.tokens
            self._peak_queue[i] = max(self._peak_queue[i],
                                      loop.peak_queue)
            self._counter_snap[i].update(eng.sched.counters)
            self._evict_snap[i] += eng.sched.evictions
            if loop.tracer is not None:
                # harvest the dead incarnation's trace: open spans are
                # closed at the failure instant and stamped "migrated",
                # so the victim's queue/prefill/decode time ACCUMULATES
                # into the fleet merge instead of resetting when the
                # replay re-roots it (replay_one resets arrival).
                # Main-router-thread state, same ownership as
                # _lat_archive — no lock needed.
                self._trace_archive[i].append(
                    loop.tracer.harvest(now, reason="migrated"))
        self._loops[i] = None
        with self._lock:
            self.fleet_counters["failovers"] += 1
            self.fleet_counters["ejections"] += 1
            stale = [k for k, v in self._sticky.items() if v == i]
            for k in stale:
                del self._sticky[k]
            self.fleet_counters["sticky_rehomed"] += len(stale)
            # prefix hints to the dead incarnation are stale too: its
            # pools are gone, so routing toward it buys nothing (the
            # hint path also health-gates, but the map should not pin
            # memory for a replica that may never return)
            for k in [k for k, v in self._prefix_owner.items() if v == i]:
                del self._prefix_owner[k]
        if transient:
            h.faults += 1
            h.backoff_s = min(self.backoff_cap_s,
                              self.backoff_base_s * 2 ** (h.faults - 1))
            h.retry_at = now + h.backoff_s
            h.state = EJECTED
        else:
            h.state = DEAD
        # migration set: requests handed over but not yet submitted
        # (inbox) ride as-is; journal-live requests re-root at
        # prompt + delivered.  Both re-enter the router's pending list
        # due immediately and re-route on the next loop pass.
        with self._inbox_locks[i]:
            moved = list(self._inboxes[i])
            self._inboxes[i].clear()
        journal = self._journals[i]
        eos = eng.serve.eos_id
        with self._lock:
            live = [rid for rid, ent in journal.entries.items()
                    if ent.status is None and rid in self._outstanding]
        replay_tokens = 0
        for rid in sorted(live):
            req = self._requests_by_id.get(rid)
            if req is None:
                continue
            rep, done = rec_lib.replay_one(journal.entries[rid], req,
                                           eos, arrival=now)
            if rep is None:
                # died between the final token and its end record: the
                # entry is complete — terminate it in place (it stays
                # in the journal as the request's output stream)
                journal.record_end(req, "ok")
                self._notify_terminal(i, req, "ok")
                continue
            # the donor's in-memory live entry is now STALE — the
            # request's authoritative stream continues wherever the
            # replay lands.  Drop it, or a readmitted donor faulting a
            # SECOND time would re-migrate a request still live on a
            # survivor (duplicate serving; worse, the duplicate's
            # record_submit would overwrite the live entry and void its
            # tokens).  In-memory only: the on-disk record stays, and a
            # full-process crash reload resolves it through the merge
            # (terminal status wins, else longest delivered).
            journal.entries.pop(rid, None)
            self._pre[rid] = done
            replay_tokens += len(rep.prompt)
            moved.append(rep)
        # surviving workers bump fleet_counters under the lock; the
        # failover path must too or the += read-modify-write races them
        with self._lock:
            self.fleet_counters["replay_tokens"] += replay_tokens
            self.fleet_counters["migrated_requests"] += len(moved)
        if moved:
            self._pending = sorted(self._pending + moved,
                                   key=lambda r: r.arrival)

    def _maybe_probe(self, now: float) -> List[int]:
        """Rebuild ejected replicas whose backoff has elapsed and mark
        them PROBING (they take traffic again; ``probe_ticks`` clean
        iterations readmit them).  Returns the replica indices revived
        this call — the parallel loop starts a fresh worker for each."""
        revived = []
        for i, h in enumerate(self.health):
            if h.state != EJECTED or now < h.retry_at:
                continue
            if self.make_engine is not None:
                eng = self.make_engine()
            else:
                eng = self.engines[i]
                eng.reset()     # fresh pools/scheduler, warm jit caches
            self._bind(i, eng)
            h.state = PROBING
            h.probe_ticks = 0
            revived.append(i)
        return revived

    # ---------------- the per-replica tick ----------------

    def _tick(self, i: int, time_fn, t0: float) -> bool:
        """One iteration for replica ``i`` — the SHARED engine body
        (serving/iteration.EngineLoop) plus the router's handoff/drain/
        probe edges.  Returns whether any work moved.  Only replica
        ``i``'s thread (or the sequential caller) runs this —
        scheduler/pool state stays single-owner."""
        self._ticks[i] += 1
        if self._fault_plan is not None:
            # the injection seam fires BEFORE the inbox snapshot so a
            # handoff is never half-consumed by a dying replica
            self._fault_plan.check(i, self._ticks[i])
        eng = self.engines[i]
        loop = self._loops[i]
        with self._inbox_locks[i]:
            todo = list(self._inboxes[i])
            self._inboxes[i].clear()
        draining = self._drain.draining
        if draining and not self._drain_shed_done[i]:
            # fleet drain: this replica sheds its waiting queue once;
            # in-flight sequences keep running inside the budget
            self._drain_shed_done[i] = True
            eng.sched.shed_waiting()
        if self._abort_req[i] and not self._abort_done[i]:
            # the drain budget's hard edge
            self._abort_done[i] = True
            eng.sched.abort_live("drained")
        now = time_fn() - t0
        for req in todo:
            if draining:
                eng.sched.fail_request(req, "shed")
                continue
            # a migrated/replayed request re-admits AT THE FRONT (it
            # already waited its turn once) with its delivered prefix
            # staged into this replica's journal
            loop.submit(req, pre=self._pre.pop(req.id, None),
                        front=req.replayed)
        emitted = loop.iterate(now, time_fn, t0)
        h = self.health[i]
        if h.state == PROBING:
            h.probe_ticks += 1
            if h.probe_ticks >= self.probe_ticks:
                # readmitted: the fault streak is broken, so the
                # "consecutive faults" backoff restarts at base — an
                # isolated fault hours later must not pay an escalated
                # penalty (flapping replicas re-escalate fast anyway:
                # a fault during PROBING never reaches this reset)
                h.state = HEALTHY
                h.faults = 0
                h.backoff_s = 0.0
                with self._lock:
                    self.fleet_counters["readmissions"] += 1
        return bool(todo) or bool(emitted) or eng._progressed

    # ---------------- the serve loop ----------------

    def run(self, requests: List[sched_lib.Request],
            time_fn=time.perf_counter, *,
            parallel: Optional[bool] = None, guard=None,
            journals: Optional[List] = None,
            replay_pre: Optional[Dict[int, List[int]]] = None,
            fault_plan: Optional[FaultPlan] = None,
            advisor=None) -> dict:
        """Serve ``requests`` (replayed against their ``arrival``
        stamps) across the replicas to completion, failing over replica
        faults.  Latency semantics match ``engine.run`` (the SHARED
        iteration body guarantees it); the result adds per-replica
        metrics, the fleet drain outcome, and the ``fleet_faults``
        block.

        ``parallel``: None (default) auto-selects — threads when the
        host has >1 usable core (``default_parallelism``), sequential
        round-robin otherwise; True/False force a mode.  ``guard``
        wires SIGTERM to a fleet-wide graceful drain.  ``journals``:
        one ``ReplayJournal`` per replica (pre-loaded journals resume a
        crashed fleet — pair with ``recovery.fleet_replay_requests``
        and pass its ``pre`` map as ``replay_pre``); None = fresh
        memory-only journals, which is what arms in-process failover.
        ``fault_plan`` injects deterministic replica faults (tests/
        bench).  ``advisor`` (serving/autoscale.ScaleAdvisor) observes
        the FLEET-level load signals — router queue + summed replica
        queues, mean pool occupancy, fleet shed rate — once per router
        loop pass; its advisory decision log rides the result as the
        ``autoscale`` block."""
        if parallel is None:
            parallel = default_parallelism()
        n = len(self.engines)
        if journals is not None and len(journals) != n:
            raise ValueError(f"need one journal per replica: got "
                             f"{len(journals)} for {n} replicas")
        self._journals = (list(journals) if journals is not None
                          else [rec_lib.ReplayJournal()
                                for _ in range(n)])
        self._fault_plan = fault_plan
        self._pre = dict(replay_pre or {})
        self._requests_by_id = {r.id: r for r in requests}
        # graft-lint: lock-ok(run setup: worker threads not started yet)
        self._outstanding = set(self._requests_by_id)
        self._pending = sorted(requests, key=lambda r: r.arrival)
        self._inboxes = [deque() for _ in range(n)]
        self._inbox_locks = [threading.Lock() for _ in range(n)]
        self._ticks = [0] * n
        self._loops: List[Optional[EngineLoop]] = [None] * n
        self._lat_archive: List[List[float]] = [[] for _ in range(n)]
        self._finish_archive: Dict[int, float] = {}
        self._first_archive: Dict[int, float] = {}
        self._advisor = advisor
        self._tokens_archive = [0] * n
        self._peak_queue = [0] * n
        self._counter_snap = [Counter() for _ in range(n)]
        self._evict_snap = [0] * n
        # trace harvests of dead incarnations, per replica slot — the
        # _lat_archive idiom: written only by the main router thread at
        # failover, merged with live loops' harvests at aggregation
        # (NOT under _lock; span state never crosses threads)
        self._trace_archive: List[List[dict]] = [[] for _ in range(n)]
        self._drain = DrainTracker(self.engines[0].serve.drain_ms)
        # graft-lint: lock-ok(run setup: worker threads not started yet)
        self._drain_counts: Counter = Counter()
        self._drain_shed_done = [False] * n
        self._abort_req = [False] * n
        self._abort_done = [False] * n
        for i, h in enumerate(self.health):
            if h.state == EJECTED:
                # stamps from a previous run's clock are stale; re-arm
                # the backoff from this run's zero
                h.retry_at = h.backoff_s
            if h.state in (HEALTHY, PROBING):
                self._bind(i, self.engines[i])
        self._running = True
        t0 = time_fn()
        try:
            if parallel:
                self._run_parallel(time_fn, t0, guard)
            else:
                self._run_sequential(time_fn, t0, guard)
            elapsed = time_fn() - t0
            return self._aggregate(parallel, elapsed)
        finally:
            self._running = False
            for i, eng in enumerate(self.engines):
                if self._loops[i] is not None:
                    # un-chain the router hook: a later engine.run on
                    # this engine must not touch dead run state
                    eng.sched.on_terminal = eng._on_terminal

    def _route_due(self, now: float, all_due: bool = False) -> None:
        while self._pending and (all_due
                                 or self._pending[0].arrival <= now):
            depths = [len(b) for b in self._inboxes]
            i = self.route(self._pending[0], depths)
            if i is None:
                return              # nothing routable; hold the queue
            req = self._pending.pop(0)
            with self._inbox_locks[i]:
                self._inboxes[i].append(req)

    def _drain_edges(self, now: float, guard) -> None:
        """Fleet drain state machine, run from the main loop: SIGTERM
        stops admission and pushes everything queued at the router to
        the replicas (whose draining ticks shed it — one terminal per
        request through the normal scheduler/journal path); the budget's
        hard edge arms per-replica abort."""
        if guard is not None and guard.should_stop \
                and not self._drain.draining:
            self._drain.start(now)
            self._route_due(now, all_due=True)
            for req in self._pending:   # nothing routable: shed direct
                self._terminal_direct(req, "shed")
            self._pending = []
        if self._drain.expired(now) and not all(self._abort_req):
            self._abort_req = [True] * len(self.engines)
            for req in self._pending:
                self._terminal_direct(req, "shed")
            self._pending = []

    def _terminal_direct(self, req, status: str) -> None:
        """Terminal for a request no routable replica can shed (every
        replica ejected/dead at drain time): record straight into
        journal 0 so the fleet status/outstanding accounting stays
        exact."""
        self._journals[0].record_end(req, status)
        self._notify_terminal(0, req, status)

    def _fleet_dead(self) -> bool:
        """True when no replica can ever serve again (all DEAD)."""
        return all(h.state == DEAD for h in self.health)

    def _observe_fleet(self, now: float) -> None:
        """Feed the ScaleAdvisor one fleet-level observation: router
        backlog plus summed replica queues, mean occupancy/live fraction
        over live replicas, fleet shed rate.  Reads of worker-owned
        scheduler state are best-effort snapshots (len() on a deque/list
        is atomic under the GIL); advice tolerates a stale tick."""
        if self._advisor is None:
            return
        qd = len(self._pending) + sum(len(b) for b in self._inboxes)
        occ, lf, live = 0.0, 0.0, 0
        shed = 0
        backlog = 0.0
        for i, eng in enumerate(self.engines):
            shed += int(eng.sched.counters.get("shed", 0))
            if self._loops[i] is None:
                continue
            live += 1
            qd += len(eng.sched.waiting)
            occ += eng.allocator.num_used / max(1, eng.serve.num_blocks - 1)
            lf += len(eng.sched.live_slots()) / eng.serve.max_slots
            # admitted-but-unprefilled work, summed fleet-wide in
            # prefill-chunk units (the same signal engine.load_signals
            # feeds a single-engine advisor)
            backlog += (eng.sched.prefill_backlog_tokens
                        / max(1, eng.serve.prefill_chunk))
        routed = sum(self._routed)
        self._advisor.observe(
            now,
            queue_depth=qd,
            occupancy=occ / live if live else 0.0,
            live_fraction=lf / live if live else 0.0,
            shed_rate=shed / max(1, routed),
            prefill_backlog=backlog)

    def _run_sequential(self, time_fn, t0, guard) -> None:
        while True:
            now = time_fn() - t0
            self._drain_edges(now, guard)
            self._maybe_probe(now)
            self._route_due(now)
            self._observe_fleet(now)
            progressed = False
            for i in list(self.routable()):
                try:
                    progressed = self._tick(i, time_fn, t0) or progressed
                except Exception as e:  # noqa: BLE001 — classified in
                    self._failover(i, e, time_fn() - t0)   # _failover
                    progressed = True
            with self._lock:
                done = not self._outstanding
            if done:
                return
            if not self.routable():
                if self._fleet_dead():
                    raise self._last_error
                progressed = False      # every replica in backoff: wait
            if not progressed:
                delay = 1e-3
                if self._pending and self.routable():
                    # clamp to the next arrival ONLY while someone can
                    # take it — with the whole fleet in backoff an
                    # overdue arrival would clamp the delay to zero and
                    # busy-spin the core for the entire backoff window
                    delay = min(delay, max(
                        0.0,
                        self._pending[0].arrival - (time_fn() - t0)))
                if delay > 0:
                    time.sleep(delay)

    def _run_parallel(self, time_fn, t0, guard) -> None:
        stop = threading.Event()
        failures: List[tuple] = []
        threads: Dict[int, threading.Thread] = {}

        def worker(i: int) -> None:
            try:
                while True:
                    progressed = self._tick(i, time_fn, t0)
                    if not progressed:
                        if stop.is_set():
                            with self._inbox_locks[i]:
                                empty = not self._inboxes[i]
                            if empty and self.engines[i].sched.all_done():
                                return
                        time.sleep(1e-3)
            except BaseException as e:   # noqa: BLE001 — handed to the
                with self._lock:         # router loop for failover
                    failures.append((i, e))

        def start(i: int) -> None:
            t = threading.Thread(target=worker, args=(i,),
                                 name=f"serve-replica-{i}", daemon=True)
            threads[i] = t
            t.start()

        for i in self.routable():
            start(i)
        try:
            while True:
                now = time_fn() - t0
                with self._lock:
                    fails, failures[:] = list(failures), []
                for i, e in fails:
                    t = threads.pop(i, None)
                    if t is not None:
                        t.join()        # the worker exits on fault
                    self._failover(i, e, time_fn() - t0)
                self._drain_edges(now, guard)
                for i in self._maybe_probe(now):
                    start(i)
                self._route_due(now)
                self._observe_fleet(now)
                with self._lock:
                    done = not self._outstanding
                if done:
                    return
                if not self.routable() and self._fleet_dead():
                    raise self._last_error
                time.sleep(1e-3)
        finally:
            stop.set()
            for t in threads.values():
                t.join()

    # ---------------- aggregation ----------------

    def _aggregate(self, parallel: bool, elapsed: float) -> dict:
        from mpi_tensorflow_tpu.utils.metrics_writer import (
            faults_block, fleet_faults_block, prefix_block)

        totals: Counter = Counter()
        per_replica = []
        flat: List[float] = []
        for i, eng in enumerate(self.engines):
            live = self._loops[i] is not None
            cnts = Counter(self._counter_snap[i])
            tokens_i = self._tokens_archive[i]
            lats = list(self._lat_archive[i])
            evictions = self._evict_snap[i]
            peak_q = self._peak_queue[i]
            if live:
                # fleet-wide pool-leak invariant: every surviving
                # replica must be quiescent, failover or not (the
                # engine-level check, asserted per replica)
                eng.sched.check_quiescent()
                if eng.drafter is not None:
                    eng.drafter.check_quiescent()
                cnts.update(eng.sched.counters)
                tokens_i += self._loops[i].tokens
                lats += self._loops[i].latencies()
                evictions += eng.sched.evictions
                peak_q = max(peak_q, self._loops[i].peak_queue)
            totals.update(cnts)
            flat += lats
            routed = self._routed[i]
            shed = int(cnts.get("shed", 0))
            per_replica.append({
                "replica": i,
                "health": self.health[i].state,
                "transient_faults": self.health[i].faults,
                "requests_routed": routed,
                "tokens": tokens_i,
                "tokens_per_sec": (tokens_i / elapsed
                                   if elapsed > 0 else 0.0),
                "queue_depth_peak": peak_q,
                "pool_occupancy_peak": round(
                    eng.peak_blocks_in_use
                    / max(1, eng.serve.num_blocks - 1), 4),
                "peak_live_blocks": eng.peak_live_blocks,
                "shed": shed,
                "shed_rate": round(shed / max(1, routed), 4),
                "evictions": evictions,
                "faults": faults_block(cnts),
            })
        # outputs/statuses come from the per-replica journals — the one
        # view that stays whole across failover (a migrated stream is
        # donor prefix + survivor suffix) and across process restarts
        outputs = rec_lib.fleet_outputs(self._journals)
        statuses = rec_lib.fleet_statuses(self._journals)
        # finish stamps: dead-incarnation archive, then live loops — a
        # migrated request's survivor stamp (strictly later) wins
        finish = dict(self._finish_archive)
        first = dict(self._first_archive)
        for lp in self._loops:
            if lp is not None:
                for rid, t in lp.last_emit.items():
                    finish[rid] = max(finish.get(rid, t), t)
                for rid, t in lp.first_emit.items():
                    first[rid] = min(first.get(rid, t), t)
        lat = np.asarray(flat) if flat else np.zeros(1)
        total = sum(len(v) for v in outputs.values())
        # workers are joined, but late probe/failover stragglers may
        # still hold references: snapshot the shared state in one hold
        with self._lock:
            fleet_counters = Counter(self.fleet_counters)
            drain_counts = Counter(self._drain_counts)
            sticky_n = len(self._sticky)
        drain = self._drain.result_counts(drain_counts)
        # fleet prefix view: scheduler counters summed over replicas
        # plus the router's own hint-hit count — the aggregate the
        # prefix-route A/B compares (per-replica detail is in stats())
        fleet_prefix = prefix_block(
            totals,
            enabled=any(e.prefix_cache is not None for e in self.engines),
            trie_blocks=sum(e.prefix_cache.num_blocks
                            for e in self.engines
                            if e.prefix_cache is not None),
            router_prefix_hits=int(
                fleet_counters["router_prefix_hits"]))
        res = {
            "parallel": parallel,
            "outputs": outputs,
            "statuses": statuses,
            "faults": faults_block(totals),
            "fleet_faults": fleet_faults_block(fleet_counters),
            "drain": drain,
            "health": [h.state for h in self.health],
            "replicas": per_replica,
            "num_replicas": len(self.engines),
            "prefix": fleet_prefix,
            "sticky_sessions": sticky_n,
            "placements": dict(self.placements),
            "tokens": total,
            "elapsed_s": elapsed,
            "tokens_per_sec": total / elapsed if elapsed > 0 else 0.0,
            "p50_token_latency_ms": float(np.percentile(lat, 50)) * 1e3,
            "p99_token_latency_ms": float(np.percentile(lat, 99)) * 1e3,
            "request_finish_s": finish,
            "request_first_token_s": first,
            # dispatch economy summed over the surviving incarnations
            # (a rebuilt replica restarts its counter — fleet numbers
            # are a floor, exact when no replica was rebuilt)
            "forward_dispatches": sum(e.forward_dispatches
                                      for e in self.engines),
            "dispatches_per_token": (
                sum(e.forward_dispatches for e in self.engines)
                / max(1, total)),
            "autoscale": (self._advisor.report()
                          if self._advisor is not None else None),
        }
        if any(eng.serve.trace == "on" for eng in self.engines):
            res["trace"] = self._trace_block(elapsed)
        return res

    def _trace_block(self, elapsed: float) -> dict:
        """Fleet trace view: per replica slot, merge the dead
        incarnations' archived harvests with the live loop's harvest
        (one Chrome-trace pid per replica), then fold every replica
        into one fleet span map.  ``merge_spans`` SUMS the phase
        accumulators, so a migrated request's queue time accumulates
        across donor and survivor incarnations — the failover span
        contract."""
        replicas = []
        all_harvests = []
        steps = dropped = 0
        for i in range(len(self.engines)):
            harvests = list(self._trace_archive[i])
            lp = self._loops[i]
            if lp is not None and lp.tracer is not None:
                harvests.append(lp.tracer.harvest(elapsed))
            if not harvests:
                continue
            step_recs = [rec for h in harvests for rec in h["steps"]]
            rep_dropped = sum(h["steps_dropped"] for h in harvests)
            replicas.append({
                "pid": i,
                "label": f"replica{i}",
                "spans": tracing.merge_spans(harvests),
                "steps": step_recs,
                "steps_dropped": rep_dropped,
            })
            all_harvests.extend(harvests)
            steps += len(step_recs)
            dropped += rep_dropped
        return {
            "enabled": True,
            "replicas": replicas,
            "spans": tracing.merge_spans(all_harvests),
            "steps": steps,
            "steps_dropped": dropped,
        }

    def compile_counts(self) -> dict:
        """Per-replica jit-cache probes, keyed ``r<i>/<fn>`` — the
        zero-recompile contract covers every replica's caches."""
        out = {}
        for i, eng in enumerate(self.engines):
            for k, v in eng.compile_counts().items():
                out[f"r{i}/{k}"] = v
        return out
