"""Data-parallel replica serving: a router over N decode engines.

The engine (serving/engine) scales UP with ``--serve-tp`` — one logical
pool, sharded over a mesh.  This layer scales OUT: ``N`` whole engine
replicas, each with its own pool, scheduler, prefix trie, and drafter,
fronted by one router that owns placement and (with the schedulers'
bounded queues) load-aware admission.  Together they are the Orca-style
distributed serving shape: aggregate KV capacity and tokens/sec grow
with replicas instead of one device's pool.

Placement policy, in order:

1. **Session affinity** — a request carrying ``Request.session`` sticks
   to the replica that served that session before.  The payoff is
   locality of everything a replica accumulates per conversation: radix
   prefix-cache blocks (a follow-up turn re-hits its own prefix trie),
   draft-model KV state, and — in a real deployment — the network hop.
2. **Least load** — sessionless requests (and a session's first
   request) go to the replica minimizing a load score built from the
   scheduler's OWN health signals: waiting-queue depth (each queued
   request is a whole admission behind), live-slot fraction, pool
   occupancy, and observed shed rate.  No new instrumentation: these
   are exactly the scale signals the schedulers already expose.

Placement can never change tokens: greedy decode is deterministic per
request, so whichever replica serves a request emits exactly the stream
a single-engine run would (pinned by tests/test_router.py).  Placement
changes latency, terminal statuses under pressure, and throughput.

Execution: ``run(..., parallel=True)`` drives each replica from its own
thread — schedulers and pools are single-owner (only the replica's
thread touches them), the router hands requests over through a locked
inbox, and jax dispatch/blocking release the GIL so replicas' device
work overlaps (the in-process stand-in for one-process-per-replica).
``parallel=False`` interleaves all replicas round-robin on the calling
thread — deterministic scheduling for tests.

Scope: the router serves a fixed trace to completion.  Graceful drain
(PreemptionGuard) and journaled crash recovery remain ENGINE-level
features — `tick()` mirrors `engine.run`'s per-iteration accounting
(latency cadence, eviction sample-discard) but does not wire guard or
journal through; routing those per-replica, and sharing one iteration
body with ``engine.run`` instead of mirroring it, is the
next extension of ROADMAP item 1.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from mpi_tensorflow_tpu.serving import scheduler as sched_lib


def default_parallelism() -> bool:
    """Whether threaded replica stepping can actually win on this host:
    only with >1 usable core.  On a single core the GIL's switch
    interval turns the thread ping-pong into pure overhead (measured
    ~10x slower than sequential on a 1-core container), while
    sequential round-robin matches a single engine minus dispatch
    overhead — so 1 core steps sequentially, and the real speedup claim
    belongs to multi-core (or multi-process / multi-chip) deployments."""
    try:
        return len(os.sched_getaffinity(0)) > 1
    except AttributeError:            # platforms without affinity API
        return (os.cpu_count() or 1) > 1


class ReplicaRouter:
    """Route requests across engine replicas; aggregate their results.

    ``engines``: fully constructed ``PagedDecodeEngine`` replicas (they
    may share model/params arrays — each still owns its pools and jit
    caches).  ``reset()`` resets every replica (jit caches survive,
    mirroring ``engine.reset``) and forgets session placements.
    """

    def __init__(self, engines: List):
        if not engines:
            raise ValueError("ReplicaRouter needs >= 1 engine replica")
        self.engines = list(engines)
        self._sticky: Dict[object, int] = {}    # session -> replica
        self.placements: Dict[int, int] = {}    # request id -> replica
        self._routed = [0] * len(self.engines)

    def reset(self) -> None:
        for eng in self.engines:
            eng.reset()
        self._sticky.clear()
        self.placements.clear()
        self._routed = [0] * len(self.engines)

    # ---------------- placement ----------------

    def load_score(self, i: int, inbox_depth: int = 0) -> float:
        """One replica's load, from its scheduler's own signals.  Queue
        depth dominates (integer weight per waiting request); live-slot
        fraction, pool occupancy, and shed rate are sub-1 tie-breakers
        that push new work away from saturated or shedding replicas."""
        eng = self.engines[i]
        sched = eng.sched
        waiting = len(sched.waiting) + inbox_depth
        live = sum(1 for s in sched.slots if s is not None)
        occ = eng.allocator.num_used / max(1, eng.serve.num_blocks - 1)
        shed_rate = sched.counters.get("shed", 0) / max(1, self._routed[i])
        return (waiting
                + live / max(1, eng.serve.max_slots) * 0.5
                + occ * 0.3
                + shed_rate * 0.2)

    def route(self, req: sched_lib.Request,
              inbox_depths: Optional[List[int]] = None) -> int:
        """Pick the replica for ``req``: sticky session first, else
        least-loaded (ties break to the lowest index, so an idle fleet
        fills deterministically)."""
        key = req.session
        i = self._sticky.get(key) if key is not None else None
        if i is None:
            depths = inbox_depths or [0] * len(self.engines)
            i = min(range(len(self.engines)),
                    key=lambda j: (self.load_score(j, depths[j]), j))
            if key is not None:
                self._sticky[key] = i
        self._routed[i] += 1
        self.placements[req.id] = i
        return i

    # ---------------- the serve loop ----------------

    def run(self, requests: List[sched_lib.Request],
            time_fn=time.perf_counter, *,
            parallel: Optional[bool] = None) -> dict:
        """Serve ``requests`` (replayed against their ``arrival``
        stamps) across the replicas to completion.  Latency semantics
        match ``engine.run`` (per-token cadence, eviction discards);
        the result adds a per-replica metrics list (queue depth, pool
        occupancy, shed rate, tokens/sec — the acceptance signals) next
        to the aggregated outputs/statuses/faults.

        ``parallel``: None (default) auto-selects — threads when the
        host has >1 usable core (``default_parallelism``), sequential
        round-robin otherwise; True/False force a mode."""
        if parallel is None:
            parallel = default_parallelism()
        n = len(self.engines)
        pending = sorted(requests, key=lambda r: r.arrival)
        inboxes = [deque() for _ in range(n)]
        locks = [threading.Lock() for _ in range(n)]
        token_times: List[dict] = [dict() for _ in range(n)]
        last_emit: List[dict] = [dict() for _ in range(n)]
        tokens_count = [0] * n
        peak_queue = [0] * n
        routing_done = threading.Event()
        errors: List[BaseException] = []
        t0 = time_fn()

        def route_due(now: float) -> None:
            while pending and pending[0].arrival <= now:
                req = pending.pop(0)
                depths = [len(b) for b in inboxes]
                i = self.route(req, depths)
                with locks[i]:
                    inboxes[i].append(req)

        def tick(i: int) -> bool:
            """One engine iteration for replica ``i`` (same shape as
            the body of ``engine.run``'s loop).  Returns whether any
            work moved.  Only replica ``i``'s thread (or the sequential
            caller) runs this — scheduler/pool state is single-owner."""
            eng = self.engines[i]
            with locks[i]:
                todo = list(inboxes[i])
                inboxes[i].clear()
            now = time_fn() - t0
            for req in todo:
                if eng.serve.deadline_ms is not None \
                        and req.deadline is None:
                    req = dataclasses.replace(
                        req,
                        deadline=req.arrival + eng.serve.deadline_ms / 1e3)
                if eng.sched.submit(req) is not None:
                    continue        # terminal status recorded on replica
                last_emit[i][req.id] = req.arrival
                token_times[i][req.id] = []
            peak_queue[i] = max(peak_queue[i], len(eng.sched.waiting))
            eng.sched.expire_deadlines(now)
            emitted = eng.step()
            now = time_fn() - t0
            for rid, tok in emitted:
                if rid in last_emit[i]:
                    token_times[i][rid].append(now - last_emit[i][rid])
                    last_emit[i][rid] = now
            tokens_count[i] += len(emitted)
            for rid in eng.sched.evicted_ids:
                # eviction discards the delivered-so-far latency sample,
                # exactly as engine.run does
                token_times[i][rid] = []
                last_emit[i][rid] = now
            eng.sched.evicted_ids.clear()
            return bool(todo) or bool(emitted) or eng._progressed

        if parallel:
            def worker(i: int) -> None:
                try:
                    while True:
                        progressed = tick(i)
                        if not progressed:
                            # observe routing_done BEFORE the inbox
                            # snapshot: once the flag is set no append
                            # can follow, so flag-then-empty is
                            # conclusive — the reverse order races a
                            # final route landing between the snapshot
                            # and the flag read, silently dropping it
                            done_routing = routing_done.is_set()
                            with locks[i]:
                                empty = not inboxes[i]
                            if done_routing and empty \
                                    and self.engines[i].sched.all_done():
                                return
                            time.sleep(1e-3)
                except BaseException as e:   # noqa: BLE001 — re-raised
                    errors.append(e)         # in the router thread below

            threads = [threading.Thread(target=worker, args=(i,),
                                        name=f"serve-replica-{i}",
                                        daemon=True) for i in range(n)]
            for t in threads:
                t.start()
            while pending and not errors:
                now = time_fn() - t0
                route_due(now)
                if pending:
                    time.sleep(min(1e-3, max(
                        0.0, pending[0].arrival - (time_fn() - t0))))
            routing_done.set()
            for t in threads:
                t.join()
            if errors:
                raise errors[0]
        else:
            routing_done.set()      # sequential: routing happens inline
            while pending or not all(e.sched.all_done()
                                     for e in self.engines):
                now = time_fn() - t0
                route_due(now)
                progressed = False
                for i in range(n):
                    progressed = tick(i) or progressed
                if not progressed:
                    delay = 1e-3
                    if pending:
                        delay = min(delay, max(
                            0.0, pending[0].arrival - (time_fn() - t0)))
                    if delay > 0:
                        time.sleep(delay)
        elapsed = time_fn() - t0

        # ---------------- aggregation ----------------
        from collections import Counter

        from mpi_tensorflow_tpu.utils.metrics_writer import faults_block

        outputs: dict = {}
        statuses: dict = {}
        totals: Counter = Counter()
        per_replica = []
        for i, eng in enumerate(self.engines):
            eng.sched.check_quiescent()
            if eng.drafter is not None:
                eng.drafter.check_quiescent()
            for s in eng.sched.finished:
                outputs[s.request.id] = list(s.generated)
            statuses.update(eng.sched.statuses)
            totals.update(eng.sched.counters)
            routed = self._routed[i]
            shed = int(eng.sched.counters.get("shed", 0))
            per_replica.append({
                "replica": i,
                "requests_routed": routed,
                "tokens": tokens_count[i],
                "tokens_per_sec": (tokens_count[i] / elapsed
                                   if elapsed > 0 else 0.0),
                "queue_depth_peak": peak_queue[i],
                "pool_occupancy_peak": round(
                    eng.peak_blocks_in_use
                    / max(1, eng.serve.num_blocks - 1), 4),
                "peak_live_blocks": eng.peak_live_blocks,
                "shed": shed,
                "shed_rate": round(shed / max(1, routed), 4),
                "evictions": eng.sched.evictions,
                "faults": faults_block(eng.sched.counters),
            })
        flat = [x for per in token_times for ts in per.values()
                for x in ts]
        lat = np.asarray(flat) if flat else np.zeros(1)
        total = sum(len(v) for v in outputs.values())
        return {
            "parallel": parallel,
            "outputs": outputs,
            "statuses": statuses,
            "faults": faults_block(totals),
            "replicas": per_replica,
            "num_replicas": n,
            "sticky_sessions": len(self._sticky),
            "placements": dict(self.placements),
            "tokens": total,
            "elapsed_s": elapsed,
            "tokens_per_sec": total / elapsed if elapsed > 0 else 0.0,
            "p50_token_latency_ms": float(np.percentile(lat, 50)) * 1e3,
            "p99_token_latency_ms": float(np.percentile(lat, 99)) * 1e3,
        }

    def compile_counts(self) -> dict:
        """Per-replica jit-cache probes, keyed ``r<i>/<fn>`` — the
        zero-recompile contract covers every replica's caches."""
        out = {}
        for i, eng in enumerate(self.engines):
            for k, v in eng.compile_counts().items():
                out[f"r{i}/{k}"] = v
        return out
