"""Trace-driven load generation + SLO metadata for the serving bench.

Before this module, the serving trace was a hand-coded Poisson block
inside ``bench.measure_serving`` — one arrival process, one length
distribution, no deadlines, no tenants.  Real serving systems are
graded by GOODPUT UNDER SLO (requests completed within their latency
deadline per second — DistServe, arXiv:2401.09670) and by behavior
under realistic traffic: bursty arrivals, heavy-tailed lengths, and
multi-tenant mixes.  This module is the workload subsystem:

- ``WorkloadSpec``   — the full description of a synthetic trace
                       (arrival process, length distributions, shared
                       prefix, tenant mix, SLO), validated the way
                       ServeConfig validates engine knobs;
- ``build_trace``    — spec + seed -> ``Trace``: the SAME (spec, seed)
                       reproduces the exact same request list across
                       runs, replicas, journal replay, and A/B arms.
                       ONE ``np.random.default_rng(seed)`` drives every
                       draw (no wall clock, no global RNG), and the
                       default Poisson path replays the historical
                       bench draw order byte-for-byte (pinned by
                       tests/test_loadgen.py);
- per-request SLO deadlines — stamped as absolute ``Request.deadline``
                       values so they ride the scheduler's existing TTL
                       machinery (an explicit deadline wins over the
                       engine's default TTL — iteration.EngineLoop);
- ``per_request_rows`` — joins trace metadata (tenant, arrival, SLO)
                       with a run result's statuses/outputs/finish
                       times into the rows ``metrics_writer.
                       goodput_block`` aggregates.

Workload matrix (``--serve-workload``):

==============  ==========================  =========================
workload        arrivals                    lengths / extras
==============  ==========================  =========================
poisson         exponential gaps            uniform [min(8,max), max]
                                            (the historical trace,
                                            byte-identical)
bursty          2-state MMPP: baseline      spec ``length_dist``
                rate / rate*burst_boost,
                exponential phase dwells
diurnal         raised-cosine envelope      spec ``length_dist``
                [floor*rate, rate] via
                Lewis–Shedler thinning
multi-tenant    MMPP (bursty arrivals)      per-tenant length caps,
                                            SLOs and sticky sessions
                                            (Request.session feeds the
                                            router's affinity map)
==============  ==========================  =========================
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Tuple

import numpy as np

from mpi_tensorflow_tpu.serving.scheduler import Request

#: the --serve-workload enum (cli.py/bench.py mirror these choices)
WORKLOADS = ("poisson", "bursty", "multi-tenant", "diurnal")
#: prompt/output length distributions ("uniform" is the historical one)
LENGTH_DISTS = ("uniform", "lognormal", "zipf")


@dataclasses.dataclass(frozen=True)
class TenantClass:
    """One named tenant in a multi-tenant mix.  ``share`` is the mix
    weight (normalized over the spec's tenants); None length/SLO knobs
    inherit the spec's.  ``session_len`` > 1 groups the tenant's
    requests into multi-turn sessions (geometric lengths) whose shared
    ``Request.session`` key feeds the router's sticky placement."""
    name: str
    share: float
    prompt_max: Optional[int] = None
    output_max: Optional[int] = None
    slo_ms: Optional[float] = None
    session_len: int = 1

    def __post_init__(self):
        if not self.name:
            raise ValueError("tenant class needs a non-empty name")
        if not self.share > 0:
            raise ValueError(f"tenant {self.name!r} share must be > 0, "
                             f"got {self.share}")
        for k in ("prompt_max", "output_max"):
            v = getattr(self, k)
            if v is not None and v < 1:
                raise ValueError(f"tenant {self.name!r} {k} must be "
                                 f">= 1, got {v}")
        if self.slo_ms is not None and not self.slo_ms > 0:
            raise ValueError(f"tenant {self.name!r} slo_ms must be > 0, "
                             f"got {self.slo_ms}")
        if self.session_len < 1:
            raise ValueError(f"tenant {self.name!r} session_len must be "
                             f">= 1, got {self.session_len}")


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """Everything that shapes a synthetic serving trace.  (spec, seed)
    is the reproducibility key: the same pair builds the exact same
    request list (arrival stamps, token content, deadlines, sessions).

    The defaults ARE the historical bench trace: ``poisson`` arrivals,
    ``uniform`` lengths, no prefix, no SLO — ``build_trace`` on a
    default spec replays bench.py's original inline generator
    byte-for-byte (the refactor pin)."""
    workload: str = "poisson"
    num_requests: int = 24
    rate_rps: float = 4.0
    prompt_max: int = 32
    output_max: int = 128
    vocab_size: int = 32000
    prefix_tokens: int = 0        # shared system prompt prepended to
                                  # every request (0 = all-unique; the
                                  # prefix draw must not advance the rng)
    length_dist: str = "uniform"
    slo_ms: Optional[float] = None  # per-request latency budget; stamped
                                  # as Request.deadline = arrival + slo
    seed: int = 0
    # bursty / multi-tenant arrivals: 2-state MMPP — a baseline phase at
    # rate_rps and a burst phase at rate_rps * burst_boost, phase dwell
    # times exponential with these means
    burst_on_s: float = 0.5
    burst_off_s: float = 2.0
    burst_boost: float = 8.0
    # diurnal envelope: peak rate_rps, trough diurnal_floor * rate_rps,
    # raised-cosine period diurnal_period_s (thinned Poisson)
    diurnal_period_s: float = 4.0
    diurnal_floor: float = 0.1
    # multi-tenant mix; () under workload="multi-tenant" uses
    # default_tenants() (interactive-vs-batch)
    tenants: Tuple[TenantClass, ...] = ()
    session_len: int = 1          # non-tenant workloads: mean multi-turn
                                  # session length (1 = no sessions)
    followup_turns: int = 0       # seeded follow-up-turn mode (prefix
                                  # v2 bench): each extra turn replays
                                  # every request as prior prompt +
                                  # ANSWER + a pre-drawn unique suffix
                                  # (Trace.followup_requests); 0 draws
                                  # nothing — the default trace stays
                                  # byte-identical

    def __post_init__(self):
        if self.workload not in WORKLOADS:
            raise ValueError(
                f"--serve-workload must be one of "
                f"{'|'.join(WORKLOADS)}, got {self.workload!r}")
        if self.num_requests < 1 or self.prompt_max < 1 \
                or self.output_max < 1:
            raise ValueError(
                f"serving trace needs >= 1 request/prompt/output token, "
                f"got requests={self.num_requests} "
                f"prompt_max={self.prompt_max} "
                f"output_max={self.output_max}")
        if not self.rate_rps > 0:
            raise ValueError(f"arrival rate must be > 0 req/s, got "
                             f"{self.rate_rps}")
        if self.vocab_size < 1:
            raise ValueError(f"vocab_size must be >= 1, got "
                             f"{self.vocab_size}")
        if self.prefix_tokens < 0:
            raise ValueError(f"--serve-prefix-tokens must be >= 0, got "
                             f"{self.prefix_tokens}")
        if self.length_dist not in LENGTH_DISTS:
            raise ValueError(
                f"length_dist must be one of {'|'.join(LENGTH_DISTS)}, "
                f"got {self.length_dist!r}")
        if self.slo_ms is not None and not self.slo_ms > 0:
            raise ValueError(f"--serve-slo-ms must be > 0, got "
                             f"{self.slo_ms}")
        if not self.burst_on_s > 0 or not self.burst_off_s > 0:
            raise ValueError(
                f"MMPP phase dwell means must be > 0 s, got "
                f"on={self.burst_on_s} off={self.burst_off_s}")
        if self.burst_boost < 1:
            raise ValueError(f"burst_boost must be >= 1 (the burst phase "
                             f"is the fast one), got {self.burst_boost}")
        if not self.diurnal_period_s > 0:
            raise ValueError(f"diurnal_period_s must be > 0, got "
                             f"{self.diurnal_period_s}")
        if not 0 < self.diurnal_floor <= 1:
            raise ValueError(f"diurnal_floor must be in (0, 1], got "
                             f"{self.diurnal_floor}")
        if self.tenants and self.workload != "multi-tenant":
            raise ValueError(
                f"tenant classes only apply under workload "
                f"'multi-tenant', got {self.workload!r} with "
                f"{len(self.tenants)} tenants")
        if self.session_len < 1:
            raise ValueError(f"session_len must be >= 1, got "
                             f"{self.session_len}")
        if self.followup_turns < 0:
            raise ValueError(f"followup_turns must be >= 0, got "
                             f"{self.followup_turns}")


def default_tenants(spec: WorkloadSpec) -> Tuple[TenantClass, ...]:
    """The built-in multi-tenant mix: a chatty interactive class (short
    outputs, tight SLO, 3-turn sticky sessions) against a batch class
    (full-length outputs, 4x looser SLO, no affinity) — the
    interference regime multi-tenant serving is graded on."""
    return (
        TenantClass("interactive", share=0.7,
                    output_max=max(1, spec.output_max // 4),
                    slo_ms=spec.slo_ms, session_len=3),
        TenantClass("batch", share=0.3,
                    output_max=spec.output_max,
                    slo_ms=(spec.slo_ms * 4
                            if spec.slo_ms is not None else None),
                    session_len=1),
    )


@dataclasses.dataclass
class Trace:
    """A built trace: per-request content + the SLO/tenant metadata the
    goodput report joins against.  ``requests()`` materializes fresh
    ``Request`` objects each call — bench replays the same trace
    through warmup, timed, A/B, and routed arms."""
    spec: WorkloadSpec
    prompts: List[List[int]]
    outputs: List[int]
    arrivals: np.ndarray
    tenants: List[str]
    slos_ms: List[Optional[float]]
    sessions: List[Optional[str]]
    # follow-up-turn mode (spec.followup_turns > 0): per-turn pre-drawn
    # unique suffixes and arrival gaps — the seeded half of a follow-up
    # prompt; the other half (the ANSWER) only exists after a run, so
    # followup_requests() joins them post hoc
    followup_suffixes: List[List[List[int]]] = \
        dataclasses.field(default_factory=list)
    followup_gaps: List[np.ndarray] = \
        dataclasses.field(default_factory=list)

    def requests(self) -> List[Request]:
        return [
            Request(i, self.prompts[i], self.outputs[i],
                    float(self.arrivals[i]),
                    deadline=(float(self.arrivals[i])
                              + self.slos_ms[i] / 1e3
                              if self.slos_ms[i] is not None else None),
                    session=self.sessions[i])
            for i in range(len(self.prompts))]

    def followup_requests(self, turn: int, prev_requests: List[Request],
                          outputs: dict, *, id_base: int,
                          arrival_base: float = 0.0) -> List[Request]:
        """Materialize follow-up turn ``turn`` (1-based, up to
        ``spec.followup_turns``): request ``i``'s new prompt is the
        prior turn's FULL prompt + its generated answer (``outputs``
        keyed by the prior request id — an engine/router run's
        ``outputs`` dict) + this turn's pre-drawn unique suffix.  The
        multi-turn regime generated-block caching exists for: everything
        up to the suffix re-prefills on a v1 cache but maps straight out
        of the trie under --serve-prefix-gen.  Ids start at ``id_base``
        (distinct from every prior turn's); arrivals replay the turn's
        seeded exponential gaps from ``arrival_base``."""
        if not 1 <= turn <= len(self.followup_suffixes):
            raise ValueError(
                f"follow-up turn {turn} out of range: trace has "
                f"{len(self.followup_suffixes)} "
                f"(spec.followup_turns={self.spec.followup_turns})")
        suffixes = self.followup_suffixes[turn - 1]
        arr = arrival_base + np.cumsum(self.followup_gaps[turn - 1])
        reqs = []
        for i, prev in enumerate(prev_requests):
            answer = list(outputs.get(prev.id, ()))
            prompt = list(prev.prompt) + answer + suffixes[i]
            a = float(arr[i])
            reqs.append(Request(
                id_base + i, prompt, self.outputs[i], a,
                deadline=(a + self.slos_ms[i] / 1e3
                          if self.slos_ms[i] is not None else None),
                session=self.sessions[i]))
        return reqs


def _mmpp_arrivals(rng, n: int, spec: WorkloadSpec) -> np.ndarray:
    """2-state Markov-modulated Poisson arrivals: a baseline phase at
    ``rate_rps`` and a burst phase at ``rate_rps * burst_boost``, with
    exponential phase dwells.  Restarting the gap draw at each phase
    boundary is exact (exponentials are memoryless)."""
    rate = {False: spec.rate_rps,
            True: spec.rate_rps * spec.burst_boost}
    t, on = 0.0, False
    phase_end = rng.exponential(spec.burst_off_s)
    out: List[float] = []
    while len(out) < n:
        gap = rng.exponential(1.0 / rate[on])
        if t + gap >= phase_end:
            t = phase_end
            on = not on
            phase_end = t + rng.exponential(
                spec.burst_on_s if on else spec.burst_off_s)
            continue
        t += gap
        out.append(t)
    arr = np.asarray(out)
    arr[0] = 0.0
    return arr


def _diurnal_arrivals(rng, n: int, spec: WorkloadSpec) -> np.ndarray:
    """Non-homogeneous Poisson arrivals under a raised-cosine rate
    envelope swinging between ``diurnal_floor * rate_rps`` (trough) and
    ``rate_rps`` (peak), via Lewis–Shedler thinning against the peak."""
    out: List[float] = []
    t = 0.0
    while len(out) < n:
        t += rng.exponential(1.0 / spec.rate_rps)
        phase = 0.5 * (1.0 - math.cos(
            2.0 * math.pi * t / spec.diurnal_period_s))
        accept = spec.diurnal_floor + (1.0 - spec.diurnal_floor) * phase
        if rng.random() <= accept:
            out.append(t)
    arr = np.asarray(out)
    arr[0] = 0.0
    return arr


def _sample_len(rng, dist: str, lo: int, hi: int) -> int:
    """One prompt/output length in [lo, hi].  ``uniform`` is the
    historical distribution; the heavy-tailed options put the median
    near ``lo`` with a tail clamped at ``hi`` (lognormal body, bounded
    Zipf) — the mixed-length regime continuous batching exists for."""
    if hi <= lo:
        return hi
    if dist == "uniform":
        return int(rng.integers(lo, hi + 1))
    if dist == "lognormal":
        return max(lo, min(hi, int(round(lo * rng.lognormal(0.0, 1.0)))))
    return max(lo, min(hi, lo - 1 + int(rng.zipf(1.5))))   # zipf


def build_trace(spec: WorkloadSpec) -> Trace:
    """Build the full trace for ``spec`` from ONE seeded generator.

    Draw order is part of the contract: shared prefix (only when
    ``prefix_tokens`` > 0 — a zero prefix must not advance the rng),
    tenant assignment (only under a tenant mix), prompt lengths +
    tokens, output budgets, arrivals, then sessions.  On a default
    Poisson/uniform spec the first four stages are literally the
    historical bench.measure_serving code, so the default trace is
    byte-identical to the pre-loadgen inline generator."""
    rng = np.random.default_rng(spec.seed)
    n = spec.num_requests
    p_lo = min(8, spec.prompt_max)
    o_lo = min(8, spec.output_max)
    # shared-prefix workload: one common N-token system prompt replayed
    # in front of every request's unique tail (prefix_tokens=0 keeps
    # the original all-unique trace byte-for-byte)
    shared = (list(map(int, rng.integers(0, spec.vocab_size,
                                         spec.prefix_tokens)))
              if spec.prefix_tokens else [])  # 0: do not advance the rng

    tenants = spec.tenants
    if spec.workload == "multi-tenant" and not tenants:
        tenants = default_tenants(spec)
    if tenants:
        shares = np.asarray([t.share for t in tenants], float)
        picks = rng.choice(len(tenants), size=n, p=shares / shares.sum())
        assigned: List[TenantClass] = [tenants[int(j)] for j in picks]
        prompts, outputs = [], []
        for t in assigned:
            p_hi = t.prompt_max or spec.prompt_max
            o_hi = t.output_max or spec.output_max
            plen = _sample_len(rng, spec.length_dist,
                               min(8, p_hi), p_hi)
            prompts.append(shared + list(map(int, rng.integers(
                0, spec.vocab_size, plen))))
            outputs.append(_sample_len(rng, spec.length_dist,
                                       min(8, o_hi), o_hi))
        tenant_names = [t.name for t in assigned]
        slos = [t.slo_ms if t.slo_ms is not None else spec.slo_ms
                for t in assigned]
    elif spec.length_dist == "uniform":
        # THE historical draw order (bench.measure_serving pre-loadgen):
        # one vectorized length draw, per-prompt token draws in request
        # order, one vectorized output draw — byte-identical by test pin
        prompts = [shared + list(map(int, rng.integers(
            0, spec.vocab_size, int(ln))))
            for ln in rng.integers(p_lo, spec.prompt_max + 1, n)]
        outputs = [int(ln) for ln in rng.integers(
            o_lo, spec.output_max + 1, n)]
        tenant_names = ["default"] * n
        slos = [spec.slo_ms] * n
    else:
        prompts = []
        for _ in range(n):
            plen = _sample_len(rng, spec.length_dist, p_lo,
                               spec.prompt_max)
            prompts.append(shared + list(map(int, rng.integers(
                0, spec.vocab_size, plen))))
        outputs = [_sample_len(rng, spec.length_dist, o_lo,
                               spec.output_max) for _ in range(n)]
        tenant_names = ["default"] * n
        slos = [spec.slo_ms] * n

    if spec.workload == "poisson":
        arrivals = np.cumsum(rng.exponential(1.0 / spec.rate_rps, n))
        arrivals[0] = 0.0
    elif spec.workload == "diurnal":
        arrivals = _diurnal_arrivals(rng, n, spec)
    else:                          # bursty and multi-tenant ride MMPP
        arrivals = _mmpp_arrivals(rng, n, spec)

    # multi-turn sessions: geometric run lengths per tenant, assigned in
    # arrival order so a session's turns are consecutive requests — the
    # affinity stream the router's sticky placement serves from one
    # replica's warm prefix/drafter state.  Mean 1 = no sessions (and
    # no rng draws: the default trace stays byte-identical).
    sessions: List[Optional[str]] = [None] * n
    per_tenant_mean = {t.name: t.session_len for t in tenants}
    state: dict = {}
    for i in range(n):
        mean = per_tenant_mean.get(tenant_names[i], spec.session_len)
        if mean <= 1:
            continue
        key = tenant_names[i]
        sid, left = state.get(key, (0, 0))
        if left == 0:
            sid += 1
            left = int(rng.geometric(1.0 / mean))
        sessions[i] = f"{key}:{sid}"
        state[key] = (sid, left - 1)

    # follow-up-turn draws come LAST (0 turns draws nothing, so every
    # pre-followup trace — including the pinned default — stays
    # byte-identical): per turn, n short suffix lengths + tokens, then
    # n exponential arrival gaps
    followup_suffixes: List[List[List[int]]] = []
    followup_gaps: List[np.ndarray] = []
    for _ in range(spec.followup_turns):
        lens = rng.integers(1, p_lo + 1, n)
        followup_suffixes.append(
            [list(map(int, rng.integers(0, spec.vocab_size, int(ln))))
             for ln in lens])
        followup_gaps.append(rng.exponential(1.0 / spec.rate_rps, n))

    return Trace(spec=spec, prompts=prompts, outputs=outputs,
                 arrivals=arrivals, tenants=tenant_names, slos_ms=slos,
                 sessions=sessions, followup_suffixes=followup_suffixes,
                 followup_gaps=followup_gaps)


def per_request_rows(trace: Trace, result: dict) -> List[dict]:
    """Join the trace's SLO/tenant metadata with a run result into the
    per-request rows ``metrics_writer.goodput_block`` aggregates.

    ``attained_ms`` is final-token emit time minus arrival on the run
    clock (``result["request_finish_s"]`` — engine.run/router.run), the
    whole-request latency a client experienced; None when the request
    never finished on this run.  A request MEETS its SLO iff it
    completed ``ok`` within its budget — the deadline sweep fails late
    work as ``deadline_exceeded``, and the attained-time check also
    catches a completion that slipped past its budget between sweeps."""
    finish = result.get("request_finish_s") or {}
    first = result.get("request_first_token_s") or {}
    statuses = result.get("statuses") or {}
    outputs = result.get("outputs") or {}
    # with tracing on the run result carries lifecycle spans
    # (serving/tracing) — join the phase attribution onto each row so
    # a per-tenant SLO miss can be read as queueing vs prefill vs
    # decode without opening the Chrome trace
    spans = (result.get("trace") or {}).get("spans") or {}
    rows = []
    for i in range(len(trace.prompts)):
        status = statuses.get(i, "missing")
        f = finish.get(i)
        attained = ((f - float(trace.arrivals[i])) * 1e3
                    if f is not None and status == "ok" else None)
        # time-to-first-token on the same clock — unlike attained_ms
        # it is kept for any request that streamed at least one token
        # (a deadline-failed request still made its client wait)
        t = first.get(i)
        ttft = ((t - float(trace.arrivals[i])) * 1e3
                if t is not None else None)
        row = {
            "tenant": trace.tenants[i],
            "status": status,
            "tokens": len(outputs.get(i, ())),
            "attained_ms": attained,
            "ttft_ms": ttft,
            "slo_ms": trace.slos_ms[i],
        }
        sp = spans.get(i)
        if sp is not None:
            row["queue_ms"] = sp["queue_s"] * 1e3
            row["prefill_ms"] = sp["prefill_s"] * 1e3
            row["decode_ms"] = sp["decode_s"] * 1e3
        rows.append(row)
    return rows
