"""Host-side structured tracing for the serving stack.

Every serving claim the bench makes (goodput-under-SLO, TTFT p99,
dispatch reduction, failover token-identity) is an end-of-run
aggregate; when a p99 regresses there was no way to see WHERE a
request spent its time.  This module is the phase-attribution layer
(DistServe / Sarathi-Serve style): it separates queueing from prefill
interference from decode latency, per request and per step.

Three pieces, all host-side and allocation-light:

- **Request lifecycle spans** (``Span``): one record per request,
  stamped arrive -> queued -> admitted -> prefill chunks -> first
  token -> decode -> terminal, including the fault transitions
  (eviction/restart, deadline sweep, drain cut, failover migration +
  replay).  Phase time lives in three accumulators (``queue_s`` /
  ``prefill_s`` / ``decode_s``) so a span that bounces between phases
  (evicted mid-decode, re-queued, re-prefilled) still sums to exactly
  its wall time: ``queue_s + prefill_s + decode_s == terminal - arrive``.
- **Step-phase timeline** (``TraceBuffer``): a bounded ring of
  per-iteration records — phase durations (deadline sweep, dispatch
  issue, host consume) plus the scheduler/pool gauges from
  ``engine.load_signals()``.  Fixed capacity, drop-oldest, with an
  explicit ``dropped`` counter — never unbounded.  The same records
  feed ``ScaleAdvisor.observe_step`` so autoscale advice is
  explainable from the trace.
- **Exports**: ``merge_spans`` folds harvests across replicas and
  failover incarnations (phase accumulators SUM, so a migrated
  request's queue time accumulates rather than resetting at
  re-admission), and ``write_chrome_trace`` emits Chrome trace-event
  (catapult) JSON — one pid per replica, request spans as async
  events, steps as duration events — loadable in Perfetto or
  chrome://tracing.

Hot-path contract: stamping uses the serve loop's existing host clock
values and ``time.monotonic`` deltas only — zero device syncs, zero
allocations beyond small per-event tuples, and nothing here touches a
jitted function, so the graft-lint HOST-SYNC pass stays clean with no
annotations.  With tracing off the engine never constructs a tracer
and every instrumentation site is a ``tracer is None`` skip: off is
byte-for-byte the untraced behavior.

Ownership: an ``EngineTracer`` is single-owner like the scheduler —
only the thread driving its engine may touch it.  The router archives
harvests from its own main thread (the ``_lat_archive`` idiom), so no
span state ever crosses the ``_GUARDED_BY`` lock.
"""

from __future__ import annotations

import json
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

# Fixed ring capacity for step records.  Deliberately NOT a knob: the
# buffer exists to bound tracing memory, and a configurable bound is a
# bound someone sets to None.  At ~200 bytes/record this is ~1.6 MB.
STEP_CAPACITY = 8192

# Per-span event cap — a span's event list is the only per-request
# growth path (one entry per chunk/eviction/terminal), so bound it the
# same way the step ring is bounded.
SPAN_EVENT_CAP = 256

#: Phases a span's open clock can be attributed to.
PHASES = ("queue", "prefill", "decode")


class TraceBuffer:
    """Bounded drop-oldest ring for step records.

    ``append`` never grows past ``capacity``; once full, the oldest
    record is dropped and ``dropped`` increments — the counter is the
    contract that truncation is visible, never silent."""

    __slots__ = ("capacity", "dropped", "_buf")

    def __init__(self, capacity: int = STEP_CAPACITY):
        if capacity < 1:
            raise ValueError(f"TraceBuffer capacity must be >= 1, "
                             f"got {capacity}")
        self.capacity = int(capacity)
        self.dropped = 0
        self._buf: deque = deque(maxlen=self.capacity)

    def append(self, rec: dict) -> None:
        if len(self._buf) == self.capacity:
            self.dropped += 1
        self._buf.append(rec)

    def records(self) -> List[dict]:
        return list(self._buf)

    def __len__(self) -> int:
        return len(self._buf)

    def __iter__(self):
        return iter(self._buf)


class Span:
    """Lifecycle record for one request on one engine incarnation.

    The state machine: ``on_submit`` opens ``queue`` at arrival;
    admission closes ``queue`` and opens ``prefill``; the first
    delivered token closes ``prefill`` and opens ``decode``; a
    terminal closes whatever is open.  An eviction closes the open
    phase, VOIDS the first-token stamp (the pre-eviction first token
    is regenerated — the same rule as ``EngineLoop.first_emit``), and
    re-opens ``queue``.  Exactly one terminal transition ever lands:
    later terminal notifications for the same span are ignored."""

    __slots__ = ("rid", "arrive", "queue_s", "prefill_s", "decode_s",
                 "phase", "phase_t0", "first_token", "terminal",
                 "status", "chunks", "evictions", "replays",
                 "prefilled_seen", "events", "events_dropped")

    def __init__(self, rid: int, arrive: float):
        self.rid = rid
        self.arrive = arrive
        self.queue_s = 0.0
        self.prefill_s = 0.0
        self.decode_s = 0.0
        self.phase: Optional[str] = "queue"
        self.phase_t0 = arrive
        self.first_token: Optional[float] = None
        self.terminal: Optional[float] = None
        self.status: Optional[str] = None
        self.chunks = 0
        self.evictions = 0
        self.replays = 0
        self.prefilled_seen = 0
        self.events: List[Tuple[float, str]] = []
        self.events_dropped = 0

    def event(self, t: float, name: str) -> None:
        if len(self.events) >= SPAN_EVENT_CAP:
            self.events_dropped += 1
            return
        self.events.append((t, name))

    def close_phase(self, now: float) -> None:
        """Fold the open phase's elapsed time into its accumulator."""
        if self.phase is None:
            return
        dt = max(0.0, now - self.phase_t0)
        if self.phase == "queue":
            self.queue_s += dt
        elif self.phase == "prefill":
            self.prefill_s += dt
        else:
            self.decode_s += dt
        self.phase = None

    def open_phase(self, phase: str, now: float) -> None:
        self.phase = phase
        self.phase_t0 = now

    def to_dict(self) -> dict:
        return {
            "rid": self.rid,
            "arrive": self.arrive,
            "queue_s": self.queue_s,
            "prefill_s": self.prefill_s,
            "decode_s": self.decode_s,
            "first_token": self.first_token,
            "terminal": self.terminal,
            "status": self.status,
            "chunks": self.chunks,
            "evictions": self.evictions,
            "replays": self.replays,
            "incarnations": 1,
            "events": [(t, n) for t, n in self.events],
            "events_dropped": self.events_dropped,
        }


class EngineTracer:
    """Per-engine span + step recorder, driven by ``EngineLoop``.

    The tracer never reads a clock of its own on the span path — every
    stamp is a ``now`` the serve loop already computed, so span times
    and the loop's stamped latencies (``first_emit``/``token_times``)
    are the SAME values, which is what makes the breakdown-vs-stamp
    cross-check exact.  Terminal hooks fire inside ``engine.step()``
    where no loop clock is in scope, so ``on_terminal`` only QUEUES
    the transition; ``flush_terminals`` lands it with the post-step
    ``now`` — after first-token stamping, so ``terminal >=
    first_token`` always holds.

    Step-phase durations (``sweep_s``/``dispatch_s``/``consume_s``)
    are accumulated by the engine/loop via ``time.monotonic`` deltas
    between ``begin_step`` and ``end_step``."""

    def __init__(self, step_capacity: int = STEP_CAPACITY):
        self.spans: Dict[int, Span] = {}
        self.buffer = TraceBuffer(step_capacity)
        self.pending_terminals: List[Tuple[int, str]] = []
        self.last_step: Optional[dict] = None
        self.sweep_s = 0.0
        self.dispatch_s = 0.0
        self.consume_s = 0.0
        self._last_now = 0.0

    # ---- request lifecycle -------------------------------------------

    def on_submit(self, req, *, replay: bool = False) -> None:
        """Open (or re-open) the request's span at its arrival stamp.
        Called BEFORE scheduler admission so a synchronous rejection's
        terminal finds the span.  A re-submit of an id that already
        reached a terminal (a replayed incarnation landing on the same
        tracer) re-opens the span and keeps the accumulators — queue
        time ACCUMULATES across incarnations."""
        sp = self.spans.get(req.id)
        if sp is None:
            sp = Span(req.id, req.arrival)
            self.spans[req.id] = sp
        else:
            # re-incarnation on the same tracer: keep phase totals,
            # clear the terminal, restart the queue clock at the NEW
            # arrival (the gap between incarnations is dead time the
            # journal replay owns, not queueing)
            sp.close_phase(sp.terminal if sp.terminal is not None
                           else req.arrival)
            sp.terminal = None
            sp.status = None
            sp.first_token = None
            sp.prefilled_seen = 0
            sp.replays += 1
            sp.open_phase("queue", req.arrival)
        sp.event(req.arrival, "replay" if replay else "queued")

    def on_terminal(self, req, status: str) -> None:
        """Terminal hook body — clock-free by design (fires inside
        ``engine.step()``); the transition lands at the next flush."""
        self.pending_terminals.append((req.id, status))

    def flush_terminals(self, now: float) -> None:
        for rid, status in self.pending_terminals:
            sp = self.spans.get(rid)
            if sp is None or sp.status is not None:
                continue            # exactly one terminal per span
            sp.close_phase(now)
            sp.terminal = now
            sp.status = status
            sp.event(now, f"terminal:{status}")
        if self.pending_terminals:
            self.pending_terminals.clear()
        self._last_now = max(self._last_now, now)

    def observe(self, occupied: Iterable[Tuple[int, int, int]],
                emitted_ids: Iterable[int], now: float) -> None:
        """Post-step observation pass: detect admissions and prefill
        chunk advances from the scheduler's occupied slots, and
        first-token transitions from this step's emissions — all at
        the same post-step ``now`` the loop stamps latencies with."""
        for rid, prefilled, _generated in occupied:
            sp = self.spans.get(rid)
            if sp is None or sp.status is not None:
                continue
            if sp.phase == "queue":
                sp.close_phase(now)
                sp.open_phase("prefill", now)
                sp.event(now, "admitted")
            if sp.phase == "prefill" and prefilled > sp.prefilled_seen:
                sp.chunks += 1
                sp.prefilled_seen = prefilled
                sp.event(now, "prefill_chunk")
        for rid in emitted_ids:
            sp = self.spans.get(rid)
            if (sp is None or sp.status is not None
                    or sp.first_token is not None):
                continue
            if sp.phase == "queue":
                # admitted, prefilled AND emitted inside one step (a
                # terminal removed it from the slots before the
                # occupancy pass could see it)
                sp.event(now, "admitted")
            sp.close_phase(now)
            sp.first_token = now
            sp.event(now, "first_token")
            sp.open_phase("decode", now)

    def on_evict(self, rid: int, now: float) -> None:
        """Eviction voids delivered work: the first-token stamp clears
        (it will be regenerated — same rule as the latency clock) and
        the span re-queues."""
        sp = self.spans.get(rid)
        if sp is None or sp.status is not None:
            return
        sp.close_phase(now)
        sp.first_token = None
        sp.prefilled_seen = 0
        sp.evictions += 1
        sp.event(now, "evicted")
        sp.open_phase("queue", now)

    # ---- step timeline -----------------------------------------------

    def begin_step(self) -> None:
        self.sweep_s = 0.0
        self.dispatch_s = 0.0
        self.consume_s = 0.0

    def end_step(self, t0: float, now: float, emitted: int,
                 signals: dict) -> None:
        rec = {
            "t0": t0,
            "t1": now,
            "sweep_s": self.sweep_s,
            "dispatch_s": self.dispatch_s,
            "consume_s": self.consume_s,
            "emitted": int(emitted),
            "signals": signals,
        }
        self.buffer.append(rec)
        self.last_step = rec
        self._last_now = max(self._last_now, now)

    # ---- harvest ------------------------------------------------------

    def harvest(self, now: Optional[float] = None, *,
                reason: Optional[str] = None) -> dict:
        """Freeze this tracer into a mergeable dict.  Open phases are
        closed at ``now`` (default: the last stamp this tracer saw) so
        a failover harvest charges the victim's spans up to the
        failure instant; ``reason`` (e.g. ``"migrated"``) is stamped
        on every span that was still open."""
        if now is None:
            now = self._last_now
        self.flush_terminals(now)
        spans = {}
        for rid, sp in self.spans.items():
            if sp.status is None and sp.phase is not None:
                sp.close_phase(now)
                if reason is not None:
                    sp.event(now, reason)
            spans[rid] = sp.to_dict()
        return {
            "spans": spans,
            "steps": self.buffer.records(),
            "steps_dropped": self.buffer.dropped,
        }


def merge_spans(harvests: Iterable[dict]) -> Dict[int, dict]:
    """Fold span dicts across harvests (replicas and/or failover
    incarnations) by request id.  Phase accumulators SUM — this is the
    failover contract: a migrated request's queue time accumulates
    across incarnations instead of resetting at re-admission.  The
    first-token stamp min-merges (mirror of the router's
    ``_first_archive``), the terminal comes from whichever incarnation
    actually finished (latest wins), and ``arrive`` is the earliest
    incarnation's arrival so end-to-end attained latency spans the
    whole migration."""
    out: Dict[int, dict] = {}
    for h in harvests:
        for rid, d in h["spans"].items():
            m = out.get(rid)
            if m is None:
                m = dict(d)
                m["events"] = list(d["events"])
                out[rid] = m
                continue
            m["queue_s"] += d["queue_s"]
            m["prefill_s"] += d["prefill_s"]
            m["decode_s"] += d["decode_s"]
            m["arrive"] = min(m["arrive"], d["arrive"])
            firsts = [t for t in (m["first_token"], d["first_token"])
                      if t is not None]
            m["first_token"] = min(firsts) if firsts else None
            if d["status"] is not None:
                if (m["status"] is None or m["terminal"] is None
                        or (d["terminal"] is not None
                            and d["terminal"] >= m["terminal"])):
                    m["status"] = d["status"]
                    m["terminal"] = d["terminal"]
            m["chunks"] += d["chunks"]
            m["evictions"] += d["evictions"]
            m["replays"] += d["replays"]
            m["incarnations"] += d.get("incarnations", 1)
            m["events"] = sorted(m["events"] + list(d["events"]),
                                 key=lambda e: e[0])
            m["events_dropped"] += d["events_dropped"]
    return out


def _us(t: float) -> int:
    return max(0, int(round(t * 1e6)))


def write_chrome_trace(path: str, replicas: List[dict]) -> dict:
    """Write Chrome trace-event (catapult) JSON: one pid per replica,
    request spans as async ``b``/``n``/``e`` events (matched by
    ``cat``+``id``), steps as ``X`` duration events on tid 1.  Open
    the file in Perfetto (ui.perfetto.dev) or chrome://tracing.

    ``replicas`` entries are harvest dicts plus ``pid``/``label``
    (the engine emits one; the router one per replica, incarnations
    pre-merged).  Returns a small summary dict ``{path, events,
    requests, steps}`` for logging."""
    events: List[dict] = []
    n_req = n_step = 0
    for rep in replicas:
        pid = int(rep.get("pid", 0))
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "ts": 0,
                       "args": {"name": rep.get("label",
                                                f"replica{pid}")}})
        for rid in sorted(rep["spans"]):
            sp = rep["spans"][rid]
            name = f"request {sp['rid']}"
            base = {"name": name, "cat": "request", "id": int(sp["rid"]),
                    "pid": pid, "tid": 0}
            events.append({**base, "ph": "b", "ts": _us(sp["arrive"]),
                           "args": {"arrive_s": sp["arrive"]}})
            for t, ev in sp["events"]:
                events.append({**base, "ph": "n", "ts": _us(t),
                               "args": {"event": ev}})
            end = sp["terminal"]
            if end is None:
                end = (sp["arrive"] + sp["queue_s"] + sp["prefill_s"]
                       + sp["decode_s"])
            events.append({**base, "ph": "e", "ts": max(_us(end),
                                                        _us(sp["arrive"])),
                           "args": {
                               "status": sp["status"],
                               "queue_ms": sp["queue_s"] * 1e3,
                               "prefill_ms": sp["prefill_s"] * 1e3,
                               "decode_ms": sp["decode_s"] * 1e3,
                               "evictions": sp["evictions"],
                           }})
            n_req += 1
        for rec in rep.get("steps", ()):
            dur = max(1, _us(rec["t1"] - rec["t0"]))
            events.append({"name": "step", "cat": "step", "ph": "X",
                           "pid": pid, "tid": 1, "ts": _us(rec["t0"]),
                           "dur": dur,
                           "args": {
                               "sweep_us": _us(rec["sweep_s"]),
                               "dispatch_us": _us(rec["dispatch_s"]),
                               "consume_us": _us(rec["consume_s"]),
                               "emitted": rec["emitted"],
                               "signals": rec["signals"],
                           }})
            n_step += 1
    # catapult tolerates unsorted input, but monotone-per-track is the
    # schema our tests (and humans reading the raw JSON) rely on
    events.sort(key=lambda e: (e["pid"], e["tid"], e["ts"]))
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return {"path": path, "events": len(events), "requests": n_req,
            "steps": n_step}
