"""Advisory replica auto-scaling from the serving stack's load signals.

The scheduler already exposes queue depth / pool occupancy / shed
counters, and the router folds the same signals into its least-load
placement score — but nothing watched them over time.  ``ScaleAdvisor``
is that consumer: ``engine.run`` (and ``router.run``) feed it one
observation per iteration, and it emits ADVISORY scale-up/scale-down
decisions under hysteresis (a watermark must hold for ``hold_ticks``
consecutive observations) and a post-decision cooldown, so a bursty
trace can't flap the advice every tick.

Advisory on purpose: nothing here spawns or kills replicas.  The
decision log is recorded in bench detail as the acceptance signal a
real replica auto-scaler (ROADMAP item 1's remaining extension) will
later act on through ``ReplicaRouter``'s existing probe/rebuild seam.

The load score mirrors ``ReplicaRouter.load_score`` — queue depth
dominates, live-slot fraction, pool occupancy, and shed rate break
ties — normalized by the currently ADVISED replica count (advice to
scale up models the per-replica load it would relieve).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class ScalePolicy:
    """Watermarks + damping for the advisor.  ``high_load`` /
    ``low_load`` bound the per-replica load score; ``hold_ticks`` is
    the hysteresis window (consecutive observations beyond a watermark
    before a decision); ``cooldown_ticks`` silences the advisor after
    each decision while the fleet would be reacting."""
    high_load: float = 4.0
    low_load: float = 0.25
    hold_ticks: int = 8
    cooldown_ticks: int = 32
    min_replicas: int = 1
    max_replicas: int = 8

    def __post_init__(self):
        if not self.high_load > self.low_load >= 0:
            raise ValueError(
                f"scale watermarks need high_load > low_load >= 0, got "
                f"high={self.high_load} low={self.low_load}")
        if self.hold_ticks < 1 or self.cooldown_ticks < 0:
            raise ValueError(
                f"scale damping needs hold_ticks >= 1 and "
                f"cooldown_ticks >= 0, got hold={self.hold_ticks} "
                f"cooldown={self.cooldown_ticks}")
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"replica bounds need 1 <= min <= max, got "
                f"min={self.min_replicas} max={self.max_replicas}")


class ScaleAdvisor:
    """Per-tick load observer -> advisory scale decisions.

    Single-owner like the scheduler: the thread driving the serve loop
    calls ``observe`` once per iteration and reads ``report`` after the
    run.  ``replicas`` tracks the ADVISED count, clamped to the
    policy's bounds — it never touches real engines."""

    def __init__(self, policy: Optional[ScalePolicy] = None, *,
                 replicas: int = 1):
        self.policy = policy if policy is not None else ScalePolicy()
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.replicas = int(replicas)
        self.ticks = 0
        self.peak_load = 0.0
        self.decisions: List[dict] = []
        self._above = 0
        self._below = 0
        self._cool = 0

    def load(self, *, queue_depth: float, occupancy: float,
             shed_rate: float = 0.0, live_fraction: float = 0.0,
             prefill_backlog: float = 0.0) -> float:
        """Instantaneous per-replica load score (the router's
        ``load_score`` weights), divided by the advised replica count.
        ``prefill_backlog`` is admitted-but-unprefilled prompt work in
        prefill-chunk units (engine.load_signals) — head-of-line
        pressure the queue depth misses: a burst of long prompts fills
        slots with sequences that emit nothing for many steps while
        the waiting queue looks empty."""
        raw = (queue_depth + 0.5 * live_fraction + 0.3 * occupancy
               + 0.2 * shed_rate + 0.2 * prefill_backlog)
        return raw / max(1, self.replicas)

    def observe(self, now_s: float, *, queue_depth: float,
                occupancy: float, shed_rate: float = 0.0,
                live_fraction: float = 0.0,
                prefill_backlog: float = 0.0) -> Optional[dict]:
        """One tick: fold the signals into the load score, advance the
        hysteresis counters, and return the decision dict if one fired
        this tick (None otherwise — the common case)."""
        load = self.load(queue_depth=queue_depth, occupancy=occupancy,
                         shed_rate=shed_rate, live_fraction=live_fraction,
                         prefill_backlog=prefill_backlog)
        self.ticks += 1
        self.peak_load = max(self.peak_load, load)
        if self._cool > 0:
            # cooldown: the fleet would still be reacting to the last
            # decision; watermark streaks restart after it
            self._cool -= 1
            self._above = self._below = 0
            return None
        p = self.policy
        if load > p.high_load:
            self._above += 1
            self._below = 0
        elif load < p.low_load:
            self._below += 1
            self._above = 0
        else:
            self._above = self._below = 0
        if self._above >= p.hold_ticks and self.replicas < p.max_replicas:
            return self._decide(now_s, "up", load)
        if self._below >= p.hold_ticks and self.replicas > p.min_replicas:
            return self._decide(now_s, "down", load)
        return None

    def observe_step(self, rec: dict) -> Optional[dict]:
        """One tick from a tracing step record (serving/tracing
        ``TraceBuffer`` entry): the record's ``signals`` are exactly
        ``engine.load_signals()`` captured at step end, so with tracing
        on the advisor and the trace read the SAME observation — advice
        is explainable by replaying the buffer through this method."""
        return self.observe(rec["t1"], **rec["signals"])

    def _decide(self, now_s: float, action: str, load: float) -> dict:
        before = self.replicas
        self.replicas += 1 if action == "up" else -1
        self._above = self._below = 0
        self._cool = self.policy.cooldown_ticks
        decision = {
            "tick": self.ticks,
            "t_s": round(float(now_s), 4),
            "action": action,
            "load": round(float(load), 4),
            "replicas_before": before,
            "replicas_after": self.replicas,
        }
        self.decisions.append(decision)
        return decision

    def report(self) -> dict:
        """The canonical ``autoscale`` result block bench detail
        carries: the decision log plus the final advice and enough
        policy echo to read the decisions against."""
        return {
            "ticks": self.ticks,
            "peak_load": round(self.peak_load, 4),
            "replicas_advised": self.replicas,
            "decisions": list(self.decisions),
            "policy": dataclasses.asdict(self.policy),
        }
