"""Continuous-batching decode engine over the paged KV cache.

Drives models/gpt.CausalLm.forward_paged with iteration-level
scheduling: every engine step advances ONE prefill chunk (if a newly
admitted sequence is mid-prefill) and ONE decode token for every live
sequence.  Chunked prefill keeps a long new prompt from stalling
in-flight decodes; slot recycling keeps finished sequences from burning
device cycles on masked rows.

Compile discipline: device dispatches run at a SMALL FIXED SET of
bucketed shapes —

- decode:  (slot bucket, table-width bucket), both powers of two, so at
  most ``(log2 max_slots + 1) * (log2 max_blocks_per_seq + 1)`` shapes;
- prefill: (1, chunk bucket) with the full table width, at most
  ``log2 prefill_chunk + 1`` shapes;
- mixed (``--serve-mixed-batch on``): (slot bucket, chunk bucket,
  table-width bucket) for the ONE fused prefill+decode forward per
  step — every triple pre-warmed at build, like speculative verify,
  because which buckets a mixed step hits depends on arrival timing

— so steady-state serving performs ZERO recompiles after bucket warmup
(pinned by tests/test_serving.py via the jit cache-size probe).  The
block pools are donated through every dispatch on TPU, so the cache
updates in place instead of ping-ponging two pool-sized buffers.

Tensor parallelism (``--serve-tp N``): the jitted steps below run the
forward through a shard_map seam (serving/tp) that partitions the
head-major pool, QKV/O, and MLP over a ``tp`` mesh axis with one psum
per row-parallel projection.  Block tables index blocks, not heads, so
everything host-side in this file is tp-unaware; the seam is resolved
once at construction, so TP adds no dispatch shapes and the
zero-recompile contract holds unchanged.  Scale-OUT (whole-engine
replicas) lives above this file in serving/router.

Prefix sharing (``--serve-prefix-cache on``): admission walks each
prompt through a radix trie of cached full blocks
(serving/prefix_cache) and maps hits to EXISTING physical blocks, so
prefill computes only the unique suffix; the engine contributes the
device half — a copy-on-write block copy before any dispatch would
write into a shared block, and trie registration when a prompt finishes
prefill.  Prefix sharing v2 (``--serve-prefix-gen on``) extends the
trie with a finishing request's generated blocks (multi-turn reuse)
and serves mid-block misses through a pre-warmed one-compile partial
tail-block copy (``_partial_fn``, the ``_cow_fn`` discipline), applied
between admission and the first prefill chunk.  Greedy outputs with
the cache on are token-identical to cache-off for every request (the
determinism contract the serving tests pin), and v2-on is
token-identical to v2-off.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Tuple

import numpy as np

from mpi_tensorflow_tpu.serving import paged_cache, \
    scheduler as sched_lib, tracing


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving-pool geometry + fault-tolerance policy (the --serve-*
    CLI knobs)."""
    num_blocks: int = 128         # pool blocks, block 0 reserved as null
    block_size: int = 16          # cache entries per block
    max_slots: int = 8            # concurrent sequences (decode batch cap)
    max_seq_len: int = 512        # per-sequence prompt+output cap
    prefill_chunk: int = 64       # max prompt tokens per prefill dispatch
    eos_id: Optional[int] = None  # emit-EOS slot recycling (None: budget
                                  # exhaustion only — the LM families
                                  # train on streams with no terminator)
    kernel: str = "auto"          # paged-attention lowering: auto | xla
                                  # | pallas (--serve-kernel; resolved
                                  # ONCE at engine construction via
                                  # ops/paged_attention.resolve_kernel,
                                  # so the choice is static under jit)
    prefix_cache: str = "off"     # radix prefix cache (--serve-prefix-
                                  # cache): "on" maps cached full prompt
                                  # blocks into new sequences (shared,
                                  # copy-on-write on divergence, LRU
                                  # trie eviction under pressure);
                                  # "off" preserves byte-for-byte the
                                  # unshared behavior
    prefix_gen: str = "off"       # prefix sharing v2 (--serve-prefix-
                                  # gen): "on" additionally (a) inserts
                                  # a finishing request's full blocks
                                  # spanning prompt + generated output
                                  # into the trie, so follow-up turns
                                  # embedding the prior answer map them
                                  # instead of re-prefilling, and (b)
                                  # serves a mid-block miss's matched
                                  # row prefix via the one-compile
                                  # partial-copy dispatch.  Requires
                                  # prefix_cache on; "off" keeps the
                                  # trie prompt-blocks-only (v1),
                                  # byte-for-byte
    prefix_route: str = "off"     # prefix-aware fleet routing (--serve-
                                  # prefix-route): "on" lets the
                                  # replica router (serving/router)
                                  # bias placement toward the replica
                                  # whose trie already caches a
                                  # request's leading full block, when
                                  # load permits — never overriding
                                  # health gating, never changing
                                  # tokens.  Requires prefix_cache on;
                                  # consumed by ReplicaRouter, carried
                                  # here so the fleet's engines and the
                                  # router agree through ONE config
    speculative: str = "off"      # speculative decoding (--serve-
                                  # speculative): "ngram" = n-gram
                                  # self-draft, "draft-model" = tiny-
                                  # model drafter over its own paged
                                  # pool (serving/speculative); "off"
                                  # keeps the one-token decode loop
                                  # byte-for-byte
    draft_k: int = 4              # draft window (--serve-draft-k):
                                  # tokens proposed per verify forward;
                                  # the verify dispatch width is k+1
                                  # and a step emits 1..k+1 tokens
    draft_auto: str = "off"       # auto-tune the draft window (--serve-
                                  # draft-auto): "on" shrinks/grows the
                                  # EFFECTIVE k with an EWMA of the
                                  # accepted length per verify step,
                                  # clamped to [1, draft_k] (the floor
                                  # keeps a 1-token probe alive so a
                                  # recovering accept rate can re-grow
                                  # it); dispatch width stays draft_k+1
                                  # so the zero-recompile contract is
                                  # untouched.  "off" drafts the full
                                  # configured k every step
    mixed_batch: str = "off"      # stall-free mixed batching (--serve-
                                  # mixed-batch): "on" fuses budget-
                                  # capped prefill chunks from MULTIPLE
                                  # mid-prefill sequences into the
                                  # decode dispatch, so every step is
                                  # ONE forward — the chunked-prefill
                                  # math already masks per-row lengths,
                                  # and decode is its chunk=1
                                  # degenerate case, so greedy outputs
                                  # are token-identical to "off" by
                                  # construction; "off" preserves the
                                  # two-dispatch prefill-then-decode
                                  # loop byte-for-byte.  Replaces the
                                  # decode dispatch like speculative
                                  # verify does, so the two do not
                                  # compose
    prefill_budget: int = 64      # mixed batching (--serve-prefill-
                                  # budget): max prefill tokens fused
                                  # into one step across all mid-
                                  # prefill sequences — bounds the
                                  # decode-latency tax a step pays for
                                  # prompt ingestion (consumed only
                                  # with mixed_batch on)
    kv_dtype: str = "fp32"        # pool storage format (--serve-kv-
                                  # dtype): "fp32" keeps blocks in the
                                  # model compute dtype — byte-for-byte
                                  # the pre-quantization pool, the
                                  # parity reference; "int8" stores
                                  # symmetric-absmax codes with per-
                                  # (block, head, slot) fp32 row scales
                                  # (serving/paged_cache.init_pools):
                                  # ~4x the tokens per pool byte, write
                                  # paths quantize on store, consume
                                  # paths dequantize in place (kernel:
                                  # in register; XLA: on the gathered
                                  # view), and greedy outputs track the
                                  # fp32 pool at a token-match-rate
                                  # gate rather than token identity;
                                  # "int4" nibble-packs two codes per
                                  # byte with per-group fp32 scales
                                  # (kv_group) plus a KIVI fp-residual
                                  # self lane — the next capacity rung
                                  # (~6-8x the tokens per pool byte)
    kv_group: int = 32            # int4 scale-group size along head_dim
                                  # (--serve-kv-group): one fp32 scale
                                  # per ``min(kv_group, head_dim)``
                                  # channels (clamped so the default
                                  # stays valid on tiny heads; must
                                  # divide head_dim).  Smaller groups =
                                  # tighter quantization, more scale
                                  # bytes.  Consumed only under
                                  # kv_dtype=int4
    kv_tier: str = "off"          # host-RAM block tier (--serve-kv-
                                  # tier): "host" demotes cold prefix-
                                  # cache blocks to a HostBlockStore on
                                  # eviction instead of discarding
                                  # them, and promotes them back into
                                  # fresh device blocks when a later
                                  # prompt walks the same trie path —
                                  # multi-turn sessions stop re-paying
                                  # prefill after their prefix ages out
                                  # of the device pool.  Requires
                                  # prefix_cache on (the trie's token
                                  # paths are the tier's keys); "off"
                                  # is byte-for-byte untiered
    tp: int = 1                   # tensor-parallel shards (--serve-tp):
                                  # >1 partitions the head-major pool,
                                  # QKV/O projections, and MLP over a
                                  # ``tp`` mesh axis via shard_map
                                  # (serving/tp), psum-combining the
                                  # row-parallel outputs; 1 keeps the
                                  # single-device path byte-for-byte.
                                  # Must divide the model's heads and
                                  # mlp dims and fit the device count
                                  # (checked at engine construction,
                                  # where the model geometry is known)
    # --- fault-tolerance policy (None = feature off / unbounded) ---
    deadline_ms: Optional[float] = None   # default per-request TTL from
                                  # arrival; expired work fails with
                                  # deadline_exceeded instead of
                                  # occupying slots (an explicit
                                  # Request.deadline wins)
    queue_depth: Optional[int] = None     # bound on the waiting queue;
                                  # a submit finding it full is load-
                                  # shed (reject-newest, queue_full)
    max_evictions: Optional[int] = None   # preemption-livelock guard: a
                                  # request evicted more than this many
                                  # times fails with evicted_too_often
    drain_ms: Optional[float] = None      # graceful-drain budget after a
                                  # stop request (SIGTERM): in-flight
                                  # work past it is cut with status
                                  # `drained` (None = finish in flight)
    failover_backoff_ms: float = 50.0     # replica circuit breaker
                                  # (serving/router): base probe backoff
                                  # after a transient replica fault —
                                  # doubled per consecutive fault, capped
                                  # at 64x, before the router rebuilds
                                  # the replica and probes it back in
    trace: str = "off"            # request-lifecycle + step-phase
                                  # tracing (serving/tracing): "on"
                                  # builds an EngineTracer at reset and
                                  # adds the `trace` result block; off
                                  # is byte-for-byte untraced (the
                                  # tracer is never constructed)
    trace_out: Optional[str] = None       # Chrome trace-event JSON path
                                  # (written by bench after the timed
                                  # run); requires trace="on"

    @classmethod
    def from_config(cls, config, **overrides):
        """Build from a run Config's ``--serve-*`` knobs (config.py) —
        THE bridge from the CLI surface to the engine; bench and any
        serve entry point construct their ServeConfig through here so
        the knobs have exactly one meaning."""
        base = dict(num_blocks=config.serve_pool_blocks,
                    block_size=config.serve_block_size,
                    max_slots=config.serve_max_slots,
                    max_seq_len=config.serve_max_seq_len,
                    kernel=config.serve_kernel,
                    prefix_cache=config.serve_prefix_cache,
                    prefix_gen=config.serve_prefix_gen,
                    prefix_route=config.serve_prefix_route,
                    speculative=config.serve_speculative,
                    draft_k=config.serve_draft_k,
                    draft_auto=config.serve_draft_auto,
                    mixed_batch=config.serve_mixed_batch,
                    prefill_budget=config.serve_prefill_budget,
                    kv_dtype=config.serve_kv_dtype,
                    kv_group=config.serve_kv_group,
                    kv_tier=config.serve_kv_tier,
                    tp=config.serve_tp,
                    deadline_ms=config.serve_deadline_ms,
                    queue_depth=config.serve_queue_depth,
                    max_evictions=config.serve_max_evictions,
                    drain_ms=config.serve_drain_ms,
                    failover_backoff_ms=config.serve_failover_backoff_ms,
                    trace=config.serve_trace,
                    trace_out=config.serve_trace_out)
        base.update({k: v for k, v in overrides.items() if v is not None})
        return cls(**base)

    @property
    def max_blocks_per_seq(self) -> int:
        return paged_cache.blocks_for(self.max_seq_len, self.block_size)

    def __post_init__(self):
        if self.block_size < 1 or self.num_blocks < 2 \
                or self.prefill_chunk < 1 or self.max_slots < 1 \
                or self.max_seq_len < 1:
            raise ValueError(f"bad pool geometry: {self}")
        if self.kernel not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"serve kernel must be auto|xla|pallas, "
                f"got {self.kernel!r}")
        if self.prefix_cache not in ("off", "on"):
            raise ValueError(
                f"serve prefix cache must be off|on, "
                f"got {self.prefix_cache!r}")
        if self.prefix_gen not in ("off", "on"):
            raise ValueError(
                f"serve prefix_gen must be off|on, "
                f"got {self.prefix_gen!r}")
        if self.prefix_route not in ("off", "on"):
            raise ValueError(
                f"serve prefix_route must be off|on, "
                f"got {self.prefix_route!r}")
        if self.prefix_gen == "on" and self.prefix_cache == "off":
            raise ValueError(
                "serve prefix_gen extends the radix prefix cache; with "
                "prefix_cache off it would be silently ignored — turn "
                "the cache on or drop it")
        if self.prefix_route == "on" and self.prefix_cache == "off":
            raise ValueError(
                "serve prefix_route biases placement toward cached "
                "prefixes; with prefix_cache off there is no trie to "
                "hint from — turn the cache on or drop it")
        if self.speculative not in ("off", "ngram", "draft-model"):
            raise ValueError(
                f"serve speculative must be off|ngram|draft-model, "
                f"got {self.speculative!r}")
        if self.draft_k < 1:
            raise ValueError(
                f"serve draft_k must be >= 1, got {self.draft_k}")
        if self.draft_auto not in ("off", "on"):
            raise ValueError(
                f"serve draft_auto must be off|on, got {self.draft_auto!r}")
        if self.draft_auto == "on" and self.speculative == "off":
            raise ValueError(
                "serve draft_auto tunes the speculative draft window; "
                "with speculative off it would be silently ignored — "
                "pick a drafter or drop it")
        if self.mixed_batch not in ("off", "on"):
            raise ValueError(
                f"serve mixed_batch must be off|on, "
                f"got {self.mixed_batch!r}")
        if self.prefill_budget < 1:
            raise ValueError(
                f"serve prefill_budget must be >= 1, "
                f"got {self.prefill_budget}")
        if self.mixed_batch == "on" and self.speculative != "off":
            raise ValueError(
                "serve mixed_batch and speculative each replace the "
                "decode dispatch with their own fused forward; they do "
                "not compose — pick one")
        if self.kv_dtype not in ("fp32", "int8", "int4"):
            raise ValueError(
                f"serve kv dtype must be fp32|int8|int4, "
                f"got {self.kv_dtype!r}")
        if self.kv_group < 1:
            raise ValueError(
                f"serve kv_group must be >= 1, got {self.kv_group}")
        if self.kv_tier not in ("off", "host"):
            raise ValueError(
                f"serve kv_tier must be off|host, got {self.kv_tier!r}")
        if self.kv_tier == "host" and self.prefix_cache == "off":
            raise ValueError(
                "serve kv_tier demotes/promotes radix-trie blocks; with "
                "prefix_cache off there are no trie paths to key the "
                "host store by — turn the cache on or drop the tier")
        if self.tp < 1:
            raise ValueError(f"serve tp must be >= 1, got {self.tp}")
        if (self.deadline_ms is not None and self.deadline_ms <= 0) \
                or (self.queue_depth is not None and self.queue_depth < 1) \
                or (self.max_evictions is not None
                    and self.max_evictions < 1) \
                or (self.drain_ms is not None and self.drain_ms < 0) \
                or self.failover_backoff_ms <= 0:
            raise ValueError(f"bad fault-tolerance policy: {self}")
        if self.trace not in ("off", "on"):
            raise ValueError(
                f"serve trace must be off|on, got {self.trace!r}")
        if self.trace_out is not None and self.trace != "on":
            raise ValueError(
                "serve trace_out names a Chrome-trace output but trace "
                "is off — there would be no trace to write; turn trace "
                "on or drop the path")
        if self.num_blocks - 1 < self.max_blocks_per_seq:
            # a lone max-length sequence must fit, or the scheduler can
            # deadlock with nothing left to evict
            raise ValueError(
                f"pool of {self.num_blocks - 1} usable blocks cannot hold "
                f"one max_seq_len={self.max_seq_len} sequence "
                f"({self.max_blocks_per_seq} blocks of {self.block_size})")


def pow2_ceil(n: int) -> int:
    """Smallest power of two >= ``n`` — THE bucketing rule the engine's
    dispatch-shape / zero-recompile contract rests on; bench's trace
    sizing reuses it so the two can never drift."""
    b = 1
    while b < n:
        b *= 2
    return b


def _bucket(n: int, cap: int) -> int:
    """Round ``n`` up to a power of two, capped at ``cap``."""
    return min(pow2_ceil(n), cap)


class PagedDecodeEngine:
    """Greedy continuous-batching decode over a paged KV cache.

    ``run(requests)`` returns ``{request id: generated token list}`` plus
    latency/throughput stats.  Greedy only: the serving path's parity
    anchor is ``CausalLm.generate(temperature=0)``; sampling belongs on
    top once the deterministic path is pinned.
    """

    def __init__(self, model, params, serve: ServeConfig, *,
                 draft_model=None, draft_params=None):
        import jax

        from mpi_tensorflow_tpu.ops import paged_attention as paged_ops
        from mpi_tensorflow_tpu.serving import speculative as spec_lib

        self.model = model
        self.serve = serve
        cap = serve.max_blocks_per_seq * serve.block_size
        if model.cfg.pos_kind == "learned" \
                and cap > model.cfg.max_positions:
            raise ValueError(
                f"max_seq_len {serve.max_seq_len} (table capacity {cap}) "
                f"exceeds max_positions {model.cfg.max_positions}")
        # tensor parallelism (serving/tp): geometry checked HERE, where
        # the model's head/mlp dims are known; the mesh, the sharded
        # parameter placement, and the shard_map forward are all
        # resolved once so TP is static under the jitted steps below
        from mpi_tensorflow_tpu.serving import tp as tp_lib

        tp_lib.check_geometry(model.cfg, serve.tp)
        self.tp_mesh = (tp_lib.make_tp_mesh(serve.tp)
                        if serve.tp > 1 else None)
        # resolve auto -> xla|pallas ONCE, host-side: the literal bakes
        # into the jitted steps below, so kernel choice cannot add
        # dispatch shapes or recompiles (the zero-recompile contract
        # covers the kernel path by construction).  Under TP each shard
        # runs the kernel over its LOCAL heads, so the compile probe
        # must see the per-shard head count
        kcfg = (model.cfg if serve.tp == 1 else dataclasses.replace(
            model.cfg, heads=model.cfg.heads // serve.tp))
        self.kernel = paged_ops.resolve_kernel(
            serve.kernel, kcfg, serve.block_size,
            serve.prefill_chunk, serve.kv_dtype, serve.kv_group)
        if self.tp_mesh is not None:
            self.params = tp_lib.shard_params(model, params, self.tp_mesh)
            self._paged_forward = tp_lib.make_paged_forward(
                model, self.tp_mesh, self.kernel,
                kv_dtype=serve.kv_dtype)
        else:
            self.params = params
            self._paged_forward = (
                lambda params, tokens, pools, tables, lengths, valid:
                model.forward_paged(params, tokens, pools, tables,
                                    lengths, valid=valid,
                                    kernel=self.kernel))
        # donate the pools so the TPU cache updates in place; CPU (the
        # test platform) does not implement donation — skip the arg to
        # keep the suite free of spurious donation warnings
        donate = (1,) if jax.default_backend() == "tpu" else ()
        self._decode_fn = jax.jit(self._decode_impl, donate_argnums=donate)
        self._prefill_fn = jax.jit(self._prefill_impl,
                                   donate_argnums=donate)
        # copy-on-write block copy: pools in, pools out, fixed shapes —
        # exactly ONE compile ever (block ids ride as traced scalars)
        self._cow_fn = jax.jit(
            self._cow_impl,
            donate_argnums=(0,) if jax.default_backend() == "tpu" else ())
        # partial tail-block copy (prefix v2): same discipline as
        # _cow_fn — block ids AND the row count ride as traced scalars,
        # so every (src, dst, n) reuses the one compiled program
        self._partial_fn = jax.jit(
            self._partial_impl,
            donate_argnums=(0,) if jax.default_backend() == "tpu" else ())
        # host-tier promotion (--serve-kv-tier host): write a demoted
        # block's host bytes into a freshly allocated device block —
        # same discipline as _cow_fn/_partial_fn: the destination id
        # rides as a traced scalar and the host leaves have one fixed
        # shape (a single block row per pool leaf), so every promotion
        # reuses the one compiled program
        self._promote_fn = jax.jit(
            self._promote_impl,
            donate_argnums=(0,) if jax.default_backend() == "tpu" else ())
        # speculative decoding: the verify step runs pending + k draft
        # tokens through one forward (chunked-prefill math, decode-style
        # batching); the drafter is a host-side policy object built ONCE
        # so its jit cache (draft-model mode) survives reset()
        self._verify_fn = jax.jit(self._verify_impl, donate_argnums=donate)
        # mixed batching: ONE fused prefill+decode forward per step
        # (--serve-mixed-batch on); shares the verify dispatch's
        # masking math — decode rows are the chunk=1 degenerate case
        self._mixed_fn = jax.jit(self._mixed_impl, donate_argnums=donate)
        self.drafter = spec_lib.make_drafter(
            serve.speculative, serve, model,
            draft_model=draft_model, draft_params=draft_params)
        # draft-window auto-tuning (--serve-draft-auto on): EWMA of the
        # accepted length per verify forward drives the EFFECTIVE k.
        # Initialized optimistic (full window) and NOT cleared by
        # reset(): like the jit caches, the learned window is warmed
        # state a trace replay should keep — and it can never change
        # emitted tokens, only how much draft work is attempted
        self._accept_ewma = float(serve.draft_k)
        self._draft_k_eff = serve.draft_k
        self.reset()
        if self.prefix_cache is not None:
            # pre-pay the CoW copy's single compile with a null-block
            # self-copy (a no-op write), so the first real CoW inside a
            # timed steady-state window can never register as a
            # recompile against the zero-recompile contract
            import jax.numpy as jnp

            z = jnp.asarray(0, jnp.int32)
            self.pools = self._cow_fn(self.pools, z, z)
            if self.serve.prefix_gen == "on":
                # same contract for the partial-copy dispatch: a zero-
                # row null-block self-copy is a no-op write that pays
                # its one compile before any timed window opens
                self.pools = self._partial_fn(self.pools, z, z, z)
            if self.serve.kv_tier == "host":
                # same contract for the promote dispatch: a zero-leaf
                # write into the null block pays its one compile, so a
                # first promotion inside a timed steady-state window
                # can never register as a recompile
                host0 = [{key: jnp.zeros(leaf.shape[1:], leaf.dtype)
                          for key, leaf in p.items()} for p in self.pools]
                self.pools = self._promote_fn(self.pools, host0, z)
        if self.drafter is not None:
            # pre-warm the verify dispatch at EVERY (slot bucket, table
            # bucket) x width-(k+1) shape, plus the drafter's own chunk
            # buckets: how many tokens a verify step emits — and hence
            # which buckets later steps hit — depends on ACCEPTANCE,
            # i.e. on token content, so a warmup trace replay cannot be
            # trusted to visit every bucket the timed trace will.  The
            # zero-recompile contract must not hinge on content luck.
            self._prewarm_verify()
            if hasattr(self.drafter, "warmup"):
                self.drafter.warmup()
        if serve.mixed_batch == "on":
            # same contract for the fused mixed dispatch: which (slot,
            # chunk, table) buckets a step hits depends on ARRIVAL
            # TIMING — how many sequences are mid-prefill at once and
            # how they split the budget — which a warmup trace replay
            # cannot be trusted to reproduce.  Pay every bucket triple
            # at build, before any timed window opens.
            self._prewarm_mixed()

    def reset(self) -> None:
        """Fresh pools/scheduler; jit caches (and their warmed bucket
        shapes) survive — the bench harness times a second trace replay
        against exactly the compiles the first replay paid for."""
        from mpi_tensorflow_tpu.serving import prefix_cache as prefix_lib

        self.pools = paged_cache.init_pools(
            self.model.cfg, self.serve.num_blocks, self.serve.block_size,
            self.serve.kv_dtype, self.serve.kv_group)
        if self.tp_mesh is not None:
            # head-axis sharding (serving/tp): one block id addresses
            # the same slot of every shard's local-heads pool, so the
            # host allocator/scheduler/trie below stay tp-unaware
            from mpi_tensorflow_tpu.serving import tp as tp_lib

            self.pools = tp_lib.shard_pools(self.pools, self.tp_mesh)
        self.allocator = paged_cache.BlockAllocator(self.serve.num_blocks)
        # fresh trie with fresh pools: cached content lives in the pool,
        # so the two reset together (a stale trie would map new
        # sequences onto zeroed blocks)
        self.prefix_cache = (
            prefix_lib.PrefixCache(self.allocator, self.serve.block_size)
            if self.serve.prefix_cache == "on" else None)
        # host-RAM block tier (--serve-kv-tier host): resets WITH the
        # pools/trie — stored bytes index device content that just went
        # away, and crash recovery rebuilds both from the journal
        self.tier = (paged_cache.HostBlockStore()
                     if self.serve.kv_tier == "host" else None)
        if self.tier is not None and self.prefix_cache is not None:
            self.prefix_cache.tier = self.tier
            self.prefix_cache.demote_fetch = self._demote_fetch
            self.prefix_cache.promote_put = self._promote_put
        if self.drafter is not None:
            # the draft pool indexes device state that resets with the
            # engine's own pools (crash recovery rebuilds both)
            self.drafter.reset()
        self.sched = sched_lib.Scheduler(
            self.allocator, self.serve.max_slots, self.serve.block_size,
            self.serve.max_blocks_per_seq,
            queue_depth=self.serve.queue_depth,
            max_evictions=self.serve.max_evictions,
            prefix_cache=self.prefix_cache,
            prefix_gen=self.serve.prefix_gen == "on",
            on_terminal=self._on_terminal)
        # pool-occupancy high-water marks: raw = every referenced block
        # (includes trie-retained blocks, which are reclaimable cache);
        # live = distinct blocks mapped by live sequences — the
        # occupancy that actually gates admission, and the number
        # sharing shrinks (two sequences on one physical block count it
        # once)
        self.peak_blocks_in_use = 0
        self.peak_live_blocks = 0
        # tracing resets WITH the engine state (like the pools/trie): a
        # rebuilt engine is a fresh incarnation whose spans the caller
        # merges across harvests.  trace off = no tracer object at all,
        # so every instrumentation site is a `tracer is None` skip
        self.tracer = (tracing.EngineTracer()
                       if self.serve.trace == "on" else None)
        self._progressed = False        # did the last step() do any work
        self._journal = None            # set by run(); step() journals a
                                        # token BEFORE record_token so the
                                        # durable order is always tok-then-
                                        # end (an end-ok preceding its own
                                        # finishing token would replay a
                                        # truncated stream as complete)
        self._last_token: dict = {}     # slot -> next token to feed
        # admitted (slot, Sequence) pairs awaiting prefill: the sequence
        # identity guards against a slot being evicted and re-admitted
        # while queued — a stale entry must not prefill the NEW occupant
        self._prefill_queue: List[tuple] = []
        self.dispatch_shapes: set = set()
        # model-forward dispatches this run (prefill + decode + verify
        # + mixed; CoW/partial copies excluded — they move cache rows,
        # not tokens): dispatches-per-emitted-token is THE CPU-visible
        # win metric of mixed batching (bench --serve-mixed-ab)
        self.forward_dispatches = 0

    def _on_terminal(self, req, status: str) -> None:
        """THE per-request exit hook (installed on every scheduler this
        engine builds): release the drafter's per-request state, then
        forward to the replay journal when one is attached — chaining
        here (instead of run() overwriting ``sched.on_terminal``) keeps
        the tok-then-end durable ordering AND the draft-pool lifecycle
        in one place."""
        if self.drafter is not None:
            self.drafter.release(req.id)
        if self._journal is not None:
            self._journal.record_end(req, status)
        if self.tracer is not None:
            # clock-free on purpose: this fires inside step() where no
            # loop clock is in scope; the tracer queues the transition
            # and EngineLoop.iterate lands it with the post-step stamp
            self.tracer.on_terminal(req, status)

    # ---------------- jitted device steps ----------------

    def _decode_impl(self, params, pools, tokens, lengths, tables):
        """(B,) tokens at per-row positions ``lengths`` -> (B,) greedy
        next tokens + updated pools.  Padding rows (bucket slack) carry
        all-null tables; their writes land in the null block and their
        output is discarded on host."""
        import jax.numpy as jnp

        from mpi_tensorflow_tpu.ops.paged_attention import NULL_BLOCK

        live = (tables[:, 0] != NULL_BLOCK)[:, None]
        logits, pools = self._paged_forward(
            params, tokens[:, None], pools, tables, lengths, live)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return nxt, pools

    def _prefill_impl(self, params, pools, tokens, length, n_real, tables):
        """One (1, chunk) prefill dispatch: writes the chunk's KV into
        the row's blocks, returns the greedy token following the LAST
        REAL lane (meaningful only on the final chunk) + updated pools."""
        import jax.numpy as jnp

        S = tokens.shape[1]
        valid = jnp.arange(S)[None] < n_real
        logits, pools = self._paged_forward(
            params, tokens, pools, tables, length[None], valid)
        nxt = jnp.argmax(logits[0, jnp.maximum(n_real - 1, 0)], axis=-1)
        return nxt.astype(jnp.int32), pools

    def _cow_impl(self, pools, src, dst):
        """Copy one pool block (all layers, K and V — and, under an int8
        pool, the scale siblings riding the same leading block axis):
        the device half of copy-on-write.  ``src``/``dst`` are traced
        scalars, so every copy reuses the one compiled program."""
        return [{key: leaf.at[dst].set(leaf[src])
                 for key, leaf in p.items()} for p in pools]

    def _partial_impl(self, pools, src, dst, n):
        """Copy the first ``n`` token-slot rows of block ``src`` into
        ``dst`` (serving/paged_cache.partial_copy_block): the device
        half of partial tail-block sharing.  All three operands are
        traced scalars — one compile, like ``_cow_impl``."""
        return paged_cache.partial_copy_block(pools, src, dst, n)

    def _promote_impl(self, pools, host, dst):
        """Write one block row of host leaves into pool block ``dst``
        (all layers, every leaf — codes and, under quantized pools,
        their scale siblings): the device half of tier promotion.
        ``dst`` is a traced scalar; ``host`` is a per-layer list of
        single-block leaves with one fixed shape — one compile."""
        return [{key: leaf.at[dst].set(hb[key])
                 for key, leaf in p.items()}
                for p, hb in zip(pools, host)]

    def _demote_fetch(self, block: int) -> list:
        """Copy pool block ``block`` to host (per-layer dicts of
        np.ndarray rows) — the prefix cache calls this just before
        eviction releases the device block (--serve-kv-tier host)."""
        return [{key: np.asarray(leaf[block])  # graft-lint: sync-ok(cold-block demotion off the dispatch path)
                 for key, leaf in p.items()} for p in self.pools]

    def _promote_put(self, leaves: list, block: int) -> None:
        """Land demoted host bytes in freshly allocated device block
        ``block`` via the pre-warmed one-compile promote dispatch —
        called during the admission match walk, BEFORE the sequence's
        first dispatch, so the promoted content is in place when the
        block table first references it."""
        import jax.numpy as jnp

        t0 = time.perf_counter()
        host = [{k: jnp.asarray(v) for k, v in p.items()} for p in leaves]
        self.pools = self._promote_fn(self.pools, host,
                                      jnp.asarray(block, jnp.int32))
        self.tier.promote_ms_total += (time.perf_counter() - t0) * 1e3

    def _verify_impl(self, params, pools, tokens, lengths, n_valid,
                     tables):
        """The speculative VERIFY dispatch: row ``b`` feeds its pending
        token plus its draft (``n_valid[b]`` real lanes of the fixed
        ``draft_k + 1`` width) at positions ``lengths[b] + lane``
        through ONE forward — the chunked-prefill math at decode-style
        batching.  Returns the greedy argmax at EVERY lane (``(B, W)``):
        lane ``i``'s token is what vanilla decode would emit after
        consuming the first ``i`` draft tokens, which is exactly the
        chain the host-side acceptance walk compares the draft against.
        Padding lanes (row slack or bucket slack) scatter into the null
        block and their argmax is discarded on host."""
        import jax.numpy as jnp

        from mpi_tensorflow_tpu.ops.paged_attention import NULL_BLOCK

        W = tokens.shape[1]
        live = tables[:, 0] != NULL_BLOCK
        valid = (jnp.arange(W)[None] < n_valid[:, None]) & live[:, None]
        logits, pools = self._paged_forward(
            params, tokens, pools, tables, lengths, valid)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), pools

    def _mixed_impl(self, params, pools, tokens, lengths, n_valid,
                    tables):
        """The fused mixed prefill+decode dispatch (--serve-mixed-batch
        on): row ``b`` feeds ``n_valid[b]`` real lanes at positions
        ``lengths[b] + lane`` through ONE forward.  A decode row is the
        chunk=1 degenerate case (its pending token at position
        length-1); a prefill row is a budget-capped chunk of its prompt
        at its prefilled offset.  The chunked-prefill math already
        masks per-row lengths (ops/paged_attention.attend), so the
        fused batch is EXACT — each row sees precisely the context the
        unfused dispatch would give it, and greedy outputs are
        token-identical to mixed-off by construction.  Returns the
        greedy argmax at EVERY lane ``(B, S)``; the host consumes lane
        ``n_valid[b] - 1`` for decode rows and prompt-completing
        prefill rows only.  Padding lanes (row slack or bucket slack)
        scatter into the null block and their argmax is discarded on
        host."""
        import jax.numpy as jnp

        from mpi_tensorflow_tpu.ops.paged_attention import NULL_BLOCK

        S = tokens.shape[1]
        live = tables[:, 0] != NULL_BLOCK
        valid = (jnp.arange(S)[None] < n_valid[:, None]) & live[:, None]
        logits, pools = self._paged_forward(
            params, tokens, pools, tables, lengths, valid)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), pools

    def _prewarm_mixed(self) -> None:
        """Compile the fused mixed dispatch at every (slot bucket,
        chunk bucket, table bucket) triple it can ever run at —
        all-null tables, zero valid lanes, so nothing real is touched.
        The chunk-bucket axis is capped by the smaller of the chunk
        size and the prefill budget (a single row can never carry more
        lanes than either allows).  Same argument as _prewarm_verify:
        bucket visits depend on arrival timing, not just the trace
        envelope, so the zero-recompile contract is paid up front."""
        import jax.numpy as jnp
        import numpy as np

        serve = self.serve
        s_cap = _bucket(min(serve.prefill_chunk, serve.prefill_budget),
                        serve.prefill_chunk)
        Bb = 1
        while True:
            Sb = 1
            while True:
                NBb = 1
                while True:
                    _, self.pools = self._mixed_fn(
                        self.params, self.pools,
                        jnp.asarray(np.zeros((Bb, Sb), np.int32)),
                        jnp.asarray(np.zeros((Bb,), np.int32)),
                        jnp.asarray(np.zeros((Bb,), np.int32)),
                        jnp.asarray(np.zeros((Bb, NBb), np.int32)))
                    if NBb >= serve.max_blocks_per_seq:
                        break
                    NBb = min(NBb * 2, serve.max_blocks_per_seq)
                if Sb >= s_cap:
                    break
                Sb = min(Sb * 2, s_cap)
            if Bb >= serve.max_slots:
                break
            Bb = min(Bb * 2, serve.max_slots)

    def prewarm_decode(self) -> None:
        """Compile the decode dispatch at every (slot bucket, table
        bucket) pair it can ever run at — all-null tables, so nothing
        real is touched.  NOT called at build: the normal engine pays
        decode compiles in its first (warmup) replay.  Bench control
        arms call this explicitly when their zero-recompile probe must
        hold on a wall-clock arrival trace (--serve-mixed-ab's off
        arm): which (occupancy, table-width) pair a decode step runs
        at tracks arrival TIMING, and a compile stall in the warmup
        replay slows it enough to visit different buckets than the
        stall-free timed replay — the same argument that makes
        _prewarm_mixed a build-time obligation."""
        import jax.numpy as jnp
        import numpy as np

        Bb = 1
        while True:
            NBb = 1
            while True:
                _, self.pools = self._decode_fn(
                    self.params, self.pools,
                    jnp.asarray(np.zeros((Bb,), np.int32)),
                    jnp.asarray(np.zeros((Bb,), np.int32)),
                    jnp.asarray(np.zeros((Bb, NBb), np.int32)))
                if NBb >= self.serve.max_blocks_per_seq:
                    break
                NBb = min(NBb * 2, self.serve.max_blocks_per_seq)
            if Bb >= self.serve.max_slots:
                break
            Bb = min(Bb * 2, self.serve.max_slots)

    def _prewarm_verify(self) -> None:
        """Compile the verify dispatch at every (slot bucket, table
        bucket) it can ever run at — all-null tables, zero valid lanes,
        so nothing real is touched.  Verify-step bucket visits depend
        on acceptance — token content — so the contract is paid up
        front.  (Decode bucket visits also drift with arrival timing
        on wall-clock traces; ``prewarm_decode`` covers that for the
        bench arms that need it.)"""
        import jax.numpy as jnp
        import numpy as np

        W = self.serve.draft_k + 1
        Bb = 1
        while True:
            NBb = 1
            while True:
                toks, self.pools = self._verify_fn(
                    self.params, self.pools,
                    jnp.asarray(np.zeros((Bb, W), np.int32)),
                    jnp.asarray(np.zeros((Bb,), np.int32)),
                    jnp.asarray(np.zeros((Bb,), np.int32)),
                    jnp.asarray(np.zeros((Bb, NBb), np.int32)))
                if NBb >= self.serve.max_blocks_per_seq:
                    break
                NBb = min(NBb * 2, self.serve.max_blocks_per_seq)
            if Bb >= self.serve.max_slots:
                break
            Bb = min(Bb * 2, self.serve.max_slots)

    # ---------------- host-side step assembly ----------------

    def _ensure_private(self, slot: int, start: int, end: int) -> bool:
        """Copy-on-write guard for the write_kv path: before a dispatch
        writes cache positions ``[start, end)`` for ``slot``, any
        backing block that is SHARED (allocator refcount > 1 — other
        sequences and/or the prefix trie read it) is replaced by a
        private copy: allocate a fresh block (evicting under pressure),
        copy the shared block's contents on device, release the shared
        reference, and point the block table at the copy.  The one
        structural trigger is the shared-final-block recompute (a fully
        cached prompt whose length is an exact block multiple); the
        decode step runs the same guard as defense in depth — a write
        may NEVER land in a block another reader maps.

        Returns False when the pool cannot supply a copy target — the
        caller fails this one request, like any allocation dead end."""
        if self.prefix_cache is None or start >= end:
            return True
        seq = self.sched.slots[slot]
        bs = self.serve.block_size
        import jax.numpy as jnp

        for j in range(start // bs, (end - 1) // bs + 1):
            if j >= len(seq.block_ids):
                continue            # growth handled by ensure_block
            src = seq.block_ids[j]
            if self.allocator.refcount(src) <= 1:
                continue            # exclusive: in-place write is safe
            dst = self.sched.alloc_for(slot)
            if dst is None:
                return False
            self.pools = self._cow_fn(self.pools,
                                      jnp.asarray(src, jnp.int32),
                                      jnp.asarray(dst, jnp.int32))
            self.allocator.release([src])
            seq.block_ids[j] = dst
            self.sched.counters["prefix_cow_copies"] += 1
        return True

    def _apply_partial_copies(self) -> None:
        """Land every pending partial tail-block copy (prefix v2):
        admission matched ``partial_rows`` leading tokens of a slot's
        tail block against cached block ``partial_src`` and charged the
        sequence as if they were prefilled — the rows must be on device
        before the first prefill chunk reads past them.  Runs right
        after admit() in step(), so eviction cannot intervene; a slot
        whose sequence left anyway (pin already dropped by the
        scheduler) is skipped."""
        import jax.numpy as jnp

        for seq in self.sched.slots:
            if seq is None or seq.partial_src is None:
                continue
            self.pools = self._partial_fn(
                self.pools, jnp.asarray(seq.partial_src, jnp.int32),
                jnp.asarray(seq.partial_dst, jnp.int32),
                jnp.asarray(seq.partial_rows, jnp.int32))
            self.sched._release_partial(seq)

    def _track_occupancy(self) -> None:
        """Advance the pool-occupancy high-water marks (see reset)."""
        self.peak_blocks_in_use = max(self.peak_blocks_in_use,
                                      self.allocator.num_used)
        live = {b for s in self.sched.slots if s is not None
                for b in s.block_ids}
        self.peak_live_blocks = max(self.peak_live_blocks, len(live))

    def _table_row(self, seq, width: int) -> np.ndarray:
        row = np.zeros((width,), np.int32)
        ids = seq.block_ids[:width]
        row[:len(ids)] = ids
        return row

    def _advance_prefill(self) -> List[Tuple[int, int]]:
        """Advance the oldest mid-prefill sequence by ONE chunk (chunked
        prefill: new prompts trickle into the pool between decode steps
        instead of stalling them for a whole long prompt).  Returns the
        ``(request id, token)`` the final chunk emits, if any."""
        import jax.numpy as jnp

        while self._prefill_queue:
            slot, seq = self._prefill_queue[0]
            if self.sched.slots[slot] is not seq:
                # evicted while queued (and possibly re-admitted: the
                # new occupant has its own queue entry) — drop the stale
                # entry, never prefill on its behalf
                self._prefill_queue.pop(0)
                continue
            break
        else:
            return []
        prompt = seq.request.prompt
        self._progressed = True          # a chunk enters the pool
        chunk = prompt[seq.prefilled:seq.prefilled + self.serve.prefill_chunk]
        if not self._ensure_private(slot, seq.prefilled,
                                    seq.prefilled + len(chunk)):
            # no pool room for a private copy of a shared block this
            # chunk writes into: fail this one request, keep serving
            self._prefill_queue.pop(0)
            self.sched.fail_live(slot, "rejected")
            return []
        sb = _bucket(len(chunk), self.serve.prefill_chunk)
        toks = np.zeros((1, sb), np.int32)
        toks[0, :len(chunk)] = chunk
        tables = self._table_row(seq, self.serve.max_blocks_per_seq)[None]
        self.dispatch_shapes.add(("prefill", sb))
        self.forward_dispatches += 1
        tr = self.tracer
        if tr is not None:
            _m0 = time.monotonic()
        nxt, self.pools = self._prefill_fn(
            self.params, self.pools, jnp.asarray(toks),
            jnp.asarray(seq.prefilled, jnp.int32),
            jnp.asarray(len(chunk), jnp.int32), jnp.asarray(tables))
        if tr is not None:
            tr.dispatch_s += time.monotonic() - _m0
        seq.prefilled += len(chunk)
        if seq.prefilled < len(prompt):
            return []
        self._prefill_queue.pop(0)
        if self.prefix_cache is not None:
            # register the fully prefilled prompt's full blocks BEFORE
            # record_token can finish the request and release them: the
            # trie's own reference is what keeps a cached block alive
            # past its donor sequence
            self.prefix_cache.insert(prompt, seq.block_ids)
        # the prompt's last position already yields the first output
        # token (exactly generate()'s prefill-argmax), so the slot
        # enters the decode pool one token ahead
        if tr is not None:
            _m0 = time.monotonic()
        tok = int(nxt)  # graft-lint: sync-ok(one scalar per admission, not per step)
        if tr is not None:
            tr.consume_s += time.monotonic() - _m0
        self._last_token[slot] = tok
        if self._journal is not None:
            self._journal.record_token(seq.request.id, tok)
        self.sched.record_token(slot, tok, self.serve.eos_id)
        return [(seq.request.id, tok)]

    def step(self) -> List[Tuple[int, int]]:
        """One engine iteration: admit, advance one prefill chunk, decode
        every live slot once.  Returns the ``(request id, token)`` pairs
        emitted."""
        import jax.numpy as jnp

        self._progressed = False
        admitted = self.sched.admit()
        self._track_occupancy()
        if admitted:
            self._progressed = True
        self._prefill_queue.extend(
            (slot, self.sched.slots[slot]) for slot in admitted)
        self._apply_partial_copies()
        if self.serve.mixed_batch == "on":
            # the fused path replaces BOTH the prefill and the decode
            # phases below; mixed off leaves them byte-for-byte
            return self._step_mixed()
        emitted = self._advance_prefill()

        if self.drafter is not None:
            return self._step_verify(emitted)

        live = []
        for slot in self.sched.live_slots():
            seq = self.sched.slots[slot]
            if seq is None or seq.prefilled < len(seq.request.prompt):
                continue            # mid-prefill: not in the decode pool
            if not self.sched.ensure_block(slot):
                # pool exhausted with nothing left to evict: THIS request
                # cannot grow — fail it alone (blocks freed, terminal
                # status recorded); every other in-flight stream keeps
                # serving.  Unreachable when submit()'s feasibility check
                # gates admission, kept as defense in depth: one request
                # must never take the engine down.
                self.sched.fail_live(slot, "rejected")
                continue
            if not self._ensure_private(slot, seq.length - 1, seq.length):
                self.sched.fail_live(slot, "rejected")
                continue
            live.append(slot)
        # eviction inside ensure_block/CoW may have retired a later slot
        live = [s for s in live if self.sched.slots[s] is not None]
        self._track_occupancy()
        if not live:
            return emitted
        self._progressed = True

        Bb = _bucket(len(live), self.serve.max_slots)
        nb = max(len(self.sched.slots[s].block_ids) for s in live)
        NBb = _bucket(nb, self.serve.max_blocks_per_seq)
        tokens = np.zeros((Bb,), np.int32)
        lengths = np.zeros((Bb,), np.int32)
        tables = np.zeros((Bb, NBb), np.int32)
        for j, slot in enumerate(live):
            seq = self.sched.slots[slot]
            tokens[j] = self._last_token[slot]
            # the pending token writes at position length-1: the cache
            # holds length-1 entries until this step lands it
            lengths[j] = seq.length - 1
            tables[j] = self._table_row(seq, NBb)
        self.dispatch_shapes.add(("decode", Bb, NBb))
        self.forward_dispatches += 1
        tr = self.tracer
        if tr is not None:
            _m0 = time.monotonic()
        nxt, self.pools = self._decode_fn(
            self.params, self.pools, jnp.asarray(tokens),
            jnp.asarray(lengths), jnp.asarray(tables))
        if tr is not None:
            _m1 = time.monotonic()
            tr.dispatch_s += _m1 - _m0
        nxt = np.asarray(nxt)  # graft-lint: sync-ok(the one budgeted bulk sync per decode dispatch)
        if tr is not None:
            tr.consume_s += time.monotonic() - _m1
        for j, slot in enumerate(live):
            tok = int(nxt[j])
            self._last_token[slot] = tok
            rid = self.sched.slots[slot].request.id
            emitted.append((rid, tok))
            if self._journal is not None:
                self._journal.record_token(rid, tok)
            self.sched.record_token(slot, tok, self.serve.eos_id)
        return emitted

    def _step_mixed(self) -> List[Tuple[int, int]]:
        """The fused replacement for the prefill-then-decode phases
        (--serve-mixed-batch on): pack the decode row of every live
        fully-prefilled slot PLUS budget-capped prefill chunks from
        every mid-prefill sequence the per-step token budget reaches
        into ONE forward, so decode ITL never stalls behind a long
        prompt and prefill is no longer serialized to one sequence per
        step.

        Packing rule: decode rows first (one pending token each), then
        the prefill queue in FIFO order — each mid-prefill sequence
        contributes ``min(prefill_chunk, remaining prompt, remaining
        budget)`` tokens until the budget runs out.  A slot is either
        decoding or mid-prefill, never both, so the row count is
        bounded by ``max_slots`` and the dispatch shape set stays
        (slot bucket) x (chunk bucket) x (table bucket), every triple
        pre-warmed at build (_prewarm_mixed).

        Every per-row invariant of the unfused loop holds per row:
        the stale-slot guard (an evicted entry must never prefill the
        slot's new occupant), ensure_block + CoW over exactly the
        row's write range, trie insertion at full prefill BEFORE
        record_token, and the journal's tok-then-end order."""
        import jax.numpy as jnp

        serve = self.serve
        emitted: List[Tuple[int, int]] = []
        # decode rows: the same admission/CoW discipline as the
        # unfused decode loop, row by row
        rows = []           # (slot, seq, lane tokens, start, is_prefill)
        for slot in self.sched.live_slots():
            seq = self.sched.slots[slot]
            if seq is None or seq.prefilled < len(seq.request.prompt):
                continue        # mid-prefill: packed below, not here
            if not self.sched.ensure_block(slot):
                self.sched.fail_live(slot, "rejected")
                continue
            if not self._ensure_private(slot, seq.length - 1, seq.length):
                self.sched.fail_live(slot, "rejected")
                continue
            rows.append((slot, seq, [self._last_token[slot]],
                         seq.length - 1, False))
        # prefill rows: FIFO over the queue under the per-step token
        # budget — MULTIPLE sequences advance per step, each by at most
        # one chunk; stale entries (evicted while queued, possibly
        # re-admitted: the new occupant has its own entry) are dropped,
        # never prefilled on behalf of
        budget = serve.prefill_budget
        for slot, seq in list(self._prefill_queue):
            if budget <= 0:
                break
            if self.sched.slots[slot] is not seq:
                self._prefill_queue = [
                    e for e in self._prefill_queue if e[1] is not seq]
                continue
            prompt = seq.request.prompt
            take = min(serve.prefill_chunk,
                       len(prompt) - seq.prefilled, budget)
            chunk = prompt[seq.prefilled:seq.prefilled + take]
            if not self._ensure_private(slot, seq.prefilled,
                                        seq.prefilled + len(chunk)):
                # no pool room for a private copy of a shared block
                # this chunk writes into: fail this one request alone
                self._prefill_queue = [
                    e for e in self._prefill_queue if e[1] is not seq]
                self.sched.fail_live(slot, "rejected")
                continue
            budget -= len(chunk)
            rows.append((slot, seq, list(chunk), seq.prefilled, True))
        # eviction inside ensure_block/CoW may have retired ANY earlier
        # row's slot (decode or mid-prefill): keep only rows whose slot
        # still holds the same sequence — a retired prefill row's queue
        # entry goes stale and drops on a later step
        rows = [r for r in rows if self.sched.slots[r[0]] is r[1]]
        self._track_occupancy()
        if not rows:
            return emitted
        self._progressed = True

        Bb = _bucket(len(rows), serve.max_slots)
        Sb = _bucket(max(len(r[2]) for r in rows), serve.prefill_chunk)
        nb = max(len(r[1].block_ids) for r in rows)
        NBb = _bucket(nb, serve.max_blocks_per_seq)
        tokens = np.zeros((Bb, Sb), np.int32)
        lengths = np.zeros((Bb,), np.int32)
        n_valid = np.zeros((Bb,), np.int32)
        tables = np.zeros((Bb, NBb), np.int32)
        for j, (slot, seq, lanes, start, _) in enumerate(rows):
            tokens[j, :len(lanes)] = lanes
            n_valid[j] = len(lanes)
            lengths[j] = start
            tables[j] = self._table_row(seq, NBb)
        self.dispatch_shapes.add(("mixed", Bb, Sb, NBb))
        self.forward_dispatches += 1
        tr = self.tracer
        if tr is not None:
            _m0 = time.monotonic()
        out, self.pools = self._mixed_fn(
            self.params, self.pools, jnp.asarray(tokens),
            jnp.asarray(lengths), jnp.asarray(n_valid),
            jnp.asarray(tables))
        if tr is not None:
            _m1 = time.monotonic()
            tr.dispatch_s += _m1 - _m0
        out = np.asarray(out)  # graft-lint: sync-ok(the one budgeted bulk sync per mixed dispatch)
        if tr is not None:
            tr.consume_s += time.monotonic() - _m1

        for j, (slot, seq, lanes, start, is_prefill) in enumerate(rows):
            if is_prefill:
                seq.prefilled += len(lanes)
                if seq.prefilled < len(seq.request.prompt):
                    continue        # still mid-prefill: no token emitted
                self._prefill_queue = [
                    e for e in self._prefill_queue if e[1] is not seq]
                if self.prefix_cache is not None:
                    # register the fully prefilled prompt's full blocks
                    # BEFORE record_token can finish the request and
                    # release them (same order as the unfused path)
                    self.prefix_cache.insert(seq.request.prompt,
                                             seq.block_ids)
            # lane n_valid-1 is exactly what the unfused dispatch
            # consumes: decode's argmax at its one lane, or prefill's
            # argmax after the prompt's last position
            tok = int(out[j, len(lanes) - 1])
            self._last_token[slot] = tok
            rid = seq.request.id
            emitted.append((rid, tok))
            if self._journal is not None:
                self._journal.record_token(rid, tok)
            self.sched.record_token(slot, tok, serve.eos_id)
        return emitted

    def _step_verify(self, emitted: List[Tuple[int, int]]) \
            -> List[Tuple[int, int]]:
        """The speculative replacement for the decode phase: draft up
        to ``draft_k`` tokens per live slot, verify every slot's window
        in ONE batched forward, accept the longest argmax-matching
        draft prefix plus the model's own token at the first mismatch,
        then roll back the blocks the rejected tail was parked in.

        Token identity with ``--serve-speculative off`` holds by
        construction: lane ``i`` of the verify output is the argmax
        over exactly the context vanilla decode would have at that
        position, and only argmax-chain-consistent tokens are emitted.
        A slot whose drafter proposes nothing rides the same dispatch
        with one valid lane — an exact one-token decode step."""
        import jax.numpy as jnp

        serve = self.serve
        bs = serve.block_size
        cap = serve.max_blocks_per_seq * bs
        # the step's draft-window cap: the configured k, or — under
        # --serve-draft-auto on — the EWMA-tuned effective k (floor 1
        # keeps a cheap probe alive so a recovering accept rate can
        # re-grow the window; the verify dispatch width stays draft_k+1
        # either way, so auto-tuning can never add a compile)
        k_cap = (self._draft_k_eff if serve.draft_auto == "on"
                 else serve.draft_k)
        live: List[int] = []
        drafts: dict = {}
        full_window: dict = {}
        for slot in self.sched.live_slots():
            seq = self.sched.slots[slot]
            if seq is None or seq.prefilled < len(seq.request.prompt):
                continue            # mid-prefill: not in the decode pool
            if not self.sched.ensure_block(slot):
                self.sched.fail_live(slot, "rejected")
                continue
            # draft window, bounded so a full accept can neither bust
            # the request's budget (k <= remaining - 1: at most
            # ``remaining`` tokens emitted) nor the table capacity
            remaining = seq.request.max_new_tokens - len(seq.generated)
            k = min(k_cap, remaining - 1, cap - seq.length)
            # whether this row was OFFERED the policy's full window: a
            # row truncated by its budget, table capacity, or pool
            # pressure necessarily accepts few tokens, which says
            # nothing about the drafter — the auto-tune EWMA must not
            # read truncation as inaccuracy
            window_full = k >= k_cap
            draft: List[int] = []
            if k > 0:
                ctx = list(seq.request.prompt) + seq.generated
                draft = list(self.drafter.draft(
                    seq.request.id, ctx, k))[:k]
            if draft:
                # cover the whole window's writes [length-1, length+|d|)
                # with free blocks only — speculation never preempts
                covered = self.sched.extend_for(slot,
                                                seq.length + len(draft))
                if covered - seq.length < len(draft):
                    window_full = False
                draft = draft[:max(0, covered - seq.length)]
            full_window[slot] = window_full
            if not self._ensure_private(slot, seq.length - 1,
                                        seq.length + len(draft)):
                self.sched.fail_live(slot, "rejected")
                continue
            live.append(slot)
            drafts[slot] = draft
        # eviction inside ensure_block/CoW may have retired a later slot
        live = [s for s in live if self.sched.slots[s] is not None]
        self._track_occupancy()
        if not live:
            return emitted
        self._progressed = True

        W = serve.draft_k + 1
        Bb = _bucket(len(live), serve.max_slots)
        nb = max(len(self.sched.slots[s].block_ids) for s in live)
        NBb = _bucket(nb, serve.max_blocks_per_seq)
        tokens = np.zeros((Bb, W), np.int32)
        lengths = np.zeros((Bb,), np.int32)
        n_valid = np.zeros((Bb,), np.int32)
        tables = np.zeros((Bb, NBb), np.int32)
        for j, slot in enumerate(live):
            seq = self.sched.slots[slot]
            row = [self._last_token[slot]] + drafts[slot]
            tokens[j, :len(row)] = row
            n_valid[j] = len(row)
            lengths[j] = seq.length - 1
            tables[j] = self._table_row(seq, NBb)
        self.dispatch_shapes.add(("verify", Bb, NBb))
        self.forward_dispatches += 1
        tr = self.tracer
        if tr is not None:
            _m0 = time.monotonic()
        out, self.pools = self._verify_fn(
            self.params, self.pools, jnp.asarray(tokens),
            jnp.asarray(lengths), jnp.asarray(n_valid),
            jnp.asarray(tables))
        if tr is not None:
            _m1 = time.monotonic()
            tr.dispatch_s += _m1 - _m0
        out = np.asarray(out)  # graft-lint: sync-ok(the one budgeted bulk sync per verify dispatch)
        if tr is not None:
            tr.consume_s += time.monotonic() - _m1

        counters = self.sched.counters
        for j, slot in enumerate(live):
            seq = self.sched.slots[slot]
            draft = drafts[slot]
            # longest exact-match prefix of the draft, then the model's
            # own token at the first mismatch (or after a full accept)
            n_acc = 0
            while n_acc < len(draft) and int(out[j, n_acc]) == draft[n_acc]:
                n_acc += 1
            emit = draft[:n_acc] + [int(out[j, n_acc])]
            if serve.eos_id is not None and serve.eos_id in emit:
                # nothing streams past EOS — and nothing past it may be
                # journaled either (the journal holds accepted tokens
                # only, and EOS terminates acceptance)
                emit = emit[:emit.index(serve.eos_id) + 1]
            counters["spec_drafted"] += len(draft)
            counters["spec_accepted"] += min(n_acc, len(emit))
            counters["spec_verify_forwards"] += 1
            counters["spec_emitted"] += len(emit)
            # effective-k accounting + EWMA update: the window the
            # policy would offer (k_cap) is what "effective k" means to
            # the bench's speculation block; the EWMA tracks ACCEPTED
            # length only over rows that drafted into a FULL window —
            # a row with no draft, or one truncated by budget/capacity/
            # pool pressure, says nothing about the drafter's accuracy
            counters["spec_k_sum"] += k_cap
            counters["spec_k_steps"] += 1
            if serve.draft_auto == "on" and draft \
                    and full_window.get(slot, False):
                a = 0.2
                self._accept_ewma = ((1 - a) * self._accept_ewma
                                     + a * n_acc)
            self._last_token[slot] = emit[-1]
            rid = seq.request.id
            for tok in emit:
                emitted.append((rid, tok))
                if self._journal is not None:
                    self._journal.record_token(rid, tok)
            self.sched.record_tokens(slot, emit, serve.eos_id)
            if self.sched.slots[slot] is seq:
                # rollback: the rejected tail's phantom KV writes sit in
                # blocks past the accepted length — release them so the
                # pool never retains entries no accepted token owns
                self.sched.rollback_blocks(slot, seq.length)
        if serve.draft_auto == "on":
            # next step's window: one past the recent mean accepted
            # length (draft what history says will land, plus one probe
            # token of headroom), clamped to [1, configured k] — round,
            # not ceil: a near-zero EWMA must reach the floor instead
            # of parking one above it forever
            self._draft_k_eff = max(1, min(
                serve.draft_k, int(round(self._accept_ewma)) + 1))
        return emitted

    # ---------------- request loop ----------------

    def run(self, requests: List[sched_lib.Request],
            time_fn=time.perf_counter, *, guard=None, journal=None,
            advisor=None) -> dict:
        """Serve ``requests`` (replayed against their ``arrival`` stamps)
        to completion or graceful drain.  The per-token latency of a
        token is the wall time since the previous token of the SAME
        sequence (first token: since arrival, queueing included) — the
        stream cadence a client sees.  An evicted request's pre-eviction
        tokens are discarded from the latency sample (they are
        regenerated; only the final delivered stream counts), with its
        clock restarted at eviction.

        ``guard`` (train/preemption.PreemptionGuard or anything with a
        ``should_stop`` flag) wires SIGTERM into a graceful drain:
        admission stops (un-admitted work is ``shed``), in-flight
        sequences finish within ``serve.drain_ms`` (None = no budget),
        and whatever the budget cuts off terminates as ``drained``.
        ``journal`` (serving/recovery.ReplayJournal) records each
        request's prompt + generated prefix so a replacement process can
        replay live sequences token-identically.
        ``advisor`` (serving/autoscale.ScaleAdvisor) observes the
        scheduler's queue-depth / occupancy / shed-rate signals once
        per iteration; its advisory decision log rides the result as
        the ``autoscale`` block (None when no advisor is attached).

        The result dict carries per-request terminal ``statuses``, the
        ``faults`` health-counter block, and the ``drain`` outcome next
        to the existing throughput/latency numbers.
        """
        from mpi_tensorflow_tpu.serving.iteration import (DrainTracker,
                                                          EngineLoop)

        serve = self.serve
        # the shared per-iteration body (serving/iteration): submit
        # stamping, journal wiring (terminal routing runs through the
        # engine's chained _on_terminal hook, already installed on the
        # scheduler at reset()), latency cadence, eviction discard —
        # ONE implementation, also driven per-replica by the router
        loop = EngineLoop(self, journal)
        drain = DrainTracker(serve.drain_ms)
        pending = sorted(requests, key=lambda r: r.arrival)
        t0 = time_fn()
        while pending or not self.sched.all_done():
            now = time_fn() - t0
            if guard is not None and guard.should_stop \
                    and not drain.draining:
                # graceful drain: stop admission, shed everything not in
                # flight, let live sequences finish inside the budget
                drain.start(now, len(self.sched.finished))
                drain.shed = len(pending)
                for req in pending:
                    self.sched.fail_request(req, "shed")
                pending = []
                drain.shed += self.sched.shed_waiting()
            if drain.expired(now):
                # budget's hard edge: cut whatever is still in flight
                self.sched.abort_live("drained")
                break
            while pending and pending[0].arrival <= now:
                loop.submit(pending.pop(0))
            # deadline sweep + step + emit/evict accounting; step()
            # journals each token at emission, BEFORE the terminal hook
            # can fire — the durable order is tok-then-end, so an
            # end-ok can never precede its own finishing token
            emitted = loop.iterate(now, time_fn, t0)
            now = time_fn() - t0
            if advisor is not None:
                if self.tracer is not None \
                        and self.tracer.last_step is not None:
                    # with tracing on the advisor consumes the SAME
                    # step record the TraceBuffer holds, so its advice
                    # is explainable from the trace (ROADMAP item 2)
                    advisor.observe_step(self.tracer.last_step)
                else:
                    advisor.observe(now, **self.load_signals())
            if not emitted and not self._progressed:
                # no work moved this iteration (idle gap before the next
                # arrival, or live-but-stalled slots): sleep instead of
                # busy-spinning a host core at 100%
                delay = 1e-3
                if pending:
                    delay = min(delay, max(0.0, pending[0].arrival - now))
                if delay > 0:
                    time.sleep(delay)
        elapsed = time_fn() - t0
        # pool-leak invariant: every terminal request released its
        # blocks; only the prefix trie's own references may remain —
        # and the draft pool (every request terminal => every draft
        # state released by the terminal hook) must have drained too
        self.sched.check_quiescent()
        if self.drafter is not None:
            self.drafter.check_quiescent()
        outputs = {s.request.id: list(s.generated)
                   for s in self.sched.finished}
        total = sum(len(v) for v in outputs.values())
        flat = loop.latencies()
        lat = np.asarray(flat) if flat else np.zeros(1)
        from mpi_tensorflow_tpu.utils.metrics_writer import faults_block

        res = {
            "outputs": outputs,
            "statuses": dict(self.sched.statuses),
            "faults": faults_block(self.sched.counters),
            "drain": drain.result(len(self.sched.finished),
                                  self.sched.counters["drained"]),
            "kernel": self.kernel,
            "prefix": self.prefix_block(),
            "speculation": self.speculation_block(),
            "tier": self.tier_block(),
            "peak_blocks_in_use": self.peak_blocks_in_use,
            "peak_live_blocks": self.peak_live_blocks,
            "tokens": total,
            "elapsed_s": elapsed,
            "tokens_per_sec": total / elapsed if elapsed > 0 else 0.0,
            "p50_token_latency_ms": float(np.percentile(lat, 50)) * 1e3,
            "p99_token_latency_ms": float(np.percentile(lat, 99)) * 1e3,
            "evictions": self.sched.evictions,
            "dispatch_shapes": sorted(self.dispatch_shapes),
            # model-forward dispatch economy: mixed batching's win is
            # fewer dispatches per emitted token (one fused forward per
            # step vs prefill + decode), measurable on any backend
            "forward_dispatches": self.forward_dispatches,
            "dispatches_per_token": (self.forward_dispatches
                                     / max(1, total)),
            # final-token emit time per request on the run clock (the
            # same clock as Request.arrival): attained whole-request
            # latency = finish - arrival (serving/loadgen goodput join)
            "request_finish_s": dict(loop.last_emit),
            # FIRST-token emit time per request on the same clock:
            # TTFT = first - arrival (the headline latency mixed
            # batching moves; serving/loadgen joins it as ttft_ms)
            "request_first_token_s": dict(loop.first_emit),
            "autoscale": (advisor.report() if advisor is not None
                          else None),
        }
        if self.tracer is not None:
            # the `trace` key exists ONLY with tracing on: the off-path
            # result dict is byte-for-byte the untraced one
            h = self.tracer.harvest(elapsed)
            res["trace"] = {
                "enabled": True,
                "replicas": [{"pid": 0, "label": "engine", **h}],
                "spans": h["spans"],
                "steps": len(h["steps"]),
                "steps_dropped": h["steps_dropped"],
            }
        return res

    def load_signals(self) -> dict:
        """Instantaneous load signals for autoscale advice
        (serving/autoscale.ScaleAdvisor.observe) — the same ingredients
        as the router's least-load placement score: waiting-queue
        depth, live-slot fraction, pool occupancy (block 0 is the
        reserved null block), and the shed fraction of requests seen."""
        live = len(self.sched.live_slots())
        waiting = len(self.sched.waiting)
        seen = max(1, waiting + live + len(self.sched.statuses))
        return {
            "queue_depth": waiting,
            "live_fraction": live / self.serve.max_slots,
            "occupancy": (self.allocator.num_used
                          / max(1, self.serve.num_blocks - 1)),
            "shed_rate": self.sched.counters["shed"] / seen,
            # admitted-but-unprefilled prompt tokens, in prefill-chunk
            # units (~ pending prefill dispatches): queue depth alone
            # misses head-of-line work already holding slots but not
            # yet serving (Scheduler.prefill_backlog_tokens)
            "prefill_backlog": (self.sched.prefill_backlog_tokens
                                / max(1, self.serve.prefill_chunk)),
        }

    def prefix_block(self) -> dict:
        """Canonical prefix-cache accounting block for this engine's
        run (utils/metrics_writer.prefix_block — the ONE constructor
        engine results, the recovery supervisor, and bench JSON
        share)."""
        from mpi_tensorflow_tpu.utils.metrics_writer import prefix_block

        return prefix_block(
            self.sched.counters,
            enabled=self.prefix_cache is not None,
            trie_blocks=(self.prefix_cache.num_blocks
                         if self.prefix_cache is not None else 0))

    def speculation_block(self) -> dict:
        """Canonical speculative-decoding accounting block
        (utils/metrics_writer.speculation_block — shared with the
        recovery supervisor's cross-attempt merge and bench JSON)."""
        from mpi_tensorflow_tpu.utils.metrics_writer import \
            speculation_block

        return speculation_block(
            self.sched.counters, enabled=self.drafter is not None,
            mode=self.serve.speculative, draft_k=self.serve.draft_k,
            draft_auto=self.serve.draft_auto)

    def tier_block(self) -> dict:
        """Canonical host-tier accounting block
        (utils/metrics_writer.tier_block — the ONE constructor engine
        results and bench JSON share); zero-safe with tiering off."""
        from mpi_tensorflow_tpu.utils.metrics_writer import tier_block

        if self.tier is None:
            return tier_block()
        s = self.tier.stats()
        return tier_block(
            enabled=True, mode=self.serve.kv_tier,
            demotions=s["demotions"], promotions=s["promotions"],
            host_blocks=s["host_blocks"],
            host_blocks_peak=s["host_blocks_peak"],
            promote_ms_total=s["promote_ms_total"],
            block_size=self.serve.block_size)

    def compile_counts(self) -> dict:
        """Live jit-cache entry counts — THE zero-recompile probe: a
        steady-state serving window must not grow either number.  A
        count of ``None`` means the probe API is unavailable on this
        jax; consumers must treat that as UNKNOWN, never as "no
        recompiles" (two Nones comparing equal would make the verdict
        vacuously true)."""
        def size(fn):
            try:
                return int(fn._cache_size())
            except Exception:
                return None
        out = {"decode": size(self._decode_fn),
               "prefill": size(self._prefill_fn),
               "cow": size(self._cow_fn),
               "partial": size(self._partial_fn),
               "verify": size(self._verify_fn),
               "mixed": size(self._mixed_fn),
               "promote": size(self._promote_fn)}
        if self.drafter is not None:
            # a drafter's own jitted dispatches are inside the steady-
            # state loop too — the contract covers them like the
            # engine's (Drafter.compile_counts; {} for host-only ones)
            out.update(self.drafter.compile_counts())
        return out
