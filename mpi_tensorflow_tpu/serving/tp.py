"""Tensor-parallel paged decode: shard the serving stack over a ``tp``
mesh axis.

The serving engine's device state is one paged KV pool per layer,
head-major ``(num_blocks, H, block_size, D)``.  Heads are embarrassingly
parallel through attention (every head attends independently; the only
cross-head contractions are the row-parallel output projections), so the
Megatron split carries over to serving unchanged:

- the POOL shards on its head axis (axis 1): each of the ``tp`` shards
  holds ``H / tp`` heads of every block — aggregate KV capacity in
  tokens is unchanged per pool, but the HBM for it is spread over the
  mesh, and (the point) per-chip attention/projection work drops
  ``tp``-fold;
- the QKV projections split column-parallel on their ``heads`` output
  dim and the MLP up-projection on ``mlp``, so each shard computes only
  its local heads' K/V (which land in its local pool shard) and its
  local MLP slice;
- the attention out-proj and MLP down-proj are row-parallel: each shard
  contributes a partial ``(B, S, E)`` product and ONE ``lax.psum`` per
  projection (two per layer) rebuilds the replicated residual stream —
  the ``reduce`` hook ``models/gpt.forward_paged`` threads into
  ``attn_out_proj`` / ``gelu_mlp``;
- the BLOCK TABLE, tokens, and lengths replicate: a table indexes
  blocks, not heads, so the host-side scheduler/allocator/prefix-trie
  machinery is completely unaware of ``tp`` — one block id means the
  same block slot in every pool shard, copy-on-write copies every
  shard's rows of a block with the same traced ids, and eviction frees
  the same id everywhere.

Each shard runs the EXISTING ``ops/paged_attention.attend`` dispatch
(XLA gather or the fused Pallas kernel) over its local heads — ``H`` is
a pure batch dimension in both lowerings — and the logits every shard
computes after the psum points are identical, so greedy serving under
TP is token-identical to the single-device engine (pinned by
tests/test_serving_tp.py on a multi-device CPU mesh via the virtual
device platform).

Everything here is resolved ONCE at engine construction: the mesh, the
param/pool placements, and the shard_map-wrapped forward are all static
under the engine's jitted steps, so TP adds no dispatch shapes and the
zero-recompile contract holds exactly as on one device.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi_tensorflow_tpu.parallel import sharding_rules as rules_lib

#: the mesh axis name the serving TP split lives on
TP_AXIS = "tp"


def _check_device_count(tp: int) -> None:
    """THE device-count rule, shared by ``check_geometry`` and
    ``make_tp_mesh`` so the two entry points cannot drift."""
    ndev = len(jax.devices())
    if tp > ndev:
        raise ValueError(
            f"--serve-tp {tp} exceeds the {ndev} visible device(s)")


def check_geometry(cfg, tp: int) -> None:
    """Reject a ``tp`` the model/mesh cannot honor — the one place the
    head/mlp divisibility and device-count rules are stated (engine
    construction and bench both route through here)."""
    if tp < 1:
        raise ValueError(f"--serve-tp must be >= 1, got {tp}")
    if tp == 1:
        return
    _check_device_count(tp)
    if cfg.heads % tp or cfg.mlp % tp:
        raise ValueError(
            f"--serve-tp {tp} must divide both heads ({cfg.heads}) and "
            f"mlp ({cfg.mlp}): the pool shards on the head axis and the "
            f"MLP up-projection on its hidden axis")


def make_tp_mesh(tp: int) -> Mesh:
    """A 1-D ``(tp,)`` mesh over the first ``tp`` devices (guarded:
    slicing past the device list would silently build a smaller
    mesh)."""
    _check_device_count(tp)
    return Mesh(np.asarray(jax.devices()[:tp]), (TP_AXIS,))


def param_specs(model, mesh: Mesh):
    """PartitionSpec pytree for the model parameters under the serving
    TP rules (heads/mlp over ``tp``, everything else replicated)."""
    return rules_lib.tree_specs(model.logical_axes(), mesh,
                                rules_lib.SERVING_TP_RULES)


def pool_specs(layers: int, kv_dtype: str = "fp32"):
    """PartitionSpec pytree for the per-layer K/V pools: the head axis
    (axis 1 of ``(num_blocks, H, block_size, D)``) over ``tp``.  A
    quantized pool's scale siblings — ``(num_blocks, H, block_size)``
    int8 row scales or ``(num_blocks, H, block_size, G)`` int4 group
    scales — carry heads on the SAME axis 1, so one spec serves every
    leaf (int4's packed-code D//2 axis is unsharded, like D)."""
    s = P(None, TP_AXIS)
    if kv_dtype in ("int8", "int4"):
        return [{"k": s, "v": s, "k_scale": s, "v_scale": s}
                for _ in range(layers)]
    return [{"k": s, "v": s} for _ in range(layers)]


def shard_params(model, params, mesh: Mesh):
    """Place the parameter pytree onto the mesh per the TP rules."""
    return rules_lib.shard_tree(params, model.logical_axes(), mesh,
                                rules_lib.SERVING_TP_RULES)


def shard_pools(pools, mesh: Mesh):
    """Place freshly initialized (host-built) pools onto the mesh,
    head-axis sharded — generic over the layer dict's leaves (codes and,
    under int8, their scale siblings all put heads on axis 1)."""
    s = NamedSharding(mesh, P(None, TP_AXIS))
    return [{key: jax.device_put(leaf, s) for key, leaf in p.items()}
            for p in pools]


def make_paged_forward(model, mesh: Mesh, kernel: str,
                       kv_dtype: str = "fp32"):
    """The shard_map-wrapped ``forward_paged``: params and pools enter
    pre-sharded (heads/mlp/pool-head-axis over ``tp``), tokens / block
    tables / lengths / valid masks replicated.  Each shard runs the full
    per-layer math over its local heads with ``lax.psum`` over ``tp`` as
    the row-parallel reduce hook, so the returned logits are replicated
    (identical on every shard) and the returned pools stay head-sharded.

    Same signature as the engine's single-device forward seam:
    ``(params, tokens, pools, tables, lengths, valid) -> (logits,
    pools)``.
    """
    specs = param_specs(model, mesh)
    pspec = pool_specs(model.cfg.layers, kv_dtype)
    rep = P()

    def inner(params, tokens, pools, tables, lengths, valid):
        red = lambda x: jax.lax.psum(x, TP_AXIS)       # noqa: E731
        return model.forward_paged(params, tokens, pools, tables,
                                   lengths, valid=valid, kernel=kernel,
                                   reduce=red)

    # check_vma off: the psum points make the logits replicated by
    # construction, and the legacy-jax shard_map shim (utils/jaxcompat)
    # cannot see through psum-into-replicated anyway — exactly the
    # train-step call sites' convention
    return jax.shard_map(inner, mesh=mesh,
                         in_specs=(specs, rep, pspec, rep, rep, rep),
                         out_specs=(rep, pspec), check_vma=False)
