"""Paged KV cache: host block allocator + device pool construction.

The device side is a fixed pool of ``(num_blocks, heads, block_size,
head_dim)`` K and V blocks per transformer layer (ops/paged_attention
reads/writes it through per-sequence block tables; the head-major
layout lets the fused Pallas kernel stream whole ``(H, block_size, D)``
blocks with no transpose).  The host side —
this module — owns WHICH block belongs to WHOM: a free-list allocator
whose accounting the scheduler's admit/evict decisions hang off.

Block 0 is reserved as the null/scratch block (masked-lane scatter
target, ops/paged_attention.NULL_BLOCK) and is never handed out.
"""

from __future__ import annotations

from typing import List


class BlockAllocator:
    """Free-list allocator over pool block ids ``1..num_blocks-1``.

    Pure host Python (no jax import): the scheduler tests exercise
    admit/evict accounting without a device.  LIFO reuse keeps recently
    freed blocks hot in whatever cache hierarchy the pool lives in.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (block 0 is the reserved null block), "
                f"got {num_blocks}")
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._used: set = set()

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return len(self._used)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` blocks; raises when the pool cannot cover them —
        callers gate on ``can_alloc`` (admission) or evict first."""
        if n > len(self._free):
            raise RuntimeError(
                f"block pool exhausted: want {n}, have {len(self._free)} "
                f"free of {self.num_blocks - 1}")
        out = [self._free.pop() for _ in range(n)]
        self._used.update(out)
        return out

    def free(self, ids: List[int]) -> None:
        for b in ids:
            if b not in self._used:
                raise ValueError(f"double free / foreign block id {b}")
            self._used.remove(b)
            self._free.append(b)

    def check(self) -> None:
        """Invariant: every non-null block is free xor used, once."""
        assert len(self._free) + len(self._used) == self.num_blocks - 1
        assert len(set(self._free)) == len(self._free)
        assert not (set(self._free) & self._used)


def blocks_for(tokens: int, block_size: int) -> int:
    """Pool blocks needed to hold ``tokens`` cache entries."""
    return -(-tokens // block_size)


def init_pools(cfg, num_blocks: int, block_size: int) -> list:
    """Per-layer K/V block pools (zeros), mirroring the per-layer
    ``{"k", "v"}`` pytree shape of models/gpt.init_cache so the engine
    threads them through jit the same way."""
    import jax.numpy as jnp

    z = jnp.zeros((num_blocks, cfg.heads, block_size, cfg.head_dim),
                  cfg.dtype)
    return [{"k": z, "v": z} for _ in range(cfg.layers)]
