"""Paged KV cache: host block allocator + device pool construction.

The device side is a fixed pool of ``(num_blocks, heads, block_size,
head_dim)`` K and V blocks per transformer layer (ops/paged_attention
reads/writes it through per-sequence block tables; the head-major
layout lets the fused Pallas kernel stream whole ``(H, block_size, D)``
blocks with no transpose).  The host side —
this module — owns WHICH block belongs to WHOM: a refcounted free-list
allocator whose accounting the scheduler's admit/evict decisions hang
off.

Refcounts are what make PHYSICAL BLOCK SHARING safe (the PagedAttention
sharing/CoW design, arXiv:2309.06180): a prompt-prefix block cached by
the radix trie (serving/prefix_cache) is referenced by every sequence
whose table maps it PLUS the trie itself, and it returns to the free
list only when the last reference releases it.  ``alloc`` hands out
exclusive blocks (refcount 1), ``share`` adds a reference to a live
block, ``release`` drops one — all frees in the serving stack route
through ``release`` so releasing a sequence that shares prefix blocks
with live sequences can never corrupt them.

Block 0 is reserved as the null/scratch block (masked-lane scatter
target, ops/paged_attention.NULL_BLOCK) and is never handed out.
"""

from __future__ import annotations

from typing import Dict, List


class BlockAllocator:
    """Refcounted free-list allocator over pool block ids
    ``1..num_blocks-1``.

    Pure host Python (no jax import): the scheduler tests exercise
    admit/evict accounting without a device.  LIFO reuse keeps recently
    freed blocks hot in whatever cache hierarchy the pool lives in.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (block 0 is the reserved null block), "
                f"got {num_blocks}")
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}     # block id -> refcount (>= 1)

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return len(self._ref)

    def refcount(self, block: int) -> int:
        """Live references on ``block`` (0 = free / never allocated).
        A count > 1 means the block is SHARED — a writer must
        copy-on-write instead of scattering into it in place."""
        return self._ref.get(block, 0)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` exclusive blocks (refcount 1); raises when the pool
        cannot cover them — callers gate on ``can_alloc`` (admission) or
        evict first."""
        if n > len(self._free):
            raise RuntimeError(
                f"block pool exhausted: want {n}, have {len(self._free)} "
                f"free of {self.num_blocks - 1}")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def share(self, ids: List[int]) -> None:
        """Add one reference to each live block — the prefix cache maps
        an already-cached block into a new sequence's table instead of
        recomputing it."""
        for b in ids:
            if b not in self._ref:
                raise ValueError(f"share of free / foreign block id {b}")
            self._ref[b] += 1

    def release(self, ids: List[int]) -> None:
        """Drop one reference per block; a block returns to the free
        list only at refcount zero.  THE one free path: callers never
        need to know whether a block is shared."""
        for b in ids:
            c = self._ref.get(b, 0)
            if c < 1:
                raise ValueError(f"double free / foreign block id {b}")
            if c == 1:
                del self._ref[b]
                self._free.append(b)
            else:
                self._ref[b] = c - 1

    # legacy name: every free is a refcounted release (a block alloc'd
    # once and never shared behaves exactly as the pre-refcount free)
    free = release

    def check(self) -> None:
        """Invariant: every non-null block is free xor referenced, once;
        every referenced block carries a positive refcount."""
        assert len(self._free) + len(self._ref) == self.num_blocks - 1
        assert len(set(self._free)) == len(self._free)
        assert not (set(self._free) & set(self._ref))
        assert 0 not in self._ref and 0 not in self._free
        assert all(c >= 1 for c in self._ref.values()), \
            f"non-positive refcount in {self._ref}"


def blocks_for(tokens: int, block_size: int) -> int:
    """Pool blocks needed to hold ``tokens`` cache entries."""
    return -(-tokens // block_size)


def partial_copy_block(pools: list, src, dst, n) -> list:
    """Copy the first ``n`` token-slot rows of block ``src`` into block
    ``dst`` across every pool leaf, leaving rows ``>= n`` of ``dst``
    untouched — the device half of partial tail-block sharing
    (prefix v2): the trie matched ``n`` leading tokens of a sequence's
    tail block against a cached block, so those rows are copied out of
    the cache instead of re-prefilled, and the unique suffix lands on
    top.

    ``src``/``dst``/``n`` are TRACED int32 scalars — the caller jits
    this once (the ``_cow_fn`` discipline) and every (src, dst, n)
    triple reuses that one executable; ``n == 0`` with ``src == dst``
    is the no-op pre-warm dispatch.  The row mask broadcasts over the
    4-d code leaves AND the 3-d int8 scale siblings (slot axis is axis
    1 of ``leaf[src]`` either way), so quantized pools copy codes and
    scales together.
    """
    import jax.numpy as jnp

    out = []
    for p in pools:
        layer = {}
        for key, leaf in p.items():
            rows = jnp.arange(leaf.shape[2]) < n
            mask = rows.reshape((-1,) + (1,) * (leaf.ndim - 3))
            layer[key] = leaf.at[dst].set(
                jnp.where(mask, leaf[src], leaf[dst]))
        out.append(layer)
    return out


def init_pools(cfg, num_blocks: int, block_size: int,
               kv_dtype: str = "fp32", kv_group: int = 32) -> list:
    """Per-layer K/V block pools (zeros), mirroring the per-layer
    ``{"k", "v"}`` pytree shape of models/gpt.init_cache so the engine
    threads them through jit the same way.

    ``kv_dtype`` selects the pool storage format (--serve-kv-dtype):

    - "fp32": blocks in the model compute dtype — byte-for-byte the
      pre-quantization pool (the parity reference);
    - "int8": blocks hold int8 codes, and each layer dict gains sibling
      ``{"k_scale", "v_scale"}`` arrays of shape ``(num_blocks, heads,
      block_size)`` fp32 — one symmetric-absmax scale per (block, head,
      token-slot) row (ops/paged_attention.quantize_kv).  The scale
      arrays share the pool's first two axes, so block-table indexing,
      copy-on-write, and TP head-sharding treat them exactly like the
      code arrays.
    - "int4": blocks hold nibble-packed uint8 codes of shape
      ``(num_blocks, heads, block_size, head_dim // 2)`` — two codes
      per byte (ops/paged_attention.pack_int4) — and the scale siblings
      grow a trailing group axis: ``(num_blocks, heads, block_size,
      head_dim // g)`` fp32 with ``g = min(kv_group, head_dim)`` (the
      --serve-kv-group knob, clamped so the default 32 stays valid on
      tiny heads; ``g`` must divide head_dim).  The 4-d scale rank is
      what the consume paths discriminate int4 on — no new leaf keys,
      so CoW/partial-copy/TP/journal stay dtype-agnostic.
    """
    import jax.numpy as jnp

    if kv_dtype not in ("fp32", "int8", "int4"):
        raise ValueError(
            f"serve kv dtype must be fp32|int8|int4, got {kv_dtype!r}")
    if kv_dtype == "int8":
        z = jnp.zeros((num_blocks, cfg.heads, block_size, cfg.head_dim),
                      jnp.int8)
        s = jnp.zeros((num_blocks, cfg.heads, block_size), jnp.float32)
        return [{"k": z, "v": z, "k_scale": s, "v_scale": s}
                for _ in range(cfg.layers)]
    if kv_dtype == "int4":
        g = min(kv_group, cfg.head_dim)
        if cfg.head_dim % 2 or g < 1 or cfg.head_dim % g:
            raise ValueError(
                f"int4 pool needs even head_dim divisible by the "
                f"effective group min(kv_group, head_dim); got "
                f"head_dim={cfg.head_dim}, kv_group={kv_group}")
        z = jnp.zeros(
            (num_blocks, cfg.heads, block_size, cfg.head_dim // 2),
            jnp.uint8)
        s = jnp.zeros(
            (num_blocks, cfg.heads, block_size, cfg.head_dim // g),
            jnp.float32)
        return [{"k": z, "v": z, "k_scale": s, "v_scale": s}
                for _ in range(cfg.layers)]
    z = jnp.zeros((num_blocks, cfg.heads, block_size, cfg.head_dim),
                  cfg.dtype)
    return [{"k": z, "v": z} for _ in range(cfg.layers)]


class HostBlockStore:
    """Host-RAM tier for demoted KV blocks (--serve-kv-tier host).

    When the prefix cache evicts an unreferenced trie leaf under pool
    pressure, the block's bytes are copied to host memory here instead
    of being lost; a later prompt that walks the same trie path
    PROMOTES the bytes back into a freshly allocated device block
    before its first dispatch (no recompute, no re-prefill).  KVQuant
    (arXiv:2401.18079) frames the cache as the long-context bottleneck;
    tiering is the rung that stops multi-turn sessions from re-paying
    prefill after their prefix ages out of the device pool.

    Keys are full trie TOKEN PATHS (tuple of per-block token tuples,
    root -> leaf), so an entry can only ever be re-admitted for the
    exact token stream that produced it — and because quantization is
    write-granularity independent, the stored bytes equal what a fresh
    prefill of that stream would write (the demote->promote byte-
    identity the tiering tests pin).  Values are per-layer dicts of
    host ``np.ndarray`` leaves, one row of each pool leaf (the block's
    codes + scales), dtype-agnostic.

    Pure host Python with no jax import (the allocator discipline):
    insertion-ordered dict, FIFO drop-oldest beyond ``capacity``
    (None = unbounded — host RAM is the budget), counters for the
    metrics ``tier`` block.
    """

    def __init__(self, capacity: int | None = None):
        if capacity is not None and capacity < 1:
            raise ValueError(
                f"host tier capacity must be >= 1 blocks, got {capacity}")
        self.capacity = capacity
        self._store: Dict[tuple, list] = {}
        self.demotions = 0
        self.promotions = 0
        self.dropped = 0
        self.host_blocks_peak = 0
        self.promote_ms_total = 0.0

    def __len__(self) -> int:
        return len(self._store)

    def __contains__(self, key: tuple) -> bool:
        return key in self._store

    def put(self, key: tuple, leaves: list) -> None:
        """Admit a demoted block's host leaves under its trie path key.
        Re-demotion of the same path overwrites (byte-identical by the
        determinism contract, so this is a no-op in content)."""
        self._store.pop(key, None)
        self._store[key] = leaves
        self.demotions += 1
        if self.capacity is not None and len(self._store) > self.capacity:
            self._store.pop(next(iter(self._store)))
            self.dropped += 1
        self.host_blocks_peak = max(self.host_blocks_peak,
                                    len(self._store))

    def pop(self, key: tuple):
        """Take a block's leaves out for promotion (or None on miss).
        The entry leaves the store — after promotion the trie node
        again owns the canonical copy, on device."""
        leaves = self._store.pop(key, None)
        if leaves is not None:
            self.promotions += 1
        return leaves

    def stats(self) -> dict:
        return {"host_blocks": len(self._store),
                "host_blocks_peak": self.host_blocks_peak,
                "demotions": self.demotions,
                "promotions": self.promotions,
                "dropped": self.dropped,
                "promote_ms_total": self.promote_ms_total}
