"""Continuous-batching serving subsystem (Orca / vLLM lineage).

Six cooperating layers, host-side policy over device-side math:

- ``paged_cache``  — fixed device pool of KV blocks + the refcounted
                     host block allocator; memory scales with LIVE
                     tokens, not ``batch x max_len`` (vs
                     models/gpt.init_cache), and refcounts let one
                     physical block back many sequences.
- ``prefix_cache`` — radix trie over full prompt blocks (RadixAttention
                     lineage): new requests map already-cached prefix
                     blocks instead of recomputing them, with
                     copy-on-write on divergence and LRU eviction of
                     unreferenced entries under pool pressure.
- ``scheduler``    — request queue, admit-on-free-blocks, per-step slot
                     recycling on EOS/budget, eviction under pressure;
                     admission control (feasibility check, bounded
                     queue, deadlines), livelock/starvation guards, and
                     a structured terminal status for every request.
- ``speculative``  — speculative-decoding drafters (Leviathan et al.
                     lineage): an n-gram self-draft and a tiny-model
                     drafter over its own paged pool propose k tokens
                     that the engine verifies in ONE batched forward,
                     accepting the longest argmax-matching prefix —
                     greedy outputs stay token-identical by
                     construction while one KV-streaming pass covers
                     up to k+1 emitted tokens.
- ``engine``       — chunked prefill + single-token decode (or
                     (k+1)-token speculative verify) steps at a small
                     fixed set of bucketed shapes (powers of two),
                     with the block pool donated through every dispatch
                     so steady-state serving updates the cache in place
                     and never recompiles after bucket warmup; graceful
                     SIGTERM drain via train/preemption.PreemptionGuard.
- ``iteration``    — THE shared per-iteration serving body (submit
                     stamping, deadline sweep, latency cadence,
                     eviction discard, journal wiring) both
                     ``engine.run`` and the router's replicas drive —
                     guard/journal/drain semantics live in exactly one
                     place.
- ``recovery``     — host-side replay journal (prompt + generated
                     prefix per request) and the transient-failure
                     supervisor: rebuild pools/engine on device loss and
                     replay live sequences token-identically (greedy
                     decode is deterministic); plus the fleet journal
                     merge/replay helpers the router's failover uses.
- ``tp``           — tensor parallelism for the engine: shard the
                     head-major pool, QKV/O projections, and MLP over a
                     ``tp`` mesh axis via shard_map (one psum per
                     row-parallel output); block tables replicate, so
                     every host-side layer above stays tp-unaware.
- ``loadgen``      — trace-driven load generation: a seeded
                     ``WorkloadSpec`` builds the synthetic request
                     trace (Poisson / bursty MMPP / diurnal /
                     multi-tenant arrivals, heavy-tailed lengths,
                     shared prefixes, per-request SLO deadlines, sticky
                     sessions) — (spec, seed) reproduces the identical
                     trace across runs, A/B arms, and replay.
- ``autoscale``    — advisory replica auto-scaling: a ``ScaleAdvisor``
                     folds the scheduler/router load signals (queue
                     depth, occupancy, shed rate) into per-tick
                     scale-up/down advice under hysteresis + cooldown,
                     recorded in bench detail; with tracing on it
                     consumes the SAME ``TraceBuffer`` step records the
                     trace exports, so advice is explainable from the
                     trace.
- ``tracing``      — host-side request-lifecycle spans (arrive/queued/
                     admitted/prefill chunks/first token/decode/
                     terminal, plus eviction and failover-migration
                     transitions) and a bounded per-step phase timeline
                     (``TraceBuffer``), fleet-merged across replicas
                     and incarnations; exports Chrome trace-event JSON
                     and the bench ``breakdown`` block.  Off = no
                     tracer object, byte-for-byte untraced; on = host
                     clocks only, zero device syncs.
- ``router``       — data-parallel scale-out WITH fleet fault
                     tolerance: N whole engine replicas (each with its
                     own replay journal) behind session-affinity +
                     health-gated least-load placement; a failed
                     replica's live work migrates to survivors by
                     journal-prefix replay (token-identical), a
                     per-replica circuit breaker ejects/probes/readmits
                     on capped exponential backoff, and SIGTERM drains
                     the whole fleet.

The decode math itself lives in models/gpt.CausalLm.forward_paged (the
shared transformer stack) and ops/paged_attention (gather/scatter).
"""

from mpi_tensorflow_tpu.serving.engine import (  # noqa: F401
    PagedDecodeEngine, ServeConfig)
from mpi_tensorflow_tpu.serving.paged_cache import (  # noqa: F401
    BlockAllocator, init_pools)
from mpi_tensorflow_tpu.serving.prefix_cache import (  # noqa: F401
    PrefixCache)
from mpi_tensorflow_tpu.serving.iteration import (  # noqa: F401
    DrainTracker, EngineLoop)
from mpi_tensorflow_tpu.serving.recovery import (  # noqa: F401
    ReplayJournal, fleet_outputs, fleet_replay_requests, fleet_statuses,
    run_with_replay)
from mpi_tensorflow_tpu.serving.router import (  # noqa: F401
    FaultPlan, ReplicaFault, ReplicaRouter)
from mpi_tensorflow_tpu.serving.scheduler import (  # noqa: F401
    Request, RejectedRequest, Scheduler, TERMINAL_STATUSES)
from mpi_tensorflow_tpu.serving.speculative import (  # noqa: F401
    Drafter, DraftModelDrafter, NgramDrafter, make_drafter)
from mpi_tensorflow_tpu.serving.loadgen import (  # noqa: F401
    LENGTH_DISTS, TenantClass, Trace, WORKLOADS, WorkloadSpec,
    build_trace, default_tenants, per_request_rows)
from mpi_tensorflow_tpu.serving.autoscale import (  # noqa: F401
    ScaleAdvisor, ScalePolicy)
from mpi_tensorflow_tpu.serving.tracing import (  # noqa: F401
    EngineTracer, Span, TraceBuffer, merge_spans, write_chrome_trace)
