"""Step-time / throughput measurement harness.

The reference has no timing at all (commented-out ``time.time()`` at
mpipy.py:78), yet the project's north-star metric is images/sec/chip
(BASELINE.json).  Measurement rule from BASELINE.md: evaluation stays OFF the
timed path — the reference's accidental every-step full-test eval
(mpipy.py:86) must not be replicated in what we time.

Asynchronous dispatch: JAX returns before the device finishes, so the timer
blocks on the final output (``block_until_ready``) and amortizes over many
steps.
"""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class StepTimer:
    """Accumulates steady-state step wall time, skipping warmup steps
    (compile + first dispatches)."""
    warmup_steps: int = 2
    _steps: int = 0
    _total: float = 0.0
    _t0: float = 0.0

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, count: int = 1) -> None:
        dt = time.perf_counter() - self._t0
        if self.warmup_steps > 0:
            self.warmup_steps -= count
            return
        self._steps += count
        self._total += dt

    @property
    def steps_timed(self) -> int:
        return self._steps

    @property
    def mean_step_seconds(self) -> float:
        return self._total / self._steps if self._steps else float("nan")

    def images_per_sec(self, batch_size: int) -> float:
        s = self.mean_step_seconds
        return batch_size / s if s == s and s > 0 else float("nan")


def time_step_fn(step_fn, state, make_args, iters: int = 20, warmup: int = 3):
    """Benchmark a train step that donates (and returns) its state.

    ``make_args(i)`` supplies the per-call non-state arguments.  Returns
    ``(mean_seconds_per_step, final_state)``.
    """
    import jax

    for i in range(warmup):
        state, metrics = step_fn(state, *make_args(i))
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for i in range(iters):
        state, metrics = step_fn(state, *make_args(i))
    jax.block_until_ready(state)
    return (time.perf_counter() - t0) / iters, state
