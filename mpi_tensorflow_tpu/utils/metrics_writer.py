"""Structured metrics sink: TensorBoard event files + JSONL fallback.

The reference's only metrics channel is the 50-step stdout trace
(``/root/reference/mpipy.py:88``); utils/logging.py reproduces that format.
This module is the machine-readable counterpart (SURVEY.md §5 metrics row):
scalars stream to a TensorBoard event file when ``tensorboardX`` is
importable, and ALWAYS to ``<dir>/metrics.jsonl`` (one ``{"step": t,
"tag": ..., "value": ...}`` line per scalar) so a zero-dependency consumer
— or this repo's tests — can read the same stream.

Multi-host: only process 0 writes (the scalars passed in are already
globally reduced by the loops); other processes construct a writer that
no-ops, so call sites need no rank guard.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional


class MetricsWriter:
    """Scalar metrics sink; safe no-op when ``log_dir`` is None/empty."""

    def __init__(self, log_dir: Optional[str], *, enabled: bool = True):
        self._dir = log_dir
        self._enabled = bool(log_dir) and enabled
        self._tb = None
        self._jsonl = None
        if not self._enabled:
            return
        os.makedirs(log_dir, exist_ok=True)
        self._jsonl = open(os.path.join(log_dir, "metrics.jsonl"), "a",
                           buffering=1)
        try:
            from tensorboardX import SummaryWriter

            self._tb = SummaryWriter(log_dir)
        except Exception:
            self._tb = None   # JSONL alone is the contract

    @property
    def active(self) -> bool:
        return self._enabled

    def scalar(self, tag: str, value: float, step: int) -> None:
        if not self._enabled:
            return
        v = float(value)
        # NaN/Inf are not JSON; strict consumers (jq, JSON.parse) abort the
        # whole stream on one bad line — encode them as null instead
        jv = v if v == v and abs(v) != float("inf") else None
        self._jsonl.write(json.dumps(
            {"step": int(step), "tag": tag, "value": jv,
             "time": round(time.time(), 3)}) + "\n")
        if self._tb is not None:
            self._tb.add_scalar(tag, v, int(step))

    def scalars(self, values: dict, step: int) -> None:
        for tag, v in values.items():
            self.scalar(tag, v, step)

    def close(self) -> None:
        self._enabled = False   # scalar() after close() is a silent no-op
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def for_process(log_dir: Optional[str], process_index: int) -> MetricsWriter:
    """Writer that is active on process 0 only (scalars are global)."""
    return MetricsWriter(log_dir, enabled=process_index == 0)


#: canonical serving health-counter keys — THE shape of the ``faults``
#: block every consumer sees (engine result dicts, the recovery
#: supervisor's merged totals, bench.py --mode serving JSON).  One
#: definition so a dashboard keyed on these names never drifts from the
#: engine's accounting.
SERVING_FAULT_KEYS = ("rejected", "shed", "deadline_exceeded",
                      "evicted_too_often", "drained", "evictions",
                      "replays")


def faults_block(counters) -> dict:
    """Normalize a scheduler/supervisor counter mapping into the
    canonical serving ``faults`` block: every key present (0 when the
    counter never fired), values plain ints."""
    return {k: int(counters.get(k, 0)) for k in SERVING_FAULT_KEYS}


#: canonical fleet-level fault-tolerance counters (serving/router) —
#: THE shape of the ``fleet_faults`` block every consumer sees (router
#: result dicts, bench.py --serve-replicas JSON).  failovers = replica
#: faults handled; migrated_requests = live/queued requests re-homed to
#: survivors; replay_tokens = prompt+prefix tokens re-ingested through
#: chunked prefill to reconstruct migrated streams; ejections /
#: readmissions = circuit-breaker transitions; sticky_rehomed /
#: sticky_evicted = session-affinity map hygiene.
FLEET_FAULT_KEYS = ("failovers", "migrated_requests", "replay_tokens",
                    "ejections", "readmissions", "sticky_rehomed",
                    "sticky_evicted")


def fleet_faults_block(counters) -> dict:
    """Normalize a router counter mapping into the canonical
    ``fleet_faults`` block: every key present (0 when the counter never
    fired), values plain ints — same discipline as ``faults_block``."""
    return {k: int(counters.get(k, 0)) for k in FLEET_FAULT_KEYS}


def prefix_block(counters, *, enabled: bool, trie_blocks: int = 0,
                 router_prefix_hits: int = 0) -> dict:
    """Normalize scheduler/supervisor counters into the canonical
    serving ``prefix`` (radix prefix cache) accounting block — one
    constructor shared by engine results, the recovery supervisor's
    cross-attempt merge, router aggregation, and bench JSON, so the key
    set and the hit-rate rounding can never drift between them.

    ``hit_rate`` counts FULL-BLOCK sharing only; partial tail-block
    rows ride separately as ``partial_copy_tokens``, and
    ``prefill_tokens_saved`` is the prefix-v2 headline — every prompt
    position served out of cache (full blocks + partial rows) instead
    of recomputed."""
    hit = int(counters.get("prefix_hit_tokens", 0))
    total = int(counters.get("prefix_prompt_tokens", 0))
    partial = int(counters.get("prefix_partial_copy_tokens", 0))
    return {
        "enabled": bool(enabled),
        "hit_tokens": hit,
        "prompt_tokens": total,
        "hit_rate": round(hit / total, 4) if total else 0.0,
        "shared_blocks": int(counters.get("prefix_shared_blocks", 0)),
        "cow_copies": int(counters.get("prefix_cow_copies", 0)),
        "trie_evictions": int(counters.get("prefix_trie_evictions", 0)),
        "trie_blocks": int(trie_blocks),
        # block-starved admissions served out of FIFO order because a
        # cached prefix made them fit (the scheduler's hit-aware
        # admission policy); 0 when the pool never came under pressure
        "hit_admissions": int(counters.get("prefix_hit_admissions", 0)),
        # prefix v2 (--serve-prefix-gen): trie nodes adopted from
        # GENERATED output at request completion, and tail rows served
        # through the partial-copy dispatch instead of re-prefill
        "gen_inserted_blocks":
            int(counters.get("prefix_gen_inserted_blocks", 0)),
        "partial_copy_tokens": partial,
        "prefill_tokens_saved": hit + partial,
        # prefix v2 (--serve-prefix-route): fleet placements the
        # router's prefix hint decided (always 0 for a single engine)
        "router_prefix_hits": int(router_prefix_hits),
    }


def speculation_block(counters, *, enabled: bool, mode: str = "off",
                      draft_k: int = 0, draft_auto: str = "off") -> dict:
    """Normalize scheduler/supervisor counters into the canonical
    serving ``speculation`` (speculative decoding) accounting block —
    one constructor shared by engine results, the recovery
    supervisor's cross-attempt merge, and bench JSON.

    ``steps_saved`` is the bandwidth proxy the feature exists for:
    tokens emitted through the verify path minus verify forwards run —
    i.e. how many full KV-streaming decode passes speculation avoided
    (0 when nothing was ever accepted; vanilla decode is one forward
    per token by definition)."""
    drafted = int(counters.get("spec_drafted", 0))
    accepted = int(counters.get("spec_accepted", 0))
    forwards = int(counters.get("spec_verify_forwards", 0))
    emitted = int(counters.get("spec_emitted", 0))
    k_sum = int(counters.get("spec_k_sum", 0))
    k_steps = int(counters.get("spec_k_steps", 0))
    return {
        "enabled": bool(enabled),
        "mode": mode,
        "draft_k": int(draft_k),
        # the window the policy actually offered, averaged over verify
        # steps: == draft_k with auto-tuning off; under --serve-draft-auto
        # on this is THE number the knob exists to report
        "draft_auto": draft_auto,
        "effective_k": (round(k_sum / k_steps, 2) if k_steps
                        else int(draft_k)),
        "draft_tokens": drafted,
        "accepted_tokens": accepted,
        "accept_rate": round(accepted / drafted, 4) if drafted else 0.0,
        "verify_forwards": forwards,
        "emitted_tokens": emitted,
        "mean_accepted_len": (round(accepted / forwards, 4)
                              if forwards else 0.0),
        "steps_saved": emitted - forwards,
    }


def kv_quant_block(*, kv_dtype: str = "fp32", matched_tokens: int = 0,
                   compared_tokens: int = 0, block_bytes_ref: int = 0,
                   block_bytes: int = 0, num_blocks: int = 0,
                   peak_live_blocks_ref: int = 0,
                   peak_live_blocks: int = 0,
                   bytes_per_decode_token_ref: float = 0.0,
                   bytes_per_decode_token: float = 0.0) -> dict:
    """Normalize KV-quantization A/B numbers into the canonical serving
    ``kv_quant`` block (bench --serve-kv-ab JSON) — same discipline as
    the blocks above: every key present, plain types, rounding here.

    ``*_ref`` is the fp32 (unquantized) arm.  ``token_match_rate`` is
    positionwise greedy-token agreement between the arms over the whole
    trace (aligned positions; length mismatches count as mismatches) —
    the quality gate quantization must clear.  ``capacity_multiplier``
    / ``effective_capacity_blocks`` answer the question the feature
    exists for: how many pool blocks the SAME HBM budget holds at the
    quantized bytes-per-block (codes + scale siblings).
    ``peak_live_blocks_delta`` pins the arms' block-accounting
    equivalence (same trace => same block walk => 0), and the
    bytes-per-decode-token pair is the decode bandwidth roofline at the
    quantized element width (1 byte/elem for int8, plus scale
    traffic)."""
    return {
        "enabled": True,
        "kv_dtype": kv_dtype,
        "matched_tokens": int(matched_tokens),
        "compared_tokens": int(compared_tokens),
        "token_match_rate": (round(matched_tokens / compared_tokens, 4)
                             if compared_tokens else 0.0),
        "block_bytes_ref": int(block_bytes_ref),
        "block_bytes": int(block_bytes),
        "capacity_multiplier": (round(block_bytes_ref / block_bytes, 4)
                                if block_bytes else 0.0),
        "effective_capacity_blocks": (
            int(num_blocks * block_bytes_ref // block_bytes)
            if block_bytes else 0),
        "num_blocks": int(num_blocks),
        "peak_live_blocks_ref": int(peak_live_blocks_ref),
        "peak_live_blocks": int(peak_live_blocks),
        "peak_live_blocks_delta": int(peak_live_blocks
                                      - peak_live_blocks_ref),
        "bytes_per_decode_token_ref": round(
            float(bytes_per_decode_token_ref), 2),
        "bytes_per_decode_token": round(float(bytes_per_decode_token), 2),
    }


#: canonical host-tier keys — THE shape of the ``tier`` block every
#: consumer sees (engine results, bench --mode serving JSON).  Tiering
#: (--serve-kv-tier host) demotes cold prefix-cache blocks to host RAM
#: on eviction and promotes them back on a later trie match;
#: prefill_tokens_saved_tier = promotions * block_size is the prefill
#: work those re-admissions avoided re-paying.
TIER_KEYS = ("enabled", "mode", "demotions", "promotions",
             "host_blocks", "host_blocks_peak",
             "promote_latency_ms_total", "promote_latency_ms_mean",
             "prefill_tokens_saved_tier")


def tier_block(*, enabled: bool = False, mode: str = "off",
               demotions: int = 0, promotions: int = 0,
               host_blocks: int = 0, host_blocks_peak: int = 0,
               promote_ms_total: float = 0.0,
               block_size: int = 0) -> dict:
    """Normalize host-tier counters into the canonical serving ``tier``
    block — same discipline as the blocks above: every TIER_KEYS key
    present, plain types, derived rates computed (zero-safely) here."""
    return {
        "enabled": bool(enabled),
        "mode": mode,
        "demotions": int(demotions),
        "promotions": int(promotions),
        "host_blocks": int(host_blocks),
        "host_blocks_peak": int(host_blocks_peak),
        "promote_latency_ms_total": round(float(promote_ms_total), 3),
        "promote_latency_ms_mean": (
            round(float(promote_ms_total) / promotions, 3)
            if promotions else 0.0),
        "prefill_tokens_saved_tier": int(promotions * block_size),
    }


#: canonical goodput-under-SLO keys — THE shape of the ``goodput``
#: block every consumer sees (bench.py --mode serving JSON, the metric
#: line's goodput_tokens_per_sec / slo_attainment fields).  Goodput =
#: tokens (and requests) per second from requests that completed within
#: their latency budget (DistServe, arXiv:2401.09670) — the serving
#: number raw tokens/sec over-reports under load.
GOODPUT_KEYS = ("enabled", "requests", "ok_requests",
                "slo_met_requests", "slo_attainment",
                "goodput_tokens_per_sec", "goodput_requests_per_sec",
                "p50_attained_ms", "p99_attained_ms",
                "ttft_p50_ms", "ttft_p99_ms", "per_tenant")


def _percentile(vals, q: float) -> float:
    """Linear-interpolation percentile over a small sample (no numpy:
    this module stays importable by zero-dependency consumers)."""
    if not vals:
        return 0.0
    s = sorted(vals)
    k = (len(s) - 1) * q
    f = int(k)
    c = min(f + 1, len(s) - 1)
    return s[f] + (s[c] - s[f]) * (k - f)


def goodput_block(rows, *, elapsed_s: float, enabled=None) -> dict:
    """Aggregate per-request rows (serving/loadgen.per_request_rows:
    ``tenant`` / ``status`` / ``tokens`` / ``attained_ms`` / ``slo_ms``
    each) into the canonical ``goodput`` block, with a per-tenant
    breakdown keyed by tenant class.

    A row MEETS its SLO when it finished ``ok`` within ``slo_ms``
    (None = no budget, so every ``ok`` completion counts — goodput
    degenerates to raw delivered throughput).  Attained-latency
    percentiles cover completed requests only: an unfinished request
    has no whole-request latency, and its miss is already counted by
    ``slo_attainment``.  ``ttft_p50_ms``/``ttft_p99_ms`` cover every
    row carrying a ``ttft_ms`` stamp (any request that streamed at
    least one token) — time-to-first-token is the queueing + prefill
    latency mixed batching targets, visible even for requests that
    later failed their deadline."""
    rows = list(rows)
    if enabled is None:
        enabled = any(r.get("slo_ms") is not None for r in rows)

    def agg(sub: list) -> dict:
        ok = [r for r in sub if r.get("status") == "ok"]
        met = [r for r in ok
               if r.get("slo_ms") is None
               or (r.get("attained_ms") is not None
                   and r["attained_ms"] <= r["slo_ms"])]
        att = [r["attained_ms"] for r in ok
               if r.get("attained_ms") is not None]
        ttft = [r["ttft_ms"] for r in sub
                if r.get("ttft_ms") is not None]
        toks = sum(int(r.get("tokens", 0)) for r in met)
        return {
            "requests": len(sub),
            "ok_requests": len(ok),
            "slo_met_requests": len(met),
            "slo_attainment": (round(len(met) / len(sub), 4)
                               if sub else 0.0),
            "goodput_tokens_per_sec": (round(toks / elapsed_s, 2)
                                       if elapsed_s > 0 else 0.0),
            "goodput_requests_per_sec": (round(len(met) / elapsed_s, 4)
                                         if elapsed_s > 0 else 0.0),
            "p50_attained_ms": round(_percentile(att, 0.5), 2),
            "p99_attained_ms": round(_percentile(att, 0.99), 2),
            "ttft_p50_ms": round(_percentile(ttft, 0.5), 2),
            "ttft_p99_ms": round(_percentile(ttft, 0.99), 2),
        }

    tenants = sorted({r.get("tenant", "default") for r in rows})
    block = agg(rows)
    block["enabled"] = bool(enabled)
    block["per_tenant"] = {
        t: agg([r for r in rows if r.get("tenant", "default") == t])
        for t in tenants}
    return block


#: canonical phase-attribution keys — THE shape of the ``breakdown``
#: block bench detail carries with --serve-trace on (serving/tracing
#: spans).  queue/prefill/decode percentiles are recomputed FROM SPANS
#: (not from the engine's scalar stamps); the two ``*_max_delta_ms``
#: keys are the cross-checks that pin the span clock to the stamped
#: clock: phase times sum to the attained whole-request latency, and
#: span TTFT equals the stamped first-token time.
BREAKDOWN_KEYS = ("enabled", "requests", "queue_ms_p50", "queue_ms_p99",
                  "prefill_ms_p50", "prefill_ms_p99", "decode_ms_p50",
                  "decode_ms_p99", "ttft_ms_p50", "ttft_ms_p99",
                  "phase_sum_vs_attained_max_delta_ms",
                  "ttft_vs_stamp_max_delta_ms", "steps", "steps_dropped")


def breakdown_block(trace, *, enabled=None, stamped_first_s=None) -> dict:
    """Aggregate a serving ``trace`` result block (engine/router
    ``res["trace"]``: fleet-merged spans + step-ring accounting) into
    the canonical ``breakdown`` block — per-phase latency percentiles
    over requests that finished ``ok``, with the span-vs-stamp
    consistency deltas.

    ``stamped_first_s`` is the run's ``request_first_token_s`` map;
    when given, ``ttft_vs_stamp_max_delta_ms`` reports the worst
    disagreement between a span's first-token stamp and the loop's —
    the loop stamps both from the same post-step clock read, so this
    should be ~0 and a drift means an instrumentation bug.  Keys are
    always exactly ``BREAKDOWN_KEYS`` (zeros when disabled/empty)."""
    if enabled is None:
        enabled = bool(trace) and bool(trace.get("enabled"))
    out = {k: 0.0 for k in BREAKDOWN_KEYS}
    out["enabled"] = bool(enabled)
    out["requests"] = 0
    out["steps"] = 0
    out["steps_dropped"] = 0
    if not enabled or not trace:
        return out
    spans = trace.get("spans", {})
    ok = [d for d in spans.values() if d.get("status") == "ok"]
    queue = [d["queue_s"] * 1e3 for d in ok]
    prefill = [d["prefill_s"] * 1e3 for d in ok]
    decode = [d["decode_s"] * 1e3 for d in ok]
    ttft = [(d["first_token"] - d["arrive"]) * 1e3 for d in ok
            if d.get("first_token") is not None]
    phase_delta = [abs((d["queue_s"] + d["prefill_s"] + d["decode_s"])
                       - (d["terminal"] - d["arrive"])) * 1e3
                   for d in ok if d.get("terminal") is not None
                   # a migrated span's attained latency includes the
                   # inter-incarnation replay gap its phase clocks
                   # deliberately exclude — the sum contract holds per
                   # incarnation, so check single-incarnation spans
                   if d.get("incarnations", 1) == 1 and not d["replays"]]
    stamp_delta = [abs(d["first_token"] - stamped_first_s[d["rid"]]) * 1e3
                   for d in ok
                   if stamped_first_s is not None
                   and d.get("first_token") is not None
                   and d["rid"] in stamped_first_s]
    out.update({
        "requests": len(ok),
        "queue_ms_p50": round(_percentile(queue, 0.5), 3),
        "queue_ms_p99": round(_percentile(queue, 0.99), 3),
        "prefill_ms_p50": round(_percentile(prefill, 0.5), 3),
        "prefill_ms_p99": round(_percentile(prefill, 0.99), 3),
        "decode_ms_p50": round(_percentile(decode, 0.5), 3),
        "decode_ms_p99": round(_percentile(decode, 0.99), 3),
        "ttft_ms_p50": round(_percentile(ttft, 0.5), 3),
        "ttft_ms_p99": round(_percentile(ttft, 0.99), 3),
        "phase_sum_vs_attained_max_delta_ms": round(
            max(phase_delta), 3) if phase_delta else 0.0,
        "ttft_vs_stamp_max_delta_ms": round(
            max(stamp_delta), 3) if stamp_delta else 0.0,
        "steps": int(trace.get("steps", 0)),
        "steps_dropped": int(trace.get("steps_dropped", 0)),
    })
    return out


def write_faults(writer: MetricsWriter, counters, step: int = 0,
                 prefix: str = "serving/faults/") -> dict:
    """Stream the normalized faults block through a MetricsWriter (one
    scalar per counter, ``serving/faults/<key>``) and return it — the
    emission path for a serve loop with a ``--metrics-dir``-style sink;
    it normalizes through ``faults_block`` so the scalar stream and a
    printed JSON block built from the same counters cannot disagree."""
    block = faults_block(counters)
    writer.scalars({prefix + k: v for k, v in block.items()}, step)
    return block
