"""Tracing / profiling subsystem.

The reference has none — its only instrumentation is a commented-out
wall-clock timer (mpipy.py:78) and the 50-step print trace (SURVEY.md §5
tracing row).  Here profiling is a first-class utility:

- ``trace(dir)``: context manager around ``jax.profiler`` — produces an
  XPlane/TensorBoard trace of device + host activity;
- ``annotate(name)``: names a region so it shows up in the trace timeline
  (host side) and, via ``jax.named_scope``, in the compiled HLO;
- ``device_memory_stats()``: per-device HBM usage snapshot, for finding the
  working-set the rematerialization knobs should target.

Wired into the CLI as ``--profile-dir`` (cli.py).
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Label a region in both the profiler timeline and the jaxpr/HLO."""
    import jax

    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield


def device_memory_stats() -> list:
    """Per-device memory snapshot: ``[{device, bytes_in_use, peak_bytes,
    limit_bytes}, ...]``.  Platforms without stats report ``None`` fields."""
    import jax

    out = []
    for d in jax.devices():
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:  # not all platforms implement memory_stats
            pass
        out.append({
            "device": str(d),
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes": stats.get("peak_bytes_in_use"),
            "limit_bytes": stats.get("bytes_limit"),
        })
    return out
