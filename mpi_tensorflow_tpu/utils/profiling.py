"""Profiling + host-side step timing — the repo's ONE timing idiom.

The reference has none — its only instrumentation is a commented-out
wall-clock timer (mpipy.py:78) and the 50-step print trace (SURVEY.md §5
tracing row).  Here measurement is a first-class utility:

- ``trace(dir)``: context manager around ``jax.profiler`` — produces an
  XPlane/TensorBoard trace of device + host activity;
- ``annotate(name)``: names a region so it shows up in the trace timeline
  (host side) and, via ``jax.named_scope``, in the compiled HLO;
- ``device_memory_stats()``: per-device HBM usage snapshot, for finding the
  working-set the rematerialization knobs should target;
- ``StepTimer`` / ``time_step_fn``: warmup-skipping wall-clock step
  timers for the TRAIN loops and bench — JAX dispatch is asynchronous,
  so both block on the final output (``block_until_ready``) and
  amortize over many steps.  Measurement rule from BASELINE.md:
  evaluation stays OFF the timed path (the reference's accidental
  every-step full-test eval at mpipy.py:86 is not replicated in what
  we time).

The SERVING side has its own timing layer — ``serving/tracing``
stamps request-lifecycle spans and per-step phase durations on the
serve loop's existing host clocks (it must never block on device
output the way ``time_step_fn`` deliberately does).  Train/bench time
here; serving traces there; nothing else reads a clock.

Wired into the CLI as ``--profile-dir`` (cli.py).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Label a region in both the profiler timeline and the jaxpr/HLO."""
    import jax

    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield


@dataclasses.dataclass
class StepTimer:
    """Accumulates steady-state step wall time, skipping warmup steps
    (compile + first dispatches)."""
    warmup_steps: int = 2
    _steps: int = 0
    _total: float = 0.0
    _t0: float = 0.0

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, count: int = 1) -> None:
        dt = time.perf_counter() - self._t0
        if self.warmup_steps > 0:
            self.warmup_steps -= count
            return
        self._steps += count
        self._total += dt

    @property
    def steps_timed(self) -> int:
        return self._steps

    @property
    def mean_step_seconds(self) -> float:
        return self._total / self._steps if self._steps else float("nan")

    def images_per_sec(self, batch_size: int) -> float:
        s = self.mean_step_seconds
        return batch_size / s if s == s and s > 0 else float("nan")


def time_step_fn(step_fn, state, make_args, iters: int = 20, warmup: int = 3):
    """Benchmark a train step that donates (and returns) its state.

    ``make_args(i)`` supplies the per-call non-state arguments.  Returns
    ``(mean_seconds_per_step, final_state)``.
    """
    import jax

    for i in range(warmup):
        state, metrics = step_fn(state, *make_args(i))
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for i in range(iters):
        state, metrics = step_fn(state, *make_args(i))
    jax.block_until_ready(state)
    return (time.perf_counter() - t0) / iters, state


def device_memory_stats() -> list:
    """Per-device memory snapshot: ``[{device, bytes_in_use, peak_bytes,
    limit_bytes}, ...]``.  Platforms without stats report ``None`` fields."""
    import jax

    out = []
    for d in jax.devices():
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:  # not all platforms implement memory_stats
            pass
        out.append({
            "device": str(d),
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes": stats.get("peak_bytes_in_use"),
            "limit_bytes": stats.get("bytes_limit"),
        })
    return out
