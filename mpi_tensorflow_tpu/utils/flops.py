"""Analytic per-step FLOP accounting for the benchmark families.

Used by bench.py to report model FLOPS utilization (MFU) next to every
throughput number — raw flops are recorded too, so any peak can re-derive
the percentage.  Analytic (not compiler-reported) on purpose: a second
``lower().compile()`` on the tunneled device costs minutes, and XLA's
cost model counts implementation flops (rematerialization, fused
epilogues), while MFU is defined against MODEL flops — the work the math
requires, not the work the compiler chose to do.

Formulas (standard accounting, e.g. the PaLM appendix convention):
- a dense matmul with N parameters costs ``2·N`` flops per token forward,
  ``6·N`` forward+backward (backward does two matmuls per forward one);
- attention scores + weighted values cost ``4·B·S²·E`` forward per layer
  (2 for QKᵀ, 2 for AV), ``12·B·S²·E`` with backward;
- the embedding gather is free; the TIED vocab decoder is a real matmul
  and is counted at the positions that reach the head (the packed
  capacity for the MLM families, every position for the causal family).
"""

from __future__ import annotations

# TPU v5e (the measurement chip): 197 TFLOP/s bf16 peak per chip.
PEAK_TFLOPS = {"bf16": 197.0, "fp32": 49.0}

# v5e HBM bandwidth, GB/s — the roofline for bandwidth-bound paths
# (autoregressive decode reads every live parameter once per token-step,
# so tokens/sec is bounded by batch * HBM_GBPS / param_bytes).
HBM_GBPS = 819.0

# fwd-only GFLOPs per image at the bench input geometry (canonical
# published MACs x 2).  fwd+bwd = 3x.
_IMAGE_FWD_GFLOPS = {
    "resnet50": 8.2,      # 4.09 GMAC @ 224x224
    "resnet20": 0.082,    # 41 MMAC @ 32x32
    "mnist_cnn": 0.024,   # 2 convs + fc on 28x28 (computed from geometry)
}


def transformer_train_flops(cfg, batch: int, seq_len: int,
                            head_positions: int | None = None) -> float:
    """Model flops for ONE fwd+bwd train step of the shared transformer
    stack (models/bert.py geometry).  ``head_positions``: tokens reaching
    the MLM head per sequence (packed capacity; default = the model's
    ce_capacity rule for the MLM families, S for causal)."""
    E, L, M, V = cfg.hidden, cfg.layers, cfg.mlp, cfg.vocab_size
    B, S = batch, seq_len
    # per-layer matmul params: QKV + out proj (4·E²) + MLP (2·E·M)
    layer_mm = 4 * E * E + 2 * E * M
    enc = 6 * B * S * L * layer_mm          # encoder matmuls, fwd+bwd
    attn = 12 * L * B * S * S * E           # scores + AV, fwd+bwd
    if head_positions is None:
        if getattr(cfg, "ce_positions", "all") == "masked":
            from mpi_tensorflow_tpu.models.bert import ce_capacity

            head_positions = ce_capacity(cfg, S)
        else:
            head_positions = S
    P = B * head_positions
    head = 6 * P * (E * E + V * E)          # transform + tied decoder
    return float(enc + attn + head)


def encdec_train_flops(cfg, n_dec: int, batch: int, src_len: int,
                       tgt_len: int) -> float:
    """One fwd+bwd step of the encoder-decoder family (models/encdec.py):
    the shared encoder accounting (head zeroed) + decoder layers
    (self-attn QKV/out and MLP at T; cross q/out at T; cross k/v at S)
    + causal self-attention (T²), cross-attention (T·S), and the tied
    vocab head over every target position."""
    E, M, V = cfg.hidden, cfg.mlp, cfg.vocab_size
    B, S, T = batch, src_len, tgt_len
    enc = transformer_train_flops(cfg, B, S, head_positions=0)
    dec_mm = 6 * n_dec * (B * T * (6 * E * E + 2 * E * M)
                          + B * S * 2 * E * E)
    attn = 12 * n_dec * B * E * (T * T + T * S)
    head = 6 * B * T * V * E
    return float(enc + dec_mm + attn + head)


def vit_train_flops(vcfg, batch: int) -> float:
    """One fwd+bwd step of the ViT family (models/vit.py): the SHARED
    encoder-layer accounting (transformer_train_flops with the vocab
    head zeroed — ViT drives the same layers, so the same coefficients)
    at sequence N = patches + CLS, plus the patch projection; the
    classification head is negligible."""
    from types import SimpleNamespace

    N = vcfg.num_patches + 1
    body = transformer_train_flops(
        SimpleNamespace(hidden=vcfg.hidden, layers=vcfg.layers,
                        mlp=vcfg.mlp, vocab_size=0),
        batch, N, head_positions=0)
    patch = 6 * batch * vcfg.num_patches \
        * (vcfg.patch ** 2 * vcfg.channels) * vcfg.hidden
    return float(body + patch)


def image_train_flops(model_name: str, batch: int) -> float | None:
    """Model flops for one fwd+bwd step of an image family, or None when
    the model has no canonical number recorded."""
    g = _IMAGE_FWD_GFLOPS.get(model_name)
    if g is None:
        return None
    return 3.0 * g * 1e9 * batch


def mfu_pct(flops_per_step: float | None, step_seconds: float,
            precision: str, platform: str = "tpu") -> float | None:
    """Achieved model-flops rate as % of the chip's peak for ``precision``
    ("bf16" | "fp32").  None when flops or peak are unknown — including
    any ``platform`` other than "tpu": the peak table is the v5e
    measurement chip's, and reporting a confident percentage against it
    from a CPU run would be exactly the quietly-wrong claim this module
    exists to prevent."""
    peak = PEAK_TFLOPS.get(precision)
    if platform != "tpu" or not flops_per_step or not peak \
            or step_seconds <= 0:
        return None
    return 100.0 * flops_per_step / step_seconds / (peak * 1e12)
