"""Host-scoped persistent-compilation-cache paths.

XLA:CPU stores AOT-compiled executables keyed WITHOUT the full host
machine-feature set; loading an entry compiled on a different CPU type
warns "This could lead to execution errors such as SIGILL" — and does
exactly that, intermittently, when a cached executable using unsupported
instructions runs (observed twice as a mid-suite "Fatal Python error"
on the round-3 box, whose cache had accumulated entries from earlier
rounds' hosts).  Scoping the CPU cache by a fingerprint of the host's
instruction set makes a foreign entry unreachable instead of fatal.
TPU entries are unaffected (device executables, loaded by the runtime,
not host-executed) and keep using the base directory.
"""

from __future__ import annotations

import hashlib
import os
import platform


def host_scoped_cpu_cache(base: str) -> str:
    """``base``/cpu-<isa fingerprint> — stable per machine type, and
    idempotent (an already-scoped path is returned unchanged, so every
    forced-CPU entry point can apply it unconditionally)."""
    try:
        with open("/proc/cpuinfo") as f:
            text = f.read()
        # x86 lists ISA extensions under "flags", aarch64 under
        # "Features".  The flags alone are NOT enough: LLVM's
        # -mcpu=native tuning attributes (+prefer-no-gather/-scatter,
        # set per CPU MODEL from CPUID family/model) vary between hosts
        # whose visible flag sets are identical — observed round 4 as a
        # cached AOT entry compiled with +prefer-no-gather crashing the
        # suite ("Fatal Python error") on a same-flags host without it.
        # So the fingerprint includes the model-identity lines too.
        # If none are present, fingerprint the whole file — a constant
        # fallback would let foreign AOT entries stay reachable, the
        # exact hazard this module exists to close.
        keys = ("flags", "Features", "model name", "model", "cpu family",
                "stepping", "vendor_id", "CPU implementer", "CPU part",
                "CPU variant")
        seen = {}
        for ln in text.splitlines():
            k = ln.split(":", 1)[0].strip()
            if k in keys and k not in seen:
                seen[k] = ln.strip()
        flags = "\n".join(seen[k] for k in keys if k in seen) or text
    except OSError:
        flags = platform.processor() or platform.machine()
    tag = hashlib.sha1(flags.encode()).hexdigest()[:12]
    if os.path.basename(os.path.normpath(base)) == f"cpu-{tag}":
        return base                      # already scoped
    path = os.path.join(base, f"cpu-{tag}")
    os.makedirs(path, exist_ok=True)
    return path
