"""Host-scoped persistent-compilation-cache paths + a round-trip safety
canary.

XLA:CPU stores AOT-compiled executables keyed WITHOUT the full host
machine-feature set; loading an entry compiled on a different CPU type
warns "This could lead to execution errors such as SIGILL" — and does
exactly that, intermittently (observed as a mid-suite "Fatal Python
error" in rounds 3 and 4).  Two distinct hazards, both closed here:

1. FOREIGN entries (different box, same cache dir): closed by scoping
   the CPU cache under a fingerprint of the host's ISA *and model
   identity* (LLVM's -mcpu=native tuning differs between models whose
   /proc/cpuinfo flags are identical).
2. SAME-HOST reload (round-4 root cause): on some boxes LLVM's native
   tuning adds attributes (+prefer-no-gather/-scatter) that the AOT
   loader cannot verify against its host-feature probe, so the box
   cannot round-trip ITS OWN cache — every load warns "Machine type
   used for XLA:CPU compilation doesn't match", and a gather-heavy
   executable (the gspmd train step) aborted deterministically on
   reload.  ``cpu_cache_roundtrip_safe`` detects this once per box
   with a compile-in-one-process / reload-in-another canary and
   persists the verdict; callers must leave the CPU cache OFF when it
   returns False.

TPU entries are unaffected (device executables, loaded by the runtime,
not host-executed) and keep using the base directory.
"""

from __future__ import annotations

import hashlib
import os
import platform
import sys


def host_scoped_cpu_cache(base: str) -> str:
    """``base``/cpu-<isa fingerprint> — stable per machine type, and
    idempotent (an already-scoped path is returned unchanged, so every
    forced-CPU entry point can apply it unconditionally)."""
    try:
        with open("/proc/cpuinfo") as f:
            text = f.read()
        # x86 lists ISA extensions under "flags", aarch64 under
        # "Features".  The flags alone are NOT enough: LLVM's
        # -mcpu=native tuning attributes (+prefer-no-gather/-scatter,
        # set per CPU MODEL from CPUID family/model) vary between hosts
        # whose visible flag sets are identical — observed round 4 as a
        # cached AOT entry compiled with +prefer-no-gather crashing the
        # suite ("Fatal Python error") on a same-flags host without it.
        # So the fingerprint includes the model-identity lines too.
        # If none are present, fingerprint the whole file — a constant
        # fallback would let foreign AOT entries stay reachable, the
        # exact hazard this module exists to close.
        keys = ("flags", "Features", "model name", "model", "cpu family",
                "stepping", "vendor_id", "CPU implementer", "CPU part",
                "CPU variant")
        seen = {}
        for ln in text.splitlines():
            k = ln.split(":", 1)[0].strip()
            if k in keys and k not in seen:
                seen[k] = ln.strip()
        flags = "\n".join(seen[k] for k in keys if k in seen) or text
    except OSError:
        flags = platform.processor() or platform.machine()
    tag = hashlib.sha1(flags.encode()).hexdigest()[:12]
    if os.path.basename(os.path.normpath(base)) == f"cpu-{tag}":
        return base                      # already scoped
    path = os.path.join(base, f"cpu-{tag}")
    os.makedirs(path, exist_ok=True)
    return path


_CANARY = r"""
import os, sys
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", sys.argv[1])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

@jax.jit
def canary(x, idx):
    # a gather: the op class whose codegen the unverifiable
    # prefer-no-gather tuning attribute changes
    return jnp.take(x, idx, axis=0).sum() * 2.0

out = canary(jnp.arange(64.0).reshape(8, 8), jnp.array([1, 3, 5]))
print("CANARY_OK", float(out))
"""


_ROUNDTRIP_MEMO: dict = {}   # (isa tag, jaxlib ver) -> bool, per process


def _jaxlib_version() -> str:
    try:
        from importlib.metadata import version

        return version("jaxlib")
    except Exception:
        return "unknown"


def _persistent_probe(memo: dict, memo_key, verdict_path: str,
                      valid_verdicts, probe_fn):
    """THE shared probe-once contract for subprocess canaries: memoized
    per process (``memo[memo_key]`` holds the finished verdict string or
    None), persisted across processes at ``verdict_path``.  Invariants
    every caller gets from this one copy:

    - a persisted verdict in ``valid_verdicts`` short-circuits; a
      torn/garbage file (reader raced a non-atomic writer from an older
      version) falls through to a re-probe;
    - only a COMPLETED probe (``probe_fn`` returns a verdict string)
      publishes — an infrastructure failure (returns None) reports for
      this session only, so the next session retries;
    - publish is atomic (tmp + os.replace): a racing reader sees the old
      state or the full verdict, never a torn file.
    """
    if memo_key in memo:
        return memo[memo_key]
    if os.path.exists(verdict_path):
        try:
            with open(verdict_path) as f:
                content = f.read().strip()
        except OSError:
            content = ""
        if content in valid_verdicts:
            memo[memo_key] = content
            return content
    verdict = probe_fn()
    if verdict is not None:
        tmp = f"{verdict_path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(verdict)
            os.replace(tmp, verdict_path)
        except OSError:
            try:
                os.unlink(tmp)       # no stray tmp on ENOSPC/races
            except OSError:
                pass
    memo[memo_key] = verdict
    return verdict


def cpu_cache_roundtrip_safe(scoped_dir: str, timeout: int = 180) -> bool:
    """True when this box can reload its OWN XLA:CPU AOT cache entries.

    Compiles a small gather-containing jit in one subprocess (writing the
    entry into a throwaway dir), reloads it in a second, and checks the
    second's stderr for the AOT loader's machine-type mismatch warning —
    the signature of the same-host tuning-attribute hazard that aborted
    the round-4 suite.  The verdict persists next to the scoped dir,
    keyed by the jaxlib version (a loader upgrade re-probes), and is
    memoized per (ISA tag, version) in-process so multiple cache bases
    in one session pay ONE probe; canary-infrastructure failures report
    False without persisting (_persistent_probe contract)."""
    tag = os.path.basename(os.path.normpath(scoped_dir))
    ver = _jaxlib_version()

    def probe():
        import subprocess
        import tempfile

        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the tunnel
        env["JAX_PLATFORMS"] = "cpu"
        cache = tempfile.mkdtemp(prefix="canary-", dir=os.path.dirname(
            os.path.normpath(scoped_dir)) or ".")
        try:
            r1 = subprocess.run([sys.executable, "-c", _CANARY, cache],
                                capture_output=True, text=True, env=env,
                                timeout=timeout)
            if r1.returncode == 0 and "CANARY_OK" in r1.stdout:
                r2 = subprocess.run([sys.executable, "-c", _CANARY, cache],
                                    capture_output=True, text=True,
                                    env=env, timeout=timeout)
                if r2.returncode == 0 and "CANARY_OK" in r2.stdout \
                        and "doesn't match the machine type" \
                        not in r2.stderr \
                        and "supported on the host machine" \
                        not in r2.stderr:
                    return "safe"
                # the reload leg itself warned or crashed: THE hazard
                return "unsafe"
            # r1 failing is infrastructure, not a reload verdict
            return None
        except Exception:
            return None                        # fail-safe: cache off
        finally:
            import shutil

            shutil.rmtree(cache, ignore_errors=True)

    verdict = _persistent_probe(
        _ROUNDTRIP_MEMO, (tag, ver),
        f"{os.path.normpath(scoped_dir)}.{ver}.roundtrip",
        ("safe", "unsafe"), probe)
    return verdict == "safe"


_FLAGS_MEMO: dict = {}       # (flags, jaxlib ver) -> bool, per process


def xla_flags_supported(flags: str, timeout: int = 180) -> bool:
    """True when the installed XLA accepts every entry in ``flags``.

    XLA hard-aborts the whole process at client init on an unknown
    XLA_FLAGS entry (parse_flags_from_env: "Unknown flags in XLA_FLAGS")
    — there is no graceful in-process probe, so try them in a throwaway
    subprocess.  The verdict persists in the system temp dir keyed by
    the jaxlib version and a flags hash (a jaxlib upgrade re-probes);
    memoization/persistence semantics are _persistent_probe's."""
    import tempfile

    ver = _jaxlib_version()
    tag = hashlib.sha1(flags.encode()).hexdigest()[:12]

    def probe():
        import subprocess

        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the tunnel
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = flags
        try:
            r = subprocess.run([sys.executable, "-c",
                                "import jax; jax.devices()"],
                               capture_output=True, text=True, env=env,
                               timeout=timeout)
        except Exception:
            return None
        if r.returncode == 0:
            return "ok"
        if "Unknown flags in XLA_FLAGS" in (r.stderr or ""):
            return "unknown-flag"
        return None    # other nonzero rcs are infrastructure noise

    verdict = _persistent_probe(
        _FLAGS_MEMO, (flags, ver),
        os.path.join(tempfile.gettempdir(),
                     f"xla-flags-{tag}.{ver}.verdict"),
        ("ok", "unknown-flag"), probe)
    return verdict == "ok"


def gated_cpu_cache(base: str):
    """THE one entry point for pointing an XLA:CPU run at a persistent
    compilation cache: host-scoped path when this box round-trips its
    own entries, ``None`` (= leave the cache off) when it does not.
    Every place that sets ``jax_compilation_cache_dir`` or
    ``JAX_COMPILATION_CACHE_DIR`` for a forced-CPU run must go through
    here — a direct ``host_scoped_cpu_cache`` call reopens the
    same-host reload abort this module exists to close.

    ``MPI_TPU_DISABLE_COMPILE_CACHE=1`` forces the cache off regardless
    of the canary verdict — the escape hatch for boxes where the simple
    canary round-trips but a REAL entry (the scanned train step) still
    aborts on reload (scripts/t1_guard.sh uses it for the post-segfault
    rerun: slow beats fatal, and a rerun must not re-crash on the very
    reload that killed the first pass)."""
    if os.environ.get("MPI_TPU_DISABLE_COMPILE_CACHE", "") not in ("", "0"):
        return None
    scoped = host_scoped_cpu_cache(base)
    return scoped if cpu_cache_roundtrip_safe(scoped) else None
