"""Trace-time path-engagement registry.

The transformer stack selects between implementations at trace time (Pallas
flash kernel vs XLA dense attention, chunked vs dense CE, packed vs
all-position MLM head) — and the flash path additionally degrades silently
when the Mosaic compile probe fails (ops/flash_attention.kernel_supported).
A benchmark number is meaningless if the artifact can't say which path it
measured: an XLA-fallback run would masquerade as a kernel number.

Model code calls ``record(key, value)`` at each selection point; the bench
harness calls ``reset()`` before tracing and ``snapshot()`` after, embedding
the result in the JSON ``detail``.  Records fire during ``jax.jit`` tracing
(Python executes once per compilation), so a snapshot taken after the first
call reflects exactly the paths baked into the compiled step.
"""

from __future__ import annotations

_RECORDS: dict = {}


def record(key: str, value) -> None:
    """Record a path selection (last write wins; layers all pick the same
    path, so one key per decision point suffices)."""
    _RECORDS[key] = value


def snapshot() -> dict:
    return dict(_RECORDS)


def reset() -> None:
    _RECORDS.clear()
