"""One implementation of the repo's JSON-strictness rule.

NaN/Inf are not JSON: ``json.dumps`` happily writes literal ``NaN`` /
``Infinity`` tokens (``allow_nan`` defaults True) and strict consumers
(jq, ``JSON.parse``) abort the whole stream on one bad line.  bench.py's
output lines and the measurement queue's MEASURE_LOG.jsonl route through
``json_safe``; utils/metrics_writer.py applies the same rule inline at
its single scalar() write site (a scalar check, not a tree walk).
"""

from __future__ import annotations


def json_safe(obj):
    """NaN and ±Inf -> None, recursively, through dicts/lists/tuples."""
    if isinstance(obj, float) and (obj != obj or obj in
                                   (float("inf"), float("-inf"))):
        return None
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj
