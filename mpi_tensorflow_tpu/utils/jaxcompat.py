"""Version compatibility shims for the installed jax.

The repo is written against the current jax surface (``jax.shard_map``
with ``check_vma``, ``lax.axis_size``); older jaxlibs ship the same
machinery under ``jax.experimental.shard_map.shard_map`` (keyword
``check_rep``) and expose a mapped axis's static size only through
``jax._src.core.axis_frame``.  One shim, installed once at package
import, keeps every call site — modules, tests, bench, scripts — on the
one modern spelling instead of scattering per-site fallbacks.
"""

from __future__ import annotations

# names of shims this jax actually needed; empty on a modern jax.
# Consumers (tests) use truthiness as "running on a legacy jaxlib" —
# e.g. to skip the ZeRO-1 x PP suite whose graphs segfault (process-
# fatal) in the old tracer.
LEGACY_SHIMS: list = []


def install() -> None:
    """Install every shim the running jax needs (each one a no-op when
    the modern surface is already present)."""
    _ensure_shard_map()
    _ensure_axis_size()
    _ensure_pcast()


def _ensure_shard_map() -> None:
    """Make ``jax.shard_map(..., check_vma=...)`` work on this jax."""
    import jax

    if getattr(jax, "shard_map", None) is not None:
        return

    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(f, /, *, mesh, in_specs, out_specs, check_vma=None,
                  **kw):
        # the old check_rep inference is strictly weaker than modern
        # check_vma (it cannot see through psum-into-replicated, which
        # the train-step call sites rely on), so an unspecified check
        # maps to False rather than the old True default
        kw.setdefault("check_rep",
                      False if check_vma is None else check_vma)
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kw)

    jax.shard_map = shard_map
    LEGACY_SHIMS.append("shard_map")


def _ensure_axis_size() -> None:
    """Make ``jax.lax.axis_size(name)`` work on this jax: the old
    ``axis_frame`` lookup already returns the STATIC Python int the call
    sites rely on (loop bounds, jnp.arange lengths)."""
    from jax import lax

    if getattr(lax, "axis_size", None) is not None:
        return

    from jax._src import core

    def axis_size(axis_name):
        return core.axis_frame(axis_name)

    lax.axis_size = axis_size
    LEGACY_SHIMS.append("axis_size")


def _ensure_pcast() -> None:
    """Make ``lax.pcast(x, axis, to="varying")`` work on this jax.

    Old shard_map has no varying-manual-axes (VMA) type system at all —
    with its ``check_rep=False`` every value is effectively already
    per-shard data, so the modern replicated->varying cast is an
    identity.  Callers must pair it with an EXPLICIT psum over the
    gradient (train/step.py does): on legacy jax the transpose of a
    replicated shard_map input does NOT insert the allreduce the modern
    VMA machinery provides."""
    from jax import lax

    if getattr(lax, "pcast", None) is not None:
        return

    def pcast(x, axis_name, *, to):
        del axis_name, to
        return x

    lax.pcast = pcast
    LEGACY_SHIMS.append("pcast")
