"""Console trace in the reference's format, kept diffable MPI-vs-TPU.

The reference prints (mpipy.py:77, 88):
    ``Process ID: <rank>  training session starts!``
    ``<rank>  process at  <step> with test error: <e>%``
every 50 steps, flushing stdout.  We reproduce the exact format so traces can
be compared side by side (SURVEY.md §5 metrics row), and add the timing lines
the reference lacks (its timer is commented out at mpipy.py:78).
"""

from __future__ import annotations

import sys


def session_start(rank: int) -> None:
    print("Process ID:", rank, " training session starts!")
    sys.stdout.flush()


def step_trace(rank: int, step: int, test_error: float) -> None:
    # exact reference format (mpipy.py:88)
    print(rank, " process at ", step, "with test error: %.1f%%" % test_error)
    sys.stdout.flush()


def val_trace(rank: int, val_error: float) -> None:
    """Validation-error line (early-stopping mode; no reference analogue —
    the reference never reads its validation shards, mpipy.py:236-241)."""
    print(f"{rank}  validation error: {val_error:.1f}%")
    sys.stdout.flush()


def timing_summary(images_per_sec: float, step_time_ms: float,
                   num_devices: int) -> None:
    print(f"[timing] {images_per_sec:,.0f} images/sec "
          f"({images_per_sec / max(num_devices, 1):,.0f} /chip) | "
          f"step {step_time_ms:.3f} ms | {num_devices} device(s)")
    sys.stdout.flush()
