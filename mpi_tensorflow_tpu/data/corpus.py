"""Real-text corpus loading for the language-model families.

The reference loads exactly one dataset (MNIST idx files, mpipy.py:185-229);
the framework's LM families (BERT-MLM, MoE, causal LM) additionally accept
any local text file, tokenized by one of two self-contained schemes:

- **byte-level** (default): ids 0-4 are specials (0 pad, 4 the MLM mask
  token, matching data/synthetic.py), bytes map to 5..260 — vocab 261.  No
  vocab file needed (zero-egress friendly).
- **WordPiece** (``vocab_file=``): a user-supplied one-token-per-line
  vocabulary (the standard BERT ``vocab.txt`` layout, e.g. the 30522-entry
  bert-base-uncased file) with greedy longest-match encoding and ``##``
  continuation pieces.  This is how ``--text-file`` training exercises the
  packed/chunked MLM head at flagship vocab size (the perf-critical path —
  VERDICT r2 #8) instead of the 261-entry byte vocab.

Every downstream component (chunked CE, vocab-parallel TP) is
vocab-size-generic; the loop adopts the loaded vocabulary's size.
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np

BYTE_VOCAB = 261          # 5 specials + 256 byte values
PAD, MASK_TOKEN = 0, 4
_BYTE_OFFSET = 5


def encode_bytes(text: bytes | str) -> np.ndarray:
    """Byte-level token ids (1-D int32)."""
    if isinstance(text, str):
        text = text.encode("utf-8")
    return np.frombuffer(text, np.uint8).astype(np.int32) + _BYTE_OFFSET


def decode_bytes(ids: np.ndarray) -> bytes:
    b = np.asarray(ids, np.int64) - _BYTE_OFFSET
    return b[(b >= 0) & (b < 256)].astype(np.uint8).tobytes()


class WordPieceVocab:
    """BERT-style WordPiece vocabulary + greedy longest-match encoder.

    Vocab file: one token per line (``vocab.txt`` layout); line number is
    the id.  Continuation pieces start with ``##``.  Encoding: lowercase
    (uncased convention), split on whitespace and punctuation, then
    longest-prefix-match within each word; words with no match become
    ``[UNK]``.  Self-contained — no tokenizer package, no downloads.
    """

    def __init__(self, tokens: list):
        self.id_of = {t: i for i, t in enumerate(tokens)}
        self.tokens = list(tokens)
        if len(self.id_of) != len(tokens):
            raise ValueError("vocab file contains duplicate tokens")
        self.unk = self.id_of.get("[UNK]")
        self.mask = self.id_of.get("[MASK]")
        self._max_piece = max((len(t) for t in tokens), default=1)
        self._native = None        # lazy C++ encoder (ASCII fast path)
        self._native_tried = False

    @classmethod
    def from_file(cls, path: str) -> "WordPieceVocab":
        # strip() so CRLF-saved vocab files don't leave \r on every token
        # (which would silently match nothing)
        with open(path, encoding="utf-8") as f:
            return cls([line.strip() for line in f if line.strip()])

    def random_replacement_ids(self) -> np.ndarray:
        """Ids eligible as BERT-recipe random replacements: everything
        except bracket-wrapped entries ([PAD], [MASK], [unused57], ...)."""
        ids = np.asarray([i for i, t in enumerate(self.tokens)
                          if not (t.startswith("[") and t.endswith("]"))],
                         np.int32)
        return ids if len(ids) else np.arange(self.size, dtype=np.int32)

    @property
    def size(self) -> int:
        return len(self.tokens)

    def _split_words(self, text: str) -> list:
        out, word = [], []
        for ch in text.lower():
            if ch.isspace():
                if word:
                    out.append("".join(word))
                    word = []
            elif not (ch.isalnum() or ch == "'"):
                if word:
                    out.append("".join(word))
                    word = []
                out.append(ch)            # punctuation is its own word
            else:
                word.append(ch)
        if word:
            out.append("".join(word))
        return out

    def encode(self, text: Union[str, bytes]) -> np.ndarray:
        """Greedy longest-match WordPiece ids (1-D int32).

        ASCII text takes the native C++ encoder (native/wordpiece.cpp,
        measured ~6x on a 1.2MB corpus) when the library builds; the Python path
        below is the reference implementation, the non-ASCII route (its
        Unicode lowercase/char classes differ from the C++ ASCII ones),
        and the no-toolchain fallback.  Parity is pinned bit-for-bit in
        tests/test_corpus.py."""
        if isinstance(text, bytes):
            text = text.decode("utf-8", errors="replace")
        if text.isascii():
            if not self._native_tried:
                self._native_tried = True
                from mpi_tensorflow_tpu.data import native

                if native.WordPieceNative.available():
                    self._native = native.WordPieceNative(self.tokens)
            if self._native is not None:
                return self._native.encode(text.encode("ascii"))
        ids = []
        for word in self._split_words(text):
            pos, pieces = 0, []
            while pos < len(word):
                end = min(len(word), pos + self._max_piece)
                piece_id = None
                while end > pos:
                    cand = word[pos:end]
                    if pos > 0:
                        cand = "##" + cand
                    if cand in self.id_of:
                        piece_id = self.id_of[cand]
                        break
                    end -= 1
                if piece_id is None:      # no match -> whole word is UNK
                    pieces = None
                    break
                pieces.append(piece_id)
                pos = end
            if pieces is None:
                if self.unk is None:
                    raise ValueError(
                        f"word {word!r} has no WordPiece match and the "
                        f"vocab has no [UNK] token to fall back to")
                ids.append(self.unk)
            else:
                ids.extend(pieces)
        return np.asarray(ids, np.int32)


def sequences_from_file(path: str, *, seq_len: int,
                        max_sequences: int | None = None,
                        vocab: Optional[WordPieceVocab] = None) -> np.ndarray:
    """Tokenize a text file into (N, seq_len) int32 rows (tail dropped —
    static shapes for jit, like the reference's size truncation,
    mpipy.py:211-213).  ``vocab``: WordPiece encoding; None = byte-level."""
    with open(path, "rb") as f:
        raw = f.read()
    if vocab is None:
        ids = encode_bytes(raw)
    elif max_sequences is None:
        ids = vocab.encode(raw)
    else:
        # stream line-by-line and stop once enough ids exist: WordPiece
        # encoding is a per-character python loop, so encoding a huge
        # corpus only to truncate to max_sequences rows would waste
        # minutes of single-core time (words never span newlines, so
        # line-wise encoding equals whole-file encoding)
        need = max_sequences * seq_len
        parts, total = [], 0
        for line in raw.decode("utf-8", errors="replace").splitlines():
            enc = vocab.encode(line)
            if len(enc):
                parts.append(enc)
                total += len(enc)
            if total >= need:
                break
        ids = (np.concatenate(parts) if parts
               else np.zeros((0,), np.int32))
    n = len(ids) // seq_len
    if max_sequences is not None:
        n = min(n, max_sequences)
    if n == 0:
        raise ValueError(f"{path}: shorter than one sequence ({seq_len})")
    return ids[:n * seq_len].reshape(n, seq_len)


def mlm_from_tokens(tokens: np.ndarray, *, mask_rate: float = 0.15,
                    mask_token: int = MASK_TOKEN, seed: int = 0,
                    random_ids: Optional[np.ndarray] = None):
    """BERT-style masking over a (N, S) token grid.

    80% of selected positions -> mask token, 10% -> random non-special id
    (drawn from ``random_ids``; default the byte range), 10% kept (the
    original BERT recipe); returns ``(inputs, targets, mask)`` in the same
    layout as data/synthetic.mlm_batches.
    """
    rng = np.random.default_rng(seed)
    tokens = np.asarray(tokens, np.int32)
    mask = rng.random(tokens.shape) < mask_rate
    r = rng.random(tokens.shape)
    inputs = tokens.copy()
    inputs[mask & (r < 0.8)] = mask_token
    rand_pos = mask & (r >= 0.8) & (r < 0.9)
    if random_ids is None:
        random_ids = np.arange(_BYTE_OFFSET, BYTE_VOCAB, dtype=np.int32)
    inputs[rand_pos] = rng.choice(np.asarray(random_ids, np.int32),
                                  size=int(rand_pos.sum()))
    return inputs, tokens, mask


def _resolve_vocab(vocab_file) -> Optional[WordPieceVocab]:
    if vocab_file is None:
        return None
    if isinstance(vocab_file, WordPieceVocab):
        return vocab_file
    return WordPieceVocab.from_file(vocab_file)


def load_mlm(path: str, *, seq_len: int = 128, mask_rate: float = 0.15,
             seed: int = 0, max_sequences: int | None = None,
             vocab_file=None):
    """Text file -> masked-LM arrays ``(inputs, targets, mask)``.

    ``vocab_file``: path to a WordPiece vocab (or a ``WordPieceVocab``) —
    masking then uses the vocab's ``[MASK]`` id and draws random
    replacements over its full id range; None = byte-level scheme."""
    vocab = _resolve_vocab(vocab_file)
    toks = sequences_from_file(path, seq_len=seq_len,
                               max_sequences=max_sequences, vocab=vocab)
    if vocab is None:
        return mlm_from_tokens(toks, mask_rate=mask_rate, seed=seed)
    if vocab.mask is None:
        raise ValueError("vocab file has no [MASK] token — required for "
                         "MLM training")
    return mlm_from_tokens(toks, mask_rate=mask_rate, seed=seed,
                           mask_token=vocab.mask,
                           random_ids=vocab.random_replacement_ids())


def load_causal(path: str, *, seq_len: int = 128,
                max_sequences: int | None = None,
                vocab_file=None) -> np.ndarray:
    """Text file -> (N, S) token rows for the causal family (targets are
    the inputs shifted — models/gpt.py derives them)."""
    return sequences_from_file(path, seq_len=seq_len,
                               max_sequences=max_sequences,
                               vocab=_resolve_vocab(vocab_file))
