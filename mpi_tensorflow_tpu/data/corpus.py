"""Real-text corpus loading for the language-model families.

The reference loads exactly one dataset (MNIST idx files, mpipy.py:185-229);
the framework's LM families (BERT-MLM, MoE, causal LM) additionally accept
any local text file — tokenized offline with a self-contained byte-level
tokenizer, so no downloads, vocab files, or external tokenizer packages are
needed (zero-egress friendly).

Byte-level scheme: ids 0-4 are specials (0 pad, 4 the MLM mask token,
matching data/synthetic.py), bytes map to 5..260 — vocab 261.  Real BERT
vocabularies drop in by re-tokenizing and raising ``vocab_size``; every
downstream component (chunked CE, vocab-parallel TP) is vocab-size-generic.
"""

from __future__ import annotations

import numpy as np

BYTE_VOCAB = 261          # 5 specials + 256 byte values
PAD, MASK_TOKEN = 0, 4
_BYTE_OFFSET = 5


def encode_bytes(text: bytes | str) -> np.ndarray:
    """Byte-level token ids (1-D int32)."""
    if isinstance(text, str):
        text = text.encode("utf-8")
    return np.frombuffer(text, np.uint8).astype(np.int32) + _BYTE_OFFSET


def decode_bytes(ids: np.ndarray) -> bytes:
    b = np.asarray(ids, np.int64) - _BYTE_OFFSET
    return b[(b >= 0) & (b < 256)].astype(np.uint8).tobytes()


def sequences_from_file(path: str, *, seq_len: int,
                        max_sequences: int | None = None) -> np.ndarray:
    """Tokenize a text file into (N, seq_len) int32 rows (tail dropped —
    static shapes for jit, like the reference's size truncation,
    mpipy.py:211-213)."""
    with open(path, "rb") as f:
        ids = encode_bytes(f.read())
    n = len(ids) // seq_len
    if max_sequences is not None:
        n = min(n, max_sequences)
    if n == 0:
        raise ValueError(f"{path}: shorter than one sequence ({seq_len})")
    return ids[:n * seq_len].reshape(n, seq_len)


def mlm_from_tokens(tokens: np.ndarray, *, mask_rate: float = 0.15,
                    mask_token: int = MASK_TOKEN, seed: int = 0):
    """BERT-style masking over a (N, S) token grid.

    80% of selected positions -> mask token, 10% -> random id, 10% kept
    (the original BERT recipe); returns ``(inputs, targets, mask)`` in the
    same layout as data/synthetic.mlm_batches.
    """
    rng = np.random.default_rng(seed)
    tokens = np.asarray(tokens, np.int32)
    mask = rng.random(tokens.shape) < mask_rate
    r = rng.random(tokens.shape)
    inputs = tokens.copy()
    inputs[mask & (r < 0.8)] = mask_token
    rand_pos = mask & (r >= 0.8) & (r < 0.9)
    # replacements drawn over the FULL byte vocab — content-independent
    # masking distribution
    inputs[rand_pos] = rng.integers(_BYTE_OFFSET, BYTE_VOCAB,
                                    size=int(rand_pos.sum()))
    return inputs, tokens, mask


def load_mlm(path: str, *, seq_len: int = 128, mask_rate: float = 0.15,
             seed: int = 0, max_sequences: int | None = None):
    """Text file -> masked-LM arrays ``(inputs, targets, mask)``."""
    toks = sequences_from_file(path, seq_len=seq_len,
                               max_sequences=max_sequences)
    return mlm_from_tokens(toks, mask_rate=mask_rate, seed=seed)


def load_causal(path: str, *, seq_len: int = 128,
                max_sequences: int | None = None) -> np.ndarray:
    """Text file -> (N, S) token rows for the causal family (targets are
    the inputs shifted — models/gpt.py derives them)."""
    return sequences_from_file(path, seq_len=seq_len,
                               max_sequences=max_sequences)
