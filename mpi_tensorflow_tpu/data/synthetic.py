"""Deterministic synthetic datasets for scale-out configs.

The BASELINE.json configs 3-5 (CIFAR-10 ResNet-20, ImageNet ResNet-50,
BERT MLM) are throughput benchmarks — the gradient/allreduce payload and the
step math are what's measured, so learnable synthetic data of the real shapes
is sufficient in an air-gapped environment (and keeps runs reproducible).
Images get a class-dependent signal so short convergence tests can verify the
training loop actually learns.
"""

from __future__ import annotations

import numpy as np

from mpi_tensorflow_tpu.data.mnist import Splits


def image_classification(train_n: int, test_n: int, *, size: int,
                         channels: int, num_classes: int,
                         seed: int = 0) -> Splits:
    """Class-separable images in ``[-0.5, 0.5]`` (same normalization as the
    MNIST pipeline, mpipy.py:230 buffers), labels int64."""
    rng = np.random.default_rng(seed)

    def make(n):
        labels = rng.integers(0, num_classes, size=n).astype(np.int64)
        x = rng.normal(0.0, 0.15, size=(n, size, size, channels))
        # class signal: a low-frequency pattern per class
        freqs = 1 + (np.arange(num_classes) % 4)
        phases = 2 * np.pi * np.arange(num_classes) / num_classes
        t = np.linspace(0, 2 * np.pi, size)
        for c in range(num_classes):
            mask = labels == c
            pattern = 0.25 * np.outer(np.sin(freqs[c] * t + phases[c]),
                                      np.cos(freqs[c] * t))
            x[mask] += pattern[None, :, :, None]
        return np.clip(x, -0.5, 0.5).astype(np.float32), labels

    tr_x, tr_y = make(train_n)
    ts_x, ts_y = make(test_n)
    val_n = max(train_n // 12, 1)
    return Splits(
        train_data=tr_x[val_n:], train_labels=tr_y[val_n:],
        test_data=ts_x, test_labels=ts_y,
        val_data=tr_x[:val_n], val_labels=tr_y[:val_n],
    )


def mlm_batches(num_examples: int, *, seq_len: int, vocab_size: int,
                mask_token: int = 4, mask_rate: float = 0.15,
                seed: int = 0):
    """Synthetic masked-LM data: token sequences with local structure
    (next-token correlation) so MLM loss is reducible.

    Returns ``(tokens, targets, mask_positions)`` with tokens already masked:
    ``tokens`` int32 (N, S) input ids, ``targets`` int32 (N, S) original ids,
    ``mask`` bool (N, S) True where the loss applies.
    """
    rng = np.random.default_rng(seed)
    # piecewise-constant runs (length 8): a masked token is recoverable from
    # its neighbors, so held-out masked error is reducible with little
    # training — the right difficulty for CI while still exercising
    # attention (the model must COPY from context, not memorize)
    run = 8
    n_runs = (seq_len + run - 1) // run
    run_tokens = rng.integers(5, vocab_size,
                              size=(num_examples, n_runs))
    clean = np.repeat(run_tokens, run, axis=1)[:, :seq_len]
    noise = rng.random((num_examples, seq_len)) < 0.02
    clean = np.where(noise,
                     rng.integers(5, vocab_size, size=clean.shape), clean)
    mask = rng.random((num_examples, seq_len)) < mask_rate
    tokens = np.where(mask, mask_token, clean)
    return (tokens.astype(np.int32), clean.astype(np.int32), mask)


def seq2seq_batches(num_examples: int, *, src_len: int, tgt_len: int,
                    vocab_size: int, bos_token: int = 0, seed: int = 0):
    """Synthetic sequence-to-sequence data for the encoder-decoder
    family: the target is the REVERSED source (BOS-seeded, truncated to
    ``tgt_len``).  Reversal forces the decoder through cross-attention —
    position t of the target copies position S-1-t of the source, which
    no causal-self-attention shortcut can produce.

    Returns ``(src, tgt)`` int32 arrays (N, src_len) / (N, tgt_len);
    ``tgt[:, 0]`` is BOS, positions 1.. are supervised.
    """
    if tgt_len > src_len + 1:
        raise ValueError(
            f"tgt_len {tgt_len} > src_len + 1 ({src_len + 1}): the "
            f"reversed source cannot fill the target")
    rng = np.random.default_rng(seed)
    src = rng.integers(5, vocab_size, size=(num_examples, src_len))
    rev = src[:, ::-1]
    tgt = np.concatenate(
        [np.full((num_examples, 1), bos_token), rev[:, :tgt_len - 1]],
        axis=1)
    return src.astype(np.int32), tgt.astype(np.int32)
