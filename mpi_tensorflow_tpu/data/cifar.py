"""CIFAR-10 pipeline (BASELINE.json config 3: ResNet-20 data-parallel).

Attempts the real binary distribution; in air-gapped environments falls back
to deterministic synthetic data of the same shapes (32x32x3, 10 classes) —
see ``data.synthetic`` for why that is sufficient for the benchmark role.
"""

from __future__ import annotations

import os
import tarfile
import urllib.error
import urllib.request

import numpy as np

from mpi_tensorflow_tpu.data.mnist import Splits
from mpi_tensorflow_tpu.data import synthetic

CIFAR_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-binary.tar.gz"
_REC = 3073  # 1 label byte + 3072 pixel bytes


def _parse_bin(path: str) -> tuple[np.ndarray, np.ndarray]:
    raw = np.frombuffer(open(path, "rb").read(), dtype=np.uint8)
    raw = raw.reshape(-1, _REC)
    labels = raw[:, 0].astype(np.int64)
    # stored CHW planar -> NHWC, normalized like MNIST: (p - 127.5)/255
    imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    imgs = (imgs.astype(np.float32) - 127.5) / 255.0
    return imgs, labels


def load_splits(data_dir: str = "./data", train_n: int | None = None,
                test_n: int | None = None) -> Splits:
    bin_dir = os.path.join(data_dir, "cifar-10-batches-bin")
    if not os.path.isdir(bin_dir):
        os.makedirs(data_dir, exist_ok=True)
        tgz = os.path.join(data_dir, "cifar-10-binary.tar.gz")
        try:
            if not os.path.exists(tgz):
                urllib.request.urlretrieve(CIFAR_URL, tgz)
            with tarfile.open(tgz) as tf:
                tf.extractall(data_dir)
        except (urllib.error.URLError, OSError):
            return synthetic.image_classification(
                train_n or 50000, test_n or 10000,
                size=32, channels=3, num_classes=10)
    tr = [_parse_bin(os.path.join(bin_dir, f"data_batch_{i}.bin"))
          for i in range(1, 6)]
    tr_x = np.concatenate([x for x, _ in tr])[:train_n]
    tr_y = np.concatenate([y for _, y in tr])[:train_n]
    ts_x, ts_y = _parse_bin(os.path.join(bin_dir, "test_batch.bin"))
    ts_x, ts_y = ts_x[:test_n], ts_y[:test_n]
    val_n = max(tr_x.shape[0] // 12, 1)
    return Splits(train_data=tr_x[val_n:], train_labels=tr_y[val_n:],
                  test_data=ts_x, test_labels=ts_y,
                  val_data=tr_x[:val_n], val_labels=tr_y[:val_n])
