"""Background window prefetch: batch assembly overlapped with device time.

The reference assembles every batch inline in its Python loop
(mpipy.py:80-82), serialized with ``sess.run``.  The fused training loop
(train/loop.py) consumes whole *windows* — (K, global_b, ...) arrays, one
per dispatch — whose assembly is a strided gather worth overlapping with
the device's execution of the previous window.

Two implementations behind one interface:

- ``NativePrefetcher``: the C++ worker (native/prefetcher.cpp, ctypes) —
  the framework's native data-loader component (SURVEY.md §2 E1/E2 role);
- ``ThreadPrefetcher``: pure-Python thread + queue fallback, always
  available.

``make_prefetcher`` picks native when the library loads, else the thread
fallback; tests pin both to the inline assembly byte-for-byte.

The window schedule (start step, valid width) is computed by the caller —
the trace-cadence logic stays in train/loop.py only.
"""

from __future__ import annotations

import ctypes
import os
import queue
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libprefetcher.so")

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    # one shared build helper (data/native.py): run make unconditionally (a
    # no-op when up to date) so source edits are never shadowed by a stale
    # binary; fall back to an existing .so when make is unavailable
    from mpi_tensorflow_tpu.data import native as _native

    _native._build()
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.pf_create.argtypes = [f32p, i64p] + [ctypes.c_int64] * 5 + \
        [i64p, i64p, ctypes.c_int64, ctypes.c_int64]
    lib.pf_create.restype = ctypes.c_void_p
    lib.pf_next.argtypes = [ctypes.c_void_p, f32p, i64p]
    lib.pf_next.restype = ctypes.c_int64
    lib.pf_destroy.argtypes = [ctypes.c_void_p]
    lib.pf_destroy.restype = None
    _lib = lib
    return _lib


def assemble_window(tr_d, tr_l, t0: int, w: int, window_k: int,
                    batch: int):
    """Reference (inline) assembly of one window — the exact gather the
    prefetchers perform, used directly when prefetch is off and by tests as
    the golden implementation.  ``tr_d``: (n_shards, local_n, ...feat),
    ``tr_l``: (n_shards, local_n)."""
    n_shards, local_n = tr_l.shape
    global_b = n_shards * batch
    bs = np.zeros((window_k, global_b) + tr_d.shape[2:], tr_d.dtype)
    ls = np.zeros((window_k, global_b), tr_l.dtype)
    for j in range(w):
        off = ((t0 + j) * batch) % (local_n - batch)       # mpipy.py:80
        bs[j] = tr_d[:, off:off + batch].reshape(global_b, *tr_d.shape[2:])
        ls[j] = tr_l[:, off:off + batch].reshape(global_b)
    return bs, ls


class ThreadPrefetcher:
    """Python-thread implementation: assembles windows ahead into a bounded
    queue (double buffering by default)."""

    def __init__(self, tr_d, tr_l, starts, widths, window_k: int,
                 batch: int, depth: int = 2):
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._n = len(starts)
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None

        def work():
            try:
                for t0, w in zip(starts, widths):
                    if self._stop.is_set():
                        return
                    item = assemble_window(tr_d, tr_l, int(t0), int(w),
                                           window_k, batch) + (int(w),)
                    while not self._stop.is_set():
                        try:
                            self._q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
            except BaseException as e:   # surface in next(), don't hang
                self._exc = e

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def next(self):
        """-> (batches, labels, width) or None when exhausted.  Raises if
        the worker thread died instead of blocking forever."""
        if self._n == 0:
            return None
        while True:
            try:
                item = self._q.get(timeout=1.0)
                break
            except queue.Empty:
                if self._exc is not None:
                    raise RuntimeError(
                        "prefetch worker failed") from self._exc
                if not self._thread.is_alive():
                    # the worker may have put its final item and exited
                    # between our timeout and the liveness check — only a
                    # truly empty queue means it died short
                    try:
                        item = self._q.get_nowait()
                        break
                    except queue.Empty:
                        if self._exc is not None:   # died raising, just now
                            raise RuntimeError(
                                "prefetch worker failed") from self._exc
                        raise RuntimeError(
                            "prefetch worker died without producing a "
                            "window") from None
        self._n -= 1
        return item

    def close(self):
        # stop the worker promptly (a preemption exit must not wait for the
        # rest of the schedule to be assembled)
        self._stop.set()
        self._thread.join(timeout=5.0)


class NativePrefetcher:
    """C++ worker (native/prefetcher.cpp).  Arrays are borrowed by the
    library — this object keeps references so they outlive the worker."""

    def __init__(self, lib, tr_d, tr_l, starts, widths, window_k: int,
                 batch: int, depth: int = 2):
        n_shards, local_n = tr_l.shape
        feat = int(np.prod(tr_d.shape[2:], dtype=np.int64))
        self._lib = lib
        self._feat_shape = tr_d.shape[2:]
        self._global_b = n_shards * batch
        self._window_k = window_k
        # borrowed by C++: keep alive + contiguous
        self._d = np.ascontiguousarray(tr_d, dtype=np.float32)
        self._l = np.ascontiguousarray(tr_l, dtype=np.int64)
        self._starts = np.asarray(starts, np.int64)
        self._widths = np.asarray(widths, np.int64)
        self._n = len(starts)
        self._h = lib.pf_create(
            self._d.reshape(-1, feat), self._l, n_shards, local_n, feat,
            batch, window_k, self._starts, self._widths, self._n, depth)
        if not self._h:
            raise RuntimeError("pf_create failed")

    def next(self):
        if self._n == 0:
            return None
        bs = np.empty((self._window_k, self._global_b) + self._feat_shape,
                      np.float32)
        ls = np.empty((self._window_k, self._global_b), np.int64)
        w = self._lib.pf_next(self._h, bs.reshape(bs.shape[0], -1), ls)
        if w == 0:
            return None
        self._n -= 1
        return bs, ls, int(w)

    def close(self):
        if self._h:
            self._lib.pf_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def make_prefetcher(tr_d, tr_l, starts, widths, window_k: int, batch: int,
                    depth: int = 2, force: Optional[str] = None):
    """Native when available (or ``force="native"``), else the thread
    fallback (``force="thread"``)."""
    lib = get_lib() if force in (None, "native") else None
    if force == "native" and lib is None:
        raise RuntimeError("native prefetcher library unavailable")
    native_ok = (np.dtype(tr_d.dtype) == np.float32
                 and np.dtype(tr_l.dtype) == np.int64)
    if lib is not None and not native_ok:
        # the C++ path is float32/int64 only; a silent cast here would make
        # prefetch=native diverge numerically from the inline/thread paths
        if force == "native":
            raise ValueError(
                f"native prefetcher requires float32 data / int64 labels, "
                f"got {tr_d.dtype}/{tr_l.dtype}")
        lib = None
    if lib is not None:
        return NativePrefetcher(lib, tr_d, tr_l, starts, widths, window_k,
                                batch, depth)
    return ThreadPrefetcher(tr_d, tr_l, starts, widths, window_k, batch,
                            depth)
