"""Per-host / per-device contiguous data sharding.

Replaces the reference's root-0 ``comm.Scatter`` fan-out (mpipy.py:236-241)
with the TPU-idiomatic pattern: every host computes its own contiguous slice
(no root bottleneck, no second copy), and
``jax.make_array_from_process_local_data`` assembles the global sharded array
when a mesh is involved.

Semantics preserved from the reference:
- sizes truncated to a multiple of the shard count (``55000//size*size`` etc.,
  mpipy.py:211-213);
- shard ``i`` receives rows ``[i*n/k, (i+1)*n/k)`` — ``MPI.Scatter`` on a
  contiguous buffer is exactly contiguous equal chunks.
"""

from __future__ import annotations

from typing import Iterable

import numpy as np


def truncate_to_multiple(n: int, k: int) -> int:
    """``n//k*k`` — the reference's size truncation (mpipy.py:211-213)."""
    return n // k * k


def shard_bounds(n: int, num_shards: int, index: int) -> tuple[int, int]:
    """[start, stop) row range of contiguous equal shard ``index`` of ``n``
    rows (rows past ``n//num_shards*num_shards`` are dropped, as Scatter
    drops them in the reference)."""
    if not 0 <= index < num_shards:
        raise ValueError(f"shard index {index} out of range [0, {num_shards})")
    per = n // num_shards
    return index * per, (index + 1) * per


def shard_array(x: np.ndarray, num_shards: int, index: int) -> np.ndarray:
    """The rows of ``x`` that shard ``index`` owns."""
    start, stop = shard_bounds(x.shape[0], num_shards, index)
    return x[start:stop]


def shard_arrays(arrays: Iterable[np.ndarray], num_shards: int, index: int):
    return tuple(shard_array(a, num_shards, index) for a in arrays)


def host_shard(x: np.ndarray, process_index: int | None = None,
               process_count: int | None = None) -> np.ndarray:
    """This host's contiguous slice, by ``jax.process_index()``.

    On a multi-host pod each host loads/keeps only the rows that feed its
    addressable devices — the Scatter equivalent with no root-0 bottleneck
    (SURVEY.md §7 "Hard parts").
    """
    import jax  # deferred: keep numpy-only callers jax-free

    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    return shard_array(x, process_count, process_index)


def make_global_array(local_batch: np.ndarray, mesh, pspec):
    """Assemble per-host local rows into one global jax.Array sharded over
    ``mesh`` by ``pspec`` (batch-axis sharding over the 'data' mesh axis)."""
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, pspec)
    return jax.make_array_from_process_local_data(sharding, local_batch)


def batch_iterator(data: np.ndarray, labels: np.ndarray, batch_size: int,
                   num_steps: int, start_step: int = 0):
    """Sequential wraparound batch slicing, no shuffling — the reference's
    batching exactly (mpipy.py:80-82): ``offset = (step*B) % (N - B)``.
    """
    n = labels.shape[0]
    for step in range(start_step, num_steps):
        offset = (step * batch_size) % (n - batch_size)
        yield step, data[offset:offset + batch_size], labels[offset:offset + batch_size]


def steps_per_run(num_examples: int, batch_size: int, epochs: int) -> int:
    """``iteration * local_train_size // batch_size`` (mpipy.py:79)."""
    return epochs * num_examples // batch_size
