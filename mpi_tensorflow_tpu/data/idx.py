"""In-repo IDX (MNIST) file format reader/writer.

The reference imports ``extract_data``/``extract_labels`` from the legacy
TensorFlow-models MNIST tutorial module ``convolutional`` (mpipy.py:12) — an
external, un-vendored dependency.  SURVEY.md §7 requires the parser to live
in-repo this time, producing the exact buffers the reference's MPI code proves
at mpipy.py:230-235: images ``float32 (N, 28, 28, 1)`` normalized to
``[-0.5, 0.5]`` via ``(pixel - 127.5) / 255``, labels ``int64 (N,)``.

IDX format: big-endian; magic ``\\x00\\x00<dtype><ndim>``; ``ndim`` uint32
dims; then the raw array.  A writer is included so tests and the synthetic
fallback can fabricate valid files without network access.
"""

from __future__ import annotations

import gzip
import struct
from typing import BinaryIO

import numpy as np

# dtype byte -> numpy dtype (big-endian where multi-byte)
_IDX_DTYPES = {
    0x08: np.dtype(np.uint8),
    0x09: np.dtype(np.int8),
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}
_DTYPE_TO_CODE = {
    np.dtype(np.uint8): 0x08,
    np.dtype(np.int8): 0x09,
    np.dtype(np.int16): 0x0B,
    np.dtype(np.int32): 0x0C,
    np.dtype(np.float32): 0x0D,
    np.dtype(np.float64): 0x0E,
}


def _open(path: str, mode: str) -> BinaryIO:
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    return open(path, mode)


def read_idx(path: str, max_items: int | None = None) -> np.ndarray:
    """Parse an (optionally gzipped) IDX file into a numpy array.

    ``max_items`` truncates along the leading dimension without reading the
    remainder, mirroring the tutorial helpers' ``num_images`` argument used at
    mpipy.py:215-218.
    """
    with _open(path, "rb") as f:
        magic = f.read(4)
        if len(magic) != 4 or magic[0] != 0 or magic[1] != 0:
            raise ValueError(f"{path}: not an IDX file (bad magic {magic!r})")
        dtype_code, ndim = magic[2], magic[3]
        if dtype_code not in _IDX_DTYPES:
            raise ValueError(f"{path}: unknown IDX dtype 0x{dtype_code:02x}")
        dtype = _IDX_DTYPES[dtype_code]
        dims = list(struct.unpack(f">{ndim}I", f.read(4 * ndim)))
        if max_items is not None and dims:
            dims[0] = min(dims[0], max_items)
        count = int(np.prod(dims)) if dims else 1
        buf = f.read(count * dtype.itemsize)
        if len(buf) != count * dtype.itemsize:
            raise ValueError(f"{path}: truncated IDX payload")
        return np.frombuffer(buf, dtype=dtype).reshape(dims)


def write_idx(path: str, array: np.ndarray) -> None:
    """Write ``array`` as an (optionally gzipped) IDX file."""
    dtype = np.dtype(array.dtype)
    if dtype not in _DTYPE_TO_CODE:
        raise ValueError(f"cannot encode dtype {dtype} as IDX")
    with _open(path, "wb") as f:
        f.write(bytes([0, 0, _DTYPE_TO_CODE[dtype], array.ndim]))
        f.write(struct.pack(f">{array.ndim}I", *array.shape))
        f.write(np.ascontiguousarray(array, dtype=dtype.newbyteorder(">")).tobytes())


PIXEL_DEPTH = 255.0


def extract_images(path: str, num_images: int | None = None) -> np.ndarray:
    """IDX image file -> ``float32 (N, H, W, 1)`` in ``[-0.5, 0.5]``.

    Normalization matches the tutorial helper the reference depends on:
    ``(pixel - PIXEL_DEPTH/2) / PIXEL_DEPTH`` — proven by the float32 recv
    buffers at mpipy.py:230.
    """
    raw = read_idx(path, max_items=num_images)
    if raw.ndim != 3:
        raise ValueError(f"{path}: expected 3-D image IDX, got {raw.ndim}-D")
    data = (raw.astype(np.float32) - PIXEL_DEPTH / 2.0) / PIXEL_DEPTH
    return data[..., np.newaxis]


def extract_labels(path: str, num_labels: int | None = None) -> np.ndarray:
    """IDX label file -> ``int64 (N,)`` (byte-compatible with the uint64 recv
    buffers the reference Scatters into at mpipy.py:231-235)."""
    raw = read_idx(path, max_items=num_labels)
    if raw.ndim != 1:
        raise ValueError(f"{path}: expected 1-D label IDX, got {raw.ndim}-D")
    return raw.astype(np.int64)


def error_rate(predictions: np.ndarray, labels: np.ndarray) -> float:
    """Classification error percent from softmax predictions.

    Same metric as the tutorial's ``error_rate`` used at mpipy.py:86:
    ``100 - 100 * (correct / total)``.
    """
    correct = np.sum(np.argmax(predictions, axis=1) == labels)
    return 100.0 - 100.0 * float(correct) / predictions.shape[0]
