"""Data layer: in-repo IDX parsing, dataset pipelines, per-host sharding.

Replaces the reference's external ``convolutional`` import (mpipy.py:12),
``data_exist_here`` downloader (mpipy.py:185-199), and root-0 ``MPI.Scatter``
distribution (mpipy.py:230-241).
"""

from mpi_tensorflow_tpu.data.idx import (  # noqa: F401
    extract_images,
    extract_labels,
    error_rate,
    read_idx,
    write_idx,
)
from mpi_tensorflow_tpu.data.sharding import (  # noqa: F401
    batch_iterator,
    host_shard,
    make_global_array,
    shard_array,
    steps_per_run,
    truncate_to_multiple,
)
