"""ctypes bindings for the native C++ IDX loader (native/idx_loader.cpp).

Fills the native data-path role the reference delegated to external C/C++
libraries (SURVEY.md §2 E1/E2).  The library is built on demand with the
in-repo Makefile; every entry point falls back to the pure-Python parser
(data/idx.py) when the toolchain or build is unavailable, and tests assert
the two produce identical arrays.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libidxloader.so")

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it if needed; None if unavailable."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    # run make unconditionally (no-op when up to date) so source edits are
    # never shadowed by a stale binary; a failed build (no make on PATH)
    # still falls back to a previously built library if one exists
    _build()
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.idx_dims.argtypes = [ctypes.c_char_p, u32p]
    lib.idx_dims.restype = ctypes.c_int
    lib.idx_load_images.argtypes = [ctypes.c_char_p, ctypes.c_int, f32p]
    lib.idx_load_images.restype = ctypes.c_int
    lib.idx_load_labels.argtypes = [ctypes.c_char_p, ctypes.c_int, i64p]
    lib.idx_load_labels.restype = ctypes.c_int
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


def extract_images(path: str, num_images: int | None = None) -> np.ndarray:
    """Native-path equivalent of ``data.idx.extract_images`` (bit-identical
    output); falls back to the Python parser when the library is missing."""
    lib = get_lib()
    if lib is None:
        from mpi_tensorflow_tpu.data import idx

        return idx.extract_images(path, num_images)
    dims = np.zeros(4, np.uint32)
    nd = lib.idx_dims(path.encode(), dims)
    if nd != 3:
        raise ValueError(f"{path}: native loader error/ndim {nd}")
    n = int(dims[0]) if num_images is None else min(int(dims[0]), num_images)
    out = np.empty((n, int(dims[1]), int(dims[2]), 1), np.float32)
    rows = lib.idx_load_images(path.encode(), n, out.reshape(-1))
    if rows != n:
        raise ValueError(f"{path}: native image load failed ({rows})")
    return out


def extract_labels(path: str, num_labels: int | None = None) -> np.ndarray:
    lib = get_lib()
    if lib is None:
        from mpi_tensorflow_tpu.data import idx

        return idx.extract_labels(path, num_labels)
    dims = np.zeros(4, np.uint32)
    nd = lib.idx_dims(path.encode(), dims)
    if nd != 1:
        raise ValueError(f"{path}: native loader error/ndim {nd}")
    n = int(dims[0]) if num_labels is None else min(int(dims[0]), num_labels)
    out = np.empty((n,), np.int64)
    rows = lib.idx_load_labels(path.encode(), n, out)
    if rows != n:
        raise ValueError(f"{path}: native label load failed ({rows})")
    return out
