"""ctypes bindings for the native C++ IDX loader (native/idx_loader.cpp).

Fills the native data-path role the reference delegated to external C/C++
libraries (SURVEY.md §2 E1/E2).  The library is built on demand with the
in-repo Makefile; every entry point falls back to the pure-Python parser
(data/idx.py) when the toolchain or build is unavailable, and tests assert
the two produce identical arrays.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libidxloader.so")

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _build() -> bool:
    try:
        # -k: targets are independent (idx needs -lz, wordpiece does not);
        # one target's link failure must not silently disable the others
        subprocess.run(["make", "-C", _NATIVE_DIR, "-k"], check=False,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return False


def _load_native_lib(path: str, configure) -> Optional[ctypes.CDLL]:
    """Shared lazy loader: run the (no-op-when-fresh) build, dlopen
    ``path``, apply ``configure(lib)`` to declare the symbol signatures.
    Returns None when the toolchain or the library is unavailable."""
    _build()
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    configure(lib)
    return lib


def _configure_idx(lib) -> None:
    u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.idx_dims.argtypes = [ctypes.c_char_p, u32p]
    lib.idx_dims.restype = ctypes.c_int
    lib.idx_load_images.argtypes = [ctypes.c_char_p, ctypes.c_int, f32p]
    lib.idx_load_images.restype = ctypes.c_int
    lib.idx_load_labels.argtypes = [ctypes.c_char_p, ctypes.c_int, i64p]
    lib.idx_load_labels.restype = ctypes.c_int


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded IDX library, building it if needed; None if unavailable."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    _lib = _load_native_lib(_LIB_PATH, _configure_idx)
    return _lib


def available() -> bool:
    return get_lib() is not None


def extract_images(path: str, num_images: int | None = None) -> np.ndarray:
    """Native-path equivalent of ``data.idx.extract_images`` (bit-identical
    output); falls back to the Python parser when the library is missing."""
    lib = get_lib()
    if lib is None:
        from mpi_tensorflow_tpu.data import idx

        return idx.extract_images(path, num_images)
    dims = np.zeros(4, np.uint32)
    nd = lib.idx_dims(path.encode(), dims)
    if nd != 3:
        raise ValueError(f"{path}: native loader error/ndim {nd}")
    n = int(dims[0]) if num_images is None else min(int(dims[0]), num_images)
    out = np.empty((n, int(dims[1]), int(dims[2]), 1), np.float32)
    rows = lib.idx_load_images(path.encode(), n, out.reshape(-1))
    if rows != n:
        raise ValueError(f"{path}: native image load failed ({rows})")
    return out


# -- native WordPiece encoder (native/wordpiece.cpp) ------------------------

_WP_LIB_PATH = os.path.join(_NATIVE_DIR, "libwordpiece.so")
_wp_lib: Optional[ctypes.CDLL] = None
_wp_tried = False


def _configure_wp(lib) -> None:
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    lib.wp_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.wp_create.restype = ctypes.c_void_p
    lib.wp_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_int64, i32p, ctypes.c_int64]
    lib.wp_encode.restype = ctypes.c_int64
    lib.wp_destroy.argtypes = [ctypes.c_void_p]
    lib.wp_destroy.restype = None


def _get_wp_lib() -> Optional[ctypes.CDLL]:
    global _wp_lib, _wp_tried
    if _wp_lib is not None or _wp_tried:
        return _wp_lib
    _wp_tried = True
    _wp_lib = _load_native_lib(_WP_LIB_PATH, _configure_wp)
    return _wp_lib


class WordPieceNative:
    """Handle to the C++ greedy longest-match encoder for one vocabulary.

    ASCII-only by contract: the C++ lowercasing/char classes match
    Python's only on the ASCII subset, so callers must route non-ASCII
    text to the Python encoder (corpus.WordPieceVocab.encode does).
    """

    def __init__(self, tokens: list):
        lib = _get_wp_lib()
        if lib is None:
            raise RuntimeError("native wordpiece library unavailable")
        blob = "\n".join(tokens).encode("utf-8")
        self._lib = lib
        self._handle = lib.wp_create(blob, len(blob))

    @staticmethod
    def available() -> bool:
        return _get_wp_lib() is not None

    def encode(self, text: bytes) -> np.ndarray:
        """ids for ASCII ``text``; raises on [UNK]-less no-match (same
        condition as the Python encoder)."""
        # every emitted id consumes >= 1 input byte, so len(text) bounds
        # the output; -1 (buffer too small) is therefore impossible here
        cap = max(8, len(text))
        out = np.empty(cap, np.int32)
        n = self._lib.wp_encode(self._handle, text, len(text), out, cap)
        if n == -2:
            raise ValueError(
                "word has no WordPiece match and the vocab has no "
                "[UNK] token to fall back to")
        if n < 0:
            raise RuntimeError(f"native wordpiece encode failed ({n})")
        return out[:n].copy()

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_handle", None)
        if lib is not None and h:
            lib.wp_destroy(h)


def extract_labels(path: str, num_labels: int | None = None) -> np.ndarray:
    lib = get_lib()
    if lib is None:
        from mpi_tensorflow_tpu.data import idx

        return idx.extract_labels(path, num_labels)
    dims = np.zeros(4, np.uint32)
    nd = lib.idx_dims(path.encode(), dims)
    if nd != 1:
        raise ValueError(f"{path}: native loader error/ndim {nd}")
    n = int(dims[0]) if num_labels is None else min(int(dims[0]), num_labels)
    out = np.empty((n,), np.int64)
    rows = lib.idx_load_labels(path.encode(), n, out)
    if rows != n:
        raise ValueError(f"{path}: native label load failed ({rows})")
    return out
