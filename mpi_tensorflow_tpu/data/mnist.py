"""MNIST dataset: download, load, and reference-faithful partitioning.

Reproduces the reference's data path end to end:
- download of the 4 idx-gz files from the GCS mirror into ``./data`` if
  absent (``data_exist_here``, mpipy.py:185-199) — with the broken error
  path fixed (the reference references an undefined ``DownloadError`` name,
  mpipy.py:197) and a deterministic synthetic fallback for air-gapped
  environments;
- rank-0-style split (mpipy.py:211-222): sizes truncated to multiples of the
  shard count, validation = first ``5000//k*k`` training rows, train = rows
  ``[5000//k*k, 55000//k*k)``, test = first ``10000//k*k`` test rows.

Unlike the reference there is no root-0 Scatter: each host slices its own
shard (``data.sharding``).
"""

from __future__ import annotations

import dataclasses
import os
import urllib.error
import urllib.request

import numpy as np

from mpi_tensorflow_tpu.data import idx, sharding

DATA_URL = "https://storage.googleapis.com/cvdf-datasets/mnist/"  # mpipy.py:17
FILES = {
    "train_images": "train-images-idx3-ubyte.gz",
    "train_labels": "train-labels-idx1-ubyte.gz",
    "test_images": "t10k-images-idx3-ubyte.gz",
    "test_labels": "t10k-labels-idx1-ubyte.gz",
}
_TRAIN_N, _TEST_N, _VAL_N = 60000, 10000, 5000


@dataclasses.dataclass
class Splits:
    """The six arrays the reference Scatters (mpipy.py:236-241), pre-shard."""
    train_data: np.ndarray
    train_labels: np.ndarray
    test_data: np.ndarray
    test_labels: np.ndarray
    val_data: np.ndarray
    val_labels: np.ndarray

    def shard(self, num_shards: int, index: int) -> "Splits":
        """Contiguous equal shard ``index`` of every split — what one MPI rank
        would have received from the reference's six Scatters."""
        return Splits(*sharding.shard_arrays(dataclasses.astuple(self),
                                             num_shards, index))


def ensure_downloaded(data_dir: str = "./data", synthetic_fallback: bool = True,
                      verbose: bool = True) -> dict:
    """Fetch the 4 MNIST files into ``data_dir`` if absent.

    Unlike the reference (every rank races on ``./data``, mpipy.py:203-206),
    call this once per host.  If the network is unreachable and
    ``synthetic_fallback`` is set, writes deterministic synthetic IDX files of
    the real shapes so the rest of the pipeline is exercised identically.
    """
    os.makedirs(data_dir, exist_ok=True)
    paths = {}
    for key, fname in FILES.items():
        path = os.path.join(data_dir, fname)
        if not os.path.exists(path):
            try:
                urllib.request.urlretrieve(DATA_URL + fname, path)
            except (urllib.error.URLError, OSError) as e:
                if os.path.exists(path):
                    os.remove(path)
                if not synthetic_fallback:
                    raise RuntimeError(f"download of {fname} failed: {e}") from e
                if verbose:
                    print(f"[data] download of {fname} failed ({e}); "
                          f"writing synthetic fallback")
                _write_synthetic(data_dir)
        paths[key] = path
    return paths


def _write_synthetic(data_dir: str, train_n: int = _TRAIN_N,
                     test_n: int = _TEST_N) -> None:
    """Deterministic fake MNIST: class-dependent blob images so a model can
    actually fit them (error decreases), same dtypes/shapes as the real set."""
    rng = np.random.default_rng(0)
    for n, img_name, lbl_name in (
        (train_n, FILES["train_images"], FILES["train_labels"]),
        (test_n, FILES["test_images"], FILES["test_labels"]),
    ):
        labels = rng.integers(0, 10, size=n).astype(np.uint8)
        images = np.zeros((n, 28, 28), dtype=np.uint8)
        # one bright 8x8 patch whose position encodes the class -> separable
        ys, xs = np.unravel_index(np.arange(10) * 7 % 20, (5, 4))
        for c in range(10):
            mask = labels == c
            patch = rng.integers(128, 255, size=(int(mask.sum()), 8, 8))
            y, x = int(ys[c]) * 4, int(xs[c]) * 5
            images[mask, y:y + 8, x:x + 8] = patch
        idx.write_idx(os.path.join(data_dir, img_name), images)
        idx.write_idx(os.path.join(data_dir, lbl_name), labels)


def load_splits(data_dir: str = "./data", num_shards: int = 1,
                train_n: int | None = None, test_n: int | None = None) -> Splits:
    """Load and split exactly as the reference's rank 0 does (mpipy.py:211-222).

    ``num_shards`` plays the role of the MPI world size in the size
    truncations. ``train_n``/``test_n`` allow small subsets for tests.
    """
    paths = {k: os.path.join(data_dir, f) for k, f in FILES.items()}
    k = num_shards
    avail_train = train_n if train_n is not None else _TRAIN_N
    avail_test = test_n if test_n is not None else _TEST_N
    # reference constants scale: val is first 1/12 of train, per mpipy.py:211-213
    val_total = sharding.truncate_to_multiple(avail_train * _VAL_N // _TRAIN_N, k)
    tr_total = sharding.truncate_to_multiple(avail_train * 55000 // _TRAIN_N, k)
    ts_total = sharding.truncate_to_multiple(avail_test, k)

    # native C++ loader when built (bit-identical; data/native.py falls back
    # to the Python parser itself when the library is unavailable)
    from mpi_tensorflow_tpu.data import native

    tr_data = native.extract_images(paths["train_images"], avail_train)
    tr_labels = native.extract_labels(paths["train_labels"], avail_train)
    ts_data = native.extract_images(paths["test_images"], ts_total)
    ts_labels = native.extract_labels(paths["test_labels"], ts_total)

    return Splits(
        train_data=tr_data[val_total:tr_total],
        train_labels=tr_labels[val_total:tr_total],
        test_data=ts_data,
        test_labels=ts_labels,
        val_data=tr_data[:val_total],
        val_labels=tr_labels[:val_total],
    )


def load_for_host(config=None, data_dir: str = "./data", num_shards: int = 1,
                  shard_index: int = 0, **kwargs) -> Splits:
    """One call: ensure data exists, load, and take this shard's slice."""
    ensure_downloaded(data_dir)
    return load_splits(data_dir, num_shards, **kwargs).shard(num_shards, shard_index)
