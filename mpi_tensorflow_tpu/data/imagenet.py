"""ImageNet-shaped pipeline (BASELINE.json config 4: ResNet-50 on v4-32).

ImageNet itself cannot be auto-downloaded; this module serves the benchmark
role with deterministic synthetic 224x224x3/1000-class data, and accepts a
user-provided directory of pre-processed ``.npy`` shards for real runs.
"""

from __future__ import annotations

import os

import numpy as np

from mpi_tensorflow_tpu.data.mnist import Splits
from mpi_tensorflow_tpu.data import synthetic

IMAGE_SIZE = 224
NUM_CLASSES = 1000


def load_splits(data_dir: str = "./data", train_n: int = 2048,
                test_n: int = 512, image_size: int = IMAGE_SIZE) -> Splits:
    np_dir = os.path.join(data_dir, "imagenet_npy")
    if not os.path.isdir(np_dir):
        # real-image path: a class-per-directory JPEG tree is decoded
        # ONCE into the mmap shard layout (data/imagenet_jpeg.py), then
        # every epoch streams from mmap with zero per-step decode cost
        from mpi_tensorflow_tpu.data import imagenet_jpeg

        if imagenet_jpeg.looks_like_tree(data_dir):
            if not imagenet_jpeg.available():
                # NEVER silently train on synthetic data when the user
                # pointed us at real images
                raise RuntimeError(
                    f"{data_dir} holds a class-per-directory image tree "
                    f"but Pillow (PIL) is not installed — install it or "
                    f"pre-convert to {np_dir} (.npy shards)")
            import jax

            fail_marker = f"{np_dir}.failed"
            if jax.process_index() == 0:
                if os.path.exists(fail_marker):
                    os.unlink(fail_marker)   # stale marker: retrying now
                print(f"[imagenet] decoding JPEG tree under {data_dir} "
                      f"-> {np_dir} (one-time)", flush=True)
                try:
                    imagenet_jpeg.ingest(data_dir, np_dir,
                                         image_size=image_size)
                except BaseException as e:
                    # commit a failure marker so the non-zero ranks
                    # polling below fail FAST instead of spinning out
                    # their 8-hour deadline on an ingest that died
                    try:
                        with open(fail_marker, "w") as f:
                            f.write(f"{type(e).__name__}: {e}\n")
                    except OSError:
                        pass         # marker is best-effort; still raise
                    raise
                else:
                    # a marker from a PREVIOUS failed attempt must not
                    # poison later runs once an ingest has succeeded
                    if os.path.exists(fail_marker):
                        os.unlink(fail_marker)
            else:
                # single-writer rule (same as the MNIST download):
                # process 0 ingests, everyone else waits for the ATOMIC
                # rename commit — a non-zero rank must never read a
                # half-written shard dir
                import time

                wait_start = time.time()
                deadline = wait_start + 8 * 3600
                marker_seen_absent = not os.path.exists(fail_marker)
                while not os.path.isdir(np_dir):
                    # a marker that APPEARS during this wait is this
                    # cohort's failure by construction: honor it
                    # immediately.  A marker already present when the
                    # wait began may be the PREVIOUS run's — process 0
                    # unlinks it the moment it starts — so honor it only
                    # after a 60s grace (covers a slow-starting rank 0
                    # on a quick supervisor restart).  The unlink can
                    # race every stat/read here; a vanished marker just
                    # means keep waiting.
                    try:
                        fresh = marker_seen_absent or \
                            time.time() - wait_start > 60.0
                        if fresh and os.path.exists(fail_marker):
                            with open(fail_marker) as f:
                                reason = f.read().strip()
                            raise RuntimeError(
                                f"process 0's JPEG ingest failed: "
                                f"{reason}")
                    except OSError:
                        pass
                    if not os.path.exists(fail_marker):
                        marker_seen_absent = True
                    if time.time() > deadline:
                        raise RuntimeError(
                            f"timed out waiting for process 0's JPEG "
                            f"ingest commit at {np_dir}")
                    time.sleep(5.0)
    if os.path.isdir(np_dir):
        tr_x = np.load(os.path.join(np_dir, "train_images.npy"), mmap_mode="r")
        meta_path = os.path.join(np_dir, "ingest_meta.json")
        if tr_x.shape[1] != image_size and os.path.exists(meta_path):
            # shards OUR JPEG ingest produced at another resolution must
            # not silently satisfy this run; user-provided shards (no
            # marker) are their own source of truth at any size — the
            # documented pre-processed-.npy contract.  (Every shipped
            # ingest writes the marker; marker-less dirs are by
            # construction user-provided.)
            raise ValueError(
                f"{np_dir} holds {tr_x.shape[1]}px auto-ingested shards "
                f"but this run wants {image_size}px — delete the dir to "
                f"re-ingest at the new size (serving the wrong "
                f"resolution silently would train a different model)")
        tr_y = np.load(os.path.join(np_dir, "train_labels.npy"))
        ts_x = np.load(os.path.join(np_dir, "val_images.npy"), mmap_mode="r")
        ts_y = np.load(os.path.join(np_dir, "val_labels.npy"))
        val_n = max(tr_x.shape[0] // 12, 1)
        return Splits(train_data=tr_x[val_n:], train_labels=tr_y[val_n:],
                      test_data=ts_x, test_labels=ts_y,
                      val_data=tr_x[:val_n], val_labels=tr_y[:val_n])
    return synthetic.image_classification(
        train_n, test_n, size=image_size, channels=3,
        num_classes=NUM_CLASSES)
