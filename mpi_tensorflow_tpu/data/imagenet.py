"""ImageNet-shaped pipeline (BASELINE.json config 4: ResNet-50 on v4-32).

ImageNet itself cannot be auto-downloaded; this module serves the benchmark
role with deterministic synthetic 224x224x3/1000-class data, and accepts a
user-provided directory of pre-processed ``.npy`` shards for real runs.
"""

from __future__ import annotations

import os

import numpy as np

from mpi_tensorflow_tpu.data.mnist import Splits
from mpi_tensorflow_tpu.data import synthetic

IMAGE_SIZE = 224
NUM_CLASSES = 1000


def load_splits(data_dir: str = "./data", train_n: int = 2048,
                test_n: int = 512, image_size: int = IMAGE_SIZE) -> Splits:
    np_dir = os.path.join(data_dir, "imagenet_npy")
    if os.path.isdir(np_dir):
        tr_x = np.load(os.path.join(np_dir, "train_images.npy"), mmap_mode="r")
        tr_y = np.load(os.path.join(np_dir, "train_labels.npy"))
        ts_x = np.load(os.path.join(np_dir, "val_images.npy"), mmap_mode="r")
        ts_y = np.load(os.path.join(np_dir, "val_labels.npy"))
        val_n = max(tr_x.shape[0] // 12, 1)
        return Splits(train_data=tr_x[val_n:], train_labels=tr_y[val_n:],
                      test_data=ts_x, test_labels=ts_y,
                      val_data=tr_x[:val_n], val_labels=tr_y[:val_n])
    return synthetic.image_classification(
        train_n, test_n, size=image_size, channels=3,
        num_classes=NUM_CLASSES)
