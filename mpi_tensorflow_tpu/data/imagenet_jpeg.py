"""Real-image (JPEG) ingestion for the ImageNet-layout directory tree.

The reference feeds pre-parsed arrays through feed_dict (mpipy.py:80-85)
and has no image-decode pipeline at all; config 4 (ResNet-50/"ImageNet",
BASELINE.json) needs one.  This module ingests the standard ImageNet
directory layout

    root/train/<class_name>/*.JPEG
    root/val/<class_name>/*.JPEG        (val/ optional: a fraction of
                                         train is carved when absent)

into the mmap ``.npy`` shard format ``data/imagenet.py`` already serves
(``imagenet_npy/{train,val}_{images,labels}.npy``) — decode once, then
every epoch streams straight from page-cache-backed mmap through the
native/thread prefetcher with zero per-step decode cost (the bench mode
``--mode hostio`` measures exactly that feed).

Decode/preprocess is the standard eval transform: shorter side to
``resize_to`` (bilinear), center-crop ``image_size``, float32 in [0, 1],
channel-normalized by the ImageNet mean/std.  Pure PIL + numpy; PIL is
gated so the module imports (and everything else keeps working) on boxes
without it.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)
_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def _pil():
    try:
        from PIL import Image

        return Image
    except ImportError as e:                 # pragma: no cover
        raise RuntimeError(
            "JPEG ingestion needs Pillow (PIL); install it or "
            "pre-convert to the imagenet_npy .npy shard format") from e


def available() -> bool:
    try:
        import PIL  # noqa: F401

        return True
    except ImportError:                      # pragma: no cover
        return False


def _image_class_dirs(base: str) -> list:
    """Subdirectories of ``base`` that contain at least one image."""
    out = []
    if not os.path.isdir(base):
        return out
    for d in sorted(os.listdir(base)):
        cdir = os.path.join(base, d)
        if not os.path.isdir(cdir) or d.startswith((".", "imagenet_npy")):
            continue
        if any(f.lower().endswith(_EXTS) for f in os.listdir(cdir)):
            out.append(d)
    return out


def looks_like_tree(root: str) -> bool:
    """Whether ``root`` (or ``root/train``) is a class-per-directory
    image tree — the auto-ingest trigger in data/imagenet.load_splits.
    Requires at least TWO image-bearing class directories: a single
    stray image-holding subdir (a figures/ folder in a shared ./data)
    must not trigger an hours-long bogus ingest."""
    return (len(_image_class_dirs(os.path.join(root, "train"))) >= 2
            or len(_image_class_dirs(root)) >= 2)


def scan_tree(split_dir: str,
              class_to_id: Optional[dict] = None,
              classes: Optional[list] = None) -> tuple[list, list]:
    """Class-per-directory scan: returns (paths, labels) with label ids
    assigned by SORTED class-directory name — deterministic across
    hosts, the property per-host sharding relies on.  Only directories
    that actually CONTAIN an image count as classes (an empty or
    non-image dir must not consume a label id), and the ingest output /
    hidden / tmp dirs never do.

    ``class_to_id``: an existing name -> id map (the TRAIN split''s) —
    the val split must label with the train map, never its own sort
    order (a class-set mismatch between splits would silently misalign
    every val label); unknown val classes fail loudly."""
    if classes is None:
        classes = _image_class_dirs(split_dir)
    if class_to_id is None:
        class_to_id = {c: i for i, c in enumerate(classes)}
    paths, labels = [], []
    for cname in classes:
        if cname not in class_to_id:
            raise ValueError(
                f"class directory {cname!r} in {split_dir} does not "
                f"exist in the training split — the label maps would "
                f"silently diverge")
        li = class_to_id[cname]
        cdir = os.path.join(split_dir, cname)
        for fname in sorted(os.listdir(cdir)):
            if fname.lower().endswith(_EXTS):
                paths.append(os.path.join(cdir, fname))
                labels.append(li)
    return paths, labels


def decode_image(path: str, image_size: int, resize_to: Optional[int] = None
                 ) -> np.ndarray:
    """One image -> (image_size, image_size, 3) float32, normalized."""
    Image = _pil()
    resize_to = resize_to or max(image_size, int(image_size * 256 / 224))
    with Image.open(path) as im:
        im = im.convert("RGB")
        w, h = im.size
        scale = resize_to / min(w, h)
        im = im.resize((max(1, round(w * scale)), max(1, round(h * scale))),
                       Image.BILINEAR)
        w, h = im.size
        left, top = (w - image_size) // 2, (h - image_size) // 2
        im = im.crop((left, top, left + image_size, top + image_size))
        x = np.asarray(im, np.float32) / 255.0
    return (x - IMAGENET_MEAN) / IMAGENET_STD


def _decoded(paths: list, image_size: int, workers: int):
    """Decoded images in path order — a process pool when it pays (the
    one-time full-ImageNet conversion is hours single-threaded on a
    many-core host), serial otherwise/on pool failure."""
    import functools

    if workers > 1 and len(paths) >= 64:
        ex = None
        try:
            from concurrent.futures import ProcessPoolExecutor

            ex = ProcessPoolExecutor(max_workers=workers)
        except OSError:                      # pragma: no cover
            ex = None                        # no sem/fork: serial below
        if ex is not None:
            # decode errors propagate from here — they must NOT be
            # caught and retried serially (a mid-stream restart would
            # write duplicate images at shifted memmap rows)
            with ex:
                yield from ex.map(
                    functools.partial(decode_image, image_size=image_size),
                    paths, chunksize=32)
            return
    for p in paths:
        yield decode_image(p, image_size)


def _ingest_split(paths: list, labels: list, out_dir: str, prefix: str,
                  image_size: int, log_every: int = 500,
                  workers: int | None = None) -> None:
    n = len(paths)
    workers = workers if workers is not None else (os.cpu_count() or 1)
    imgs = np.lib.format.open_memmap(
        os.path.join(out_dir, f"{prefix}_images.npy"), mode="w+",
        dtype=np.float32, shape=(n, image_size, image_size, 3))
    for i, x in enumerate(_decoded(paths, image_size, workers)):
        imgs[i] = x
        if log_every and (i + 1) % log_every == 0:
            print(f"[imagenet_jpeg] {prefix}: {i + 1}/{n} decoded",
                  flush=True)
    imgs.flush()
    del imgs
    np.save(os.path.join(out_dir, f"{prefix}_labels.npy"),
            np.asarray(labels, np.int64))


def _shuffled(paths: list, labels: list, seed: int) -> tuple[list, list]:
    """Seeded global permutation of (paths, labels), applied BEFORE the
    shards are written: scan_tree emits strictly class-sorted order, and
    a class-sorted train shard would make every per-device block and the
    head-of-shard val carve (data/imagenet.py load_splits) single-class.
    Seeded so every host that ingests the same tree writes the same
    shard order (the per-host sharding determinism contract)."""
    perm = np.random.default_rng(seed).permutation(len(paths))
    return [paths[i] for i in perm], [labels[i] for i in perm]


def ingest(root: str, out_dir: Optional[str] = None,
           image_size: int = 224, val_fraction: float = 0.04,
           shuffle_seed: int = 0) -> str:
    """Decode a class-per-directory JPEG tree into the mmap `.npy` shard
    layout ``data/imagenet.load_splits`` serves.  Returns ``out_dir``.

    ``root`` may contain ``train/``+``val/`` split subdirectories, or be
    a flat class-per-directory tree (then every ``1/val_fraction``-th
    image, round-robin per class order, becomes the val split — a
    deterministic carve, no RNG).

    Shard order: a seeded global permutation is applied to every split
    before writing (``shuffle_seed``), so per-device blocks and the val
    carve in data/imagenet.py are class-balanced instead of inheriting
    scan_tree's class-sorted order.
    """
    out_dir = out_dir or os.path.join(root, "imagenet_npy")
    train_dir = os.path.join(root, "train")
    val_dir = os.path.join(root, "val")
    def carve(paths, labels):
        """Deterministic every-k-th val carve — images leave the train
        split (never copied: the val shard serves as TEST data and must
        not overlap training)."""
        k = max(2, int(round(1.0 / max(val_fraction, 1e-6))))
        tr = [(p, l) for i, (p, l) in enumerate(zip(paths, labels))
              if i % k]
        va = [(p, l) for i, (p, l) in enumerate(zip(paths, labels))
              if not i % k]
        return ([p for p, _ in tr], [l for _, l in tr],
                [p for p, _ in va], [l for _, l in va])

    if os.path.isdir(train_dir):
        # ONE label map, owned by the train split; val labels through it
        # (one listing pass: scan_tree reuses the class list)
        train_classes = _image_class_dirs(train_dir)
        cmap = {c: i for i, c in enumerate(train_classes)}
        tr_p, tr_l = _shuffled(*scan_tree(train_dir, cmap,
                                          classes=train_classes),
                               seed=shuffle_seed)
        va_p, va_l = [], []
        if os.path.isdir(val_dir):
            va_p, va_l = _shuffled(*scan_tree(val_dir, cmap),
                                   seed=shuffle_seed + 1)
        if not va_p:
            # no val/, or a val/ without class-per-directory structure
            # (the standard ImageNet val tarball extracts FLAT, with
            # labels in a separate devkit file we cannot infer):
            # committing a zero-row val shard would permanently serve an
            # empty test split — carve from train instead, loudly
            print(f"[imagenet_jpeg] no class-per-directory val split "
                  f"under {root}: carving a deterministic "
                  f"{val_fraction:.0%} of train as val", flush=True)
            tr_p, tr_l, va_p, va_l = carve(tr_p, tr_l)
    else:
        paths, labels = _shuffled(*scan_tree(root), seed=shuffle_seed)
        tr_p, tr_l, va_p, va_l = carve(paths, labels)
    if not tr_p:
        raise ValueError(f"no images found under {root!r} "
                         f"(expected class-per-directory *.jpeg)")
    gb = (len(tr_p) + len(va_p)) * image_size * image_size * 3 * 4 / 1e9
    print(f"[imagenet_jpeg] decoding {len(tr_p)}+{len(va_p)} images -> "
          f"~{gb:.1f} GB of float32 .npy shards under {out_dir}",
          flush=True)
    # ATOMIC commit: decode into a tmp dir and rename into place —
    # out_dir's existence is load_splits' done-marker, so a crashed or
    # interrupted ingest must leave nothing behind (a half-written shard
    # dir would permanently shadow both re-ingest and the synthetic
    # fallback)
    tmp = f"{out_dir}.tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    try:
        _ingest_split(tr_p, tr_l, tmp, "train", image_size)
        _ingest_split(va_p, va_l, tmp, "val", image_size)
        import json

        with open(os.path.join(tmp, "ingest_meta.json"), "w") as f:
            # provenance marker: load_splits enforces resolution ONLY on
            # shards OUR ingest produced — user-provided pre-processed
            # shards are their own source of truth at any size
            json.dump({"image_size": image_size,
                       "train_n": len(tr_p), "val_n": len(va_p)}, f)
        try:
            os.rename(tmp, out_dir)
        except OSError:
            if not os.path.isdir(out_dir):
                # NOT the concurrent-writer race: nothing committed the
                # destination, so this ingest genuinely failed to land —
                # swallowing it would silently fall through to synthetic
                # data (load_splits treats the dir as the done-marker)
                raise
            # a concurrent writer committed first: theirs is complete
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return out_dir
