"""recompile-hazard pass: the zero-recompile contract, statically.

The engine's dispatch discipline (engine.py: pow2-bucketed shapes, jit
caches warmed at build) is pinned at RUNTIME by the
``compile_counts()`` probes; this pass is the static complement — it
catches the three ways a PR reintroduces steady-state recompiles
before any test runs:

- ``JIT-BRANCH`` — a Python ``if``/``while`` on a traced argument
                   inside a function reachable from a ``jax.jit`` /
                   ``shard_map`` call site.  Trace-time-static forms
                   are exempt: ``is None`` pytree-structure checks,
                   ``isinstance``, and ``.shape``/``.ndim``/``.dtype``/
                   ``len()`` accesses (static under tracing).
- ``JIT-LOOP``   — ``jax.jit``/``pjit``/``shard_map`` CONSTRUCTED
                   inside a loop body: each iteration builds a fresh
                   callable with a fresh cache.  Intentional compile
                   probes allowlist with ``# graft-lint: jit-ok(...)``.
- ``JIT-SHAPE``  — a dispatch-buffer shape (``np.zeros`` family) in
                   ``serving/`` built from a raw ``len(...)`` instead
                   of the pow2 bucket helpers (``pow2_ceil`` /
                   ``_bucket`` in serving/engine.py): request-length-
                   dependent shapes compile once per distinct length.

The mixed-batch fused dispatch (engine._step_mixed) passes clean under
these rules as shipped: every axis of its ("mixed", B, S, NB) shape
routes through ``_bucket``, and its jit entry is built once at engine
construction with the grid pre-warmed — so the shipped baseline stays
``{}``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from mpi_tensorflow_tpu.analysis import core

PASS_IDS = ("JIT-BRANCH", "JIT-LOOP", "JIT-SHAPE")

JIT_CTORS = {"jax.jit", "jit", "pjit", "jax.pjit"}
SHARD_CTORS = {"jax.shard_map", "shard_map",
               "jax.experimental.shard_map.shard_map"}
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}
STATIC_CALLS = {"isinstance", "len", "type"}
ARRAY_CTORS = {"zeros", "ones", "empty", "full"}
ARRAY_MODULES = {"np", "jnp", "numpy", "onp"}


def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _enclosing_class(node: ast.AST, parents) -> Optional[ast.ClassDef]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = parents.get(cur)
    return None


def _resolve(expr: ast.AST, tree: ast.Module, parents,
             site: ast.AST, depth: int = 0) -> Optional[ast.AST]:
    """Best-effort resolution of a jit/shard_map first argument to a
    function definition in the same module (one assignment /
    ``functools.partial`` / ``shard_map`` hop deep)."""
    if depth > 4:
        return None
    if isinstance(expr, ast.Lambda):
        return expr
    if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                      ast.Name) \
            and expr.value.id == "self":
        cls = _enclosing_class(site, parents)
        if cls is not None:
            return core.find_function(cls, expr.attr)
        return None
    if isinstance(expr, ast.Call):
        name = core.dotted_name(expr.func)
        if name in (JIT_CTORS | SHARD_CTORS
                    | {"functools.partial", "partial"}) and expr.args:
            return _resolve(expr.args[0], tree, parents, site, depth + 1)
        return None
    if isinstance(expr, ast.Name):
        fn = core.find_function(tree, expr.id)
        if fn is not None:
            return fn
        # name = jax.shard_map(f, ...) / functools.partial(f, ...)
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == expr.id
                            for t in node.targets):
                return _resolve(node.value, tree, parents, site,
                                depth + 1)
    return None


def _jit_roots(tree: ast.Module, parents) -> Iterable[ast.AST]:
    """Function definitions reachable from jit/shard_map call sites or
    carrying a jit decorator."""
    seen: Set[int] = set()
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Call) \
                and core.dotted_name(node.func) in (JIT_CTORS
                                                    | SHARD_CTORS) \
                and node.args:
            target = _resolve(node.args[0], tree, parents, node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                base = dec.func if isinstance(dec, ast.Call) else dec
                if core.dotted_name(base) in JIT_CTORS:
                    target = node
        if target is not None and id(target) not in seen:
            seen.add(id(target))
            yield target


def _branch_sites(fn: ast.AST):
    """Yield ``(branch_node, traced_names)`` where the traced set is
    the params of the branch's lexical ANCESTOR functions (the jit root
    plus closure-capturing nested defs — ``lax.scan`` bodies etc.).
    Sibling/descendant defs are excluded: a param name in a nested def
    shadows only its own body, and counting it at the outer branch
    false-positives on closure-captured static config values."""

    def visit(node: ast.AST, scope: Set[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            scope = scope | set(core.arg_names(node))
        if isinstance(node, (ast.If, ast.While)):
            yield node, scope
        for child in ast.iter_child_nodes(node):
            yield from visit(child, scope)

    yield from visit(fn, set())


def _value_branches(test: ast.AST, traced: Set[str]) -> List[str]:
    """Traced names whose Python VALUE the test depends on, skipping
    trace-time-static subexpressions."""
    hits: List[str] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Attribute) \
                and node.attr in STATIC_ATTRS:
            return                      # x.shape / x.dtype: static
        if isinstance(node, ast.Call) \
                and core.dotted_name(node.func) in STATIC_CALLS:
            return                      # isinstance/len/type: static
        if isinstance(node, ast.Compare) \
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops):
            return                      # `x is None`: pytree structure
        if isinstance(node, ast.Name) \
                and isinstance(node.ctx, ast.Load) \
                and node.id in traced:
            hits.append(node.id)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(test)
    return hits


def _len_bound_names(fn: ast.AST) -> Set[str]:
    """Names assigned from a bare ``len(...)`` in this function."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and core.dotted_name(node.value.func) == "len":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def run(sources: Dict[str, str]) -> List[core.Finding]:
    findings: List[core.Finding] = []
    trees = core.parse_sources(sources)
    for rel, tree in trees.items():
        src = sources[rel]
        parents = _parents(tree)

        # --- JIT-BRANCH: value branching inside jit-reachable fns ---
        for fn in _jit_roots(tree, parents):
            for node, traced in _branch_sites(fn):
                for name in sorted(set(_value_branches(node.test,
                                                       traced))):
                    if core.allowlist_reason(src, node.lineno, "jit"):
                        continue
                    findings.append(core.Finding(
                        rel, node.lineno, "JIT-BRANCH",
                        f"branch on traced argument {name!r} inside a "
                        f"jitted function (recompiles per Python "
                        f"value; hoist or use lax.cond/jnp.where)"))

        # --- JIT-LOOP: jit construction inside loop bodies ---
        for node in ast.walk(tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            for sub in ast.walk(node):
                if sub is node:
                    continue
                if isinstance(sub, ast.Call) \
                        and core.dotted_name(sub.func) in (JIT_CTORS
                                                           | SHARD_CTORS):
                    if core.allowlist_reason(src, sub.lineno, "jit"):
                        continue
                    findings.append(core.Finding(
                        rel, sub.lineno, "JIT-LOOP",
                        f"{core.dotted_name(sub.func)} constructed "
                        f"inside a loop body: every iteration builds "
                        f"a fresh callable with an empty compile "
                        f"cache"))

        # --- JIT-SHAPE: unbucketed dispatch shapes in serving/ ---
        if "serving/" not in rel:
            continue
        for fn in core.iter_functions(tree):
            len_names = _len_bound_names(fn)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                name = core.dotted_name(node.func)
                if name is None or "." not in name:
                    continue
                mod, _, ctor = name.rpartition(".")
                if ctor not in ARRAY_CTORS \
                        or mod.split(".")[0] not in ARRAY_MODULES:
                    continue
                shape = node.args[0]
                elts = (shape.elts if isinstance(shape, ast.Tuple)
                        else [shape])
                for el in elts:
                    raw = (isinstance(el, ast.Call)
                           and core.dotted_name(el.func) == "len") \
                        or (isinstance(el, ast.Name)
                            and el.id in len_names)
                    if not raw:
                        continue
                    if core.allowlist_reason(src, node.lineno, "jit"):
                        continue
                    findings.append(core.Finding(
                        rel, node.lineno, "JIT-SHAPE",
                        f"dispatch buffer shaped by a raw length in "
                        f"{name}: route it through the pow2 bucket "
                        f"helpers (engine.pow2_ceil/_bucket) or the "
                        f"shape recompiles per distinct length"))
    return findings
