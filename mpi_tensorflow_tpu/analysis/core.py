"""Shared plumbing for the graft-lint passes.

Every pass is a module with ``run(sources) -> List[Finding]`` where
``sources`` maps repo-relative paths (forward slashes) to file text.
Passes locate the files they care about by CONTENT (e.g. "the module
defining ``class ServeConfig``"), not by hardcoded paths, so the test
fixtures can feed small synthetic trees through the exact production
code path.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterator, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    """One ``file:line: PASS-ID message`` diagnostic."""
    file: str
    line: int
    pass_id: str
    message: str

    def format(self) -> str:
        return f"{self.file}:{self.line}: {self.pass_id} {self.message}"

    @property
    def baseline_key(self) -> str:
        """Suppressions are counted per (pass, file) — coarse enough to
        survive line churn, fine enough that a NEW violation in a file
        with no budget fails immediately."""
        return f"{self.pass_id}:{self.file}"


def repo_root() -> str:
    """The repository root (parent of the package directory)."""
    here = os.path.dirname(os.path.abspath(__file__))     # .../analysis
    return os.path.dirname(os.path.dirname(here))         # repo


def load_sources(root: Optional[str] = None) -> Dict[str, str]:
    """Package sources + the repo-root entry points (bench.py consumes
    serve knobs directly, so the knob-bridge dead-field check must see
    it).  Keys are repo-relative with forward slashes."""
    root = root or repo_root()
    pkg = os.path.join(root, "mpi_tensorflow_tpu")
    out: Dict[str, str] = {}
    for base, _dirs, files in os.walk(pkg):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(base, name)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as fh:
                out[rel] = fh.read()
    for extra in ("bench.py",):
        path = os.path.join(root, extra)
        if os.path.exists(path):
            with open(path, encoding="utf-8") as fh:
                out[extra] = fh.read()
    return out


def parse_sources(sources: Dict[str, str]) -> Dict[str, ast.Module]:
    """Parse every source, skipping files that do not parse (the names
    pass would drown in noise on a syntax error the interpreter will
    report anyway)."""
    out: Dict[str, ast.Module] = {}
    for rel, text in sources.items():
        try:
            out[rel] = ast.parse(text, filename=rel)
        except SyntaxError:
            continue
    return out


_ALLOW_RE = re.compile(r"#\s*graft-lint:\s*([a-z-]+)-ok\(([^)]*)\)")


def allowlist_reason(source: str, lineno: int, tag: str) -> Optional[str]:
    """Return the ``# graft-lint: <tag>-ok(<reason>)`` reason covering
    ``lineno``, or None.  The marker may sit on the flagged line itself
    or on the line directly above it (long lines push it up)."""
    lines = source.splitlines()
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = _ALLOW_RE.search(lines[ln - 1])
            if m and m.group(1) == tag:
                return m.group(2) or "unspecified"
    return None


def iter_functions(tree: ast.AST) -> Iterator[ast.AST]:
    """Every function/async-function definition in ``tree``."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def find_class(trees: Dict[str, ast.Module],
               name: str) -> Optional[Tuple[str, ast.ClassDef]]:
    """Locate ``class <name>`` anywhere in the parsed sources."""
    for rel, tree in trees.items():
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == name:
                return rel, node
    return None


def find_function(tree: ast.AST, name: str) -> Optional[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.jit`` -> "jax.jit", ``jit`` -> "jit", else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def arg_names(fn: ast.AST) -> List[str]:
    """Positional + keyword parameter names of a def/lambda, minus
    ``self``/``cls``."""
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]
