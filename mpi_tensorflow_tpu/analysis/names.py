"""names pass: undefined names and unused imports, pyflakes-style.

The reference repo class of bug this kills: an exception handler
referencing a ``DownloadError`` that was never imported — dead until
the one rainy day it runs, then a ``NameError`` on top of the real
failure.  The check is deliberately conservative (one flat binding
scope per module: every Store/def/import/arg anywhere counts), so it
can miss cross-scope mistakes but cannot false-positive on forward
references or method-order tricks.

- ``NAMES-UNDEF``  — a loaded name bound nowhere in the module and not
                     a builtin.
- ``NAMES-IMPORT`` — an import binding no code in the module loads
                     (``__init__.py`` re-export surfaces are skipped;
                     ``# noqa`` or ``# graft-lint: name-ok(...)`` on
                     the import line opts out).
"""

from __future__ import annotations

import ast
import builtins
from typing import Dict, List, Set, Tuple

from mpi_tensorflow_tpu.analysis import core

PASS_IDS = ("NAMES-UNDEF", "NAMES-IMPORT")

_BUILTINS = set(dir(builtins)) | {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__builtins__", "__debug__", "__class__", "__loader__",
}


def _module_bindings(tree: ast.Module) -> Set[str]:
    bound: Set[str] = set()
    star_import = False
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            bound.add(node.name)
            bound |= set(core.arg_names(node)) \
                if not isinstance(node, ast.ClassDef) else set()
            if not isinstance(node, ast.ClassDef):
                bound |= {"self", "cls"}
        elif isinstance(node, ast.Lambda):
            bound |= set(core.arg_names(node)) | {"self", "cls"}
        elif isinstance(node, ast.Import):
            for alias in node.names:
                bound.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "*":
                    star_import = True
                else:
                    bound.add(alias.asname or alias.name)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
        elif isinstance(node, ast.Global):
            bound |= set(node.names)
        elif isinstance(node, ast.Nonlocal):
            bound |= set(node.names)
    if star_import:
        bound.add("*")
    return bound


def _loads(tree: ast.Module) -> List[Tuple[str, int]]:
    return [(n.id, n.lineno) for n in ast.walk(tree)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)]


def _dunder_all(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "__all__"
                        for t in node.targets) \
                and isinstance(node.value, (ast.List, ast.Tuple)):
            out |= {e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
    return out


def _line_opts_out(src_lines: List[str], lineno: int) -> bool:
    if not 1 <= lineno <= len(src_lines):
        return False
    line = src_lines[lineno - 1]
    return "noqa" in line or "graft-lint: name-ok(" in line


def run(sources: Dict[str, str]) -> List[core.Finding]:
    findings: List[core.Finding] = []
    trees = core.parse_sources(sources)
    for rel, tree in trees.items():
        src_lines = sources[rel].splitlines()
        bound = _module_bindings(tree)
        loads = _loads(tree)
        loaded_names = {n for n, _ in loads}
        exported = _dunder_all(tree)

        # --- undefined names (skip under a star import: bindings
        #     unknown) ---
        if "*" not in bound:
            seen: Set[Tuple[str, int]] = set()
            for name, lineno in loads:
                if name in bound or name in _BUILTINS:
                    continue
                if (name, lineno) in seen \
                        or _line_opts_out(src_lines, lineno):
                    continue
                seen.add((name, lineno))
                findings.append(core.Finding(
                    rel, lineno, "NAMES-UNDEF",
                    f"name {name!r} is loaded but bound nowhere in "
                    f"this module (NameError waiting for this path "
                    f"to run)"))

        # --- unused imports (re-export surfaces excluded) ---
        if rel.endswith("__init__.py"):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                binding = alias.asname or alias.name.split(".")[0]
                if isinstance(node, ast.ImportFrom) \
                        and alias.asname is None:
                    binding = alias.name
                if binding in loaded_names or binding in exported:
                    continue
                line = getattr(alias, "lineno", node.lineno)
                if _line_opts_out(src_lines, line):
                    continue
                findings.append(core.Finding(
                    rel, line, "NAMES-IMPORT",
                    f"import {binding!r} is never used in this "
                    f"module"))
    return findings
