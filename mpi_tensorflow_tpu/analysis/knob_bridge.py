"""knob-bridge pass: the ``--serve-*`` CLI surface must bridge.

Every serve knob crosses three layers — argparse flag, Config field,
downstream consumer (``ServeConfig.from_config`` / ``WorkloadSpec`` /
the router) — and CHANGES.md has hand-checked that bridge in every PR
since the serving engine landed.  This pass mechanizes it:

- ``KNOB-FLAG``  — a ``--serve-*`` flag with no Config field, a flag
                   parsed but never wired through ``config_from_args``,
                   or a ``serve_*`` Config field with no flag.
- ``KNOB-GUARD`` — a knob missing validation at any of the three
                   layers: argparse (``choices=`` or ``type=``), the
                   fail-fast guards in ``cli.main``, and the downstream
                   consumer's ``__post_init__`` (or the router's
                   constructor guard for the fleet size).
- ``KNOB-DEAD``  — a ``serve_*`` Config field nothing ever reads.

The pass discovers files by content (the module defining
``build_parser``, the ``Config`` / ``ServeConfig`` / ``WorkloadSpec`` /
``ReplicaRouter`` classes), so fixture trees exercise the same code.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from mpi_tensorflow_tpu.analysis import core

PASS_IDS = ("KNOB-FLAG", "KNOB-GUARD", "KNOB-DEAD")

#: serve knobs whose downstream validation layer is NOT
#: ``ServeConfig.__post_init__`` (they never enter ``from_config``):
#: field -> (consumer class, validated attr on it, or None meaning
#: "constructor must guard by raising").  Keep this table current —
#: a serve field in neither ``from_config`` nor here is itself a
#: KNOB-GUARD finding.
EXTRA_BRIDGES: Dict[str, Tuple[str, Optional[str]]] = {
    "serve_workload": ("WorkloadSpec", "workload"),
    "serve_slo_ms": ("WorkloadSpec", "slo_ms"),
    "serve_replicas": ("ReplicaRouter", None),
}


def _find_cli(trees: Dict[str, ast.Module]) -> Optional[Tuple[str,
                                                              ast.Module]]:
    for rel, tree in trees.items():
        if core.find_function(tree, "build_parser") is not None \
                and core.find_function(tree, "main") is not None:
            return rel, tree
    return None


def _serve_flags(cli_tree: ast.Module) -> Dict[str, dict]:
    """``dest -> {flag, line, kwargs}`` for every --serve-* flag."""
    parser_fn = core.find_function(cli_tree, "build_parser")
    flags: Dict[str, dict] = {}
    for node in ast.walk(parser_fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument" and node.args):
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and first.value.startswith("--serve-")):
            continue
        dest = first.value[2:].replace("-", "_")
        flags[dest] = {
            "flag": first.value,
            "line": node.lineno,
            "kwargs": {kw.arg for kw in node.keywords if kw.arg},
        }
    return flags


def _config_fields(trees) -> Optional[Tuple[str, Dict[str, int]]]:
    loc = core.find_class(trees, "Config")
    if loc is None:
        return None
    rel, cls = loc
    fields = {}
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                          ast.Name):
            fields[node.target.id] = node.lineno
    return rel, fields


def _wired_kwargs(cli_tree: ast.Module) -> Set[str]:
    """Keyword names passed to ``Config(...)`` in ``config_from_args``."""
    fn = core.find_function(cli_tree, "config_from_args")
    if fn is None:
        return set()
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and core.dotted_name(node.func) == "Config":
            out |= {kw.arg for kw in node.keywords if kw.arg}
    return out


def _main_guarded(cli_tree: ast.Module) -> Set[str]:
    """``serve_*`` attrs referenced inside ``if`` tests in ``main`` —
    the fail-fast guard layer."""
    fn = core.find_function(cli_tree, "main")
    out: Set[str] = set()
    if fn is None:
        return out
    for node in ast.walk(fn):
        if isinstance(node, ast.If):
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Attribute) \
                        and sub.attr.startswith("serve_"):
                    out.add(sub.attr)
    return out


def _from_config_map(trees) -> Dict[str, str]:
    """``serve_* Config field -> ServeConfig field`` parsed from the
    ``from_config`` bridge (the keyword mapping is THE bridge — parsing
    it rather than hardcoding it is the point of this pass)."""
    loc = core.find_class(trees, "ServeConfig")
    if loc is None:
        return {}
    _rel, cls = loc
    fn = core.find_function(cls, "from_config")
    if fn is None:
        return {}
    mapping: Dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg and isinstance(kw.value, ast.Attribute) \
                        and kw.value.attr.startswith("serve_"):
                    mapping[kw.value.attr] = kw.arg
    return mapping


def _post_init_validated(trees, class_name: str) -> Optional[Set[str]]:
    """Attrs referenced in ``if`` tests inside ``__post_init__`` of
    ``class_name`` (``self.x`` or bare dataclass-field names)."""
    loc = core.find_class(trees, class_name)
    if loc is None:
        return None
    _rel, cls = loc
    fn = core.find_function(cls, "__post_init__")
    if fn is None:
        return set()
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.If):
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Attribute):
                    out.add(sub.attr)
                elif isinstance(sub, ast.Name):
                    out.add(sub.id)
    return out


def _ctor_raises(trees, class_name: str) -> bool:
    loc = core.find_class(trees, class_name)
    if loc is None:
        return False
    _rel, cls = loc
    fn = core.find_function(cls, "__init__")
    return fn is not None and any(isinstance(n, ast.Raise)
                                  for n in ast.walk(fn))


def _consumed_fields(trees, skip_files: Set[str]) -> Set[str]:
    """Every ``.serve_*`` attribute READ outside the cli/config
    modules (bench.py resolves unset bench knobs through them; the
    ``from_config`` bridge is counted separately)."""
    out: Set[str] = set()
    for rel, tree in trees.items():
        if rel in skip_files:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.attr.startswith("serve_"):
                out.add(node.attr)
    return out


def run(sources: Dict[str, str]) -> List[core.Finding]:
    trees = core.parse_sources(sources)
    cli = _find_cli(trees)
    cfg = _config_fields(trees)
    if cli is None or cfg is None:
        return []           # not a tree this pass applies to
    cli_rel, cli_tree = cli
    cfg_rel, fields = cfg
    serve_fields = {k: v for k, v in fields.items()
                    if k.startswith("serve_")}
    flags = _serve_flags(cli_tree)
    wired = _wired_kwargs(cli_tree)
    guarded = _main_guarded(cli_tree)
    bridge = _from_config_map(trees)
    serve_cfg_validated = _post_init_validated(trees, "ServeConfig")
    findings: List[core.Finding] = []

    def add(pass_id, file, line, msg):
        findings.append(core.Finding(file, line, pass_id, msg))

    # --- flag <-> field <-> construction wiring ---
    for dest, info in flags.items():
        if dest not in fields:
            add("KNOB-FLAG", cli_rel, info["line"],
                f"{info['flag']} has no Config field {dest!r}")
        if dest not in wired:
            add("KNOB-FLAG", cli_rel, info["line"],
                f"{info['flag']} parsed but never wired into Config "
                f"(config_from_args drops it)")
    for field, line in serve_fields.items():
        if field not in flags:
            add("KNOB-FLAG", cfg_rel, line,
                f"Config.{field} has no --serve-* flag")

    # --- three-layer validation ---
    main_fn = core.find_function(cli_tree, "main")
    main_line = main_fn.lineno if main_fn else 1
    for field, line in serve_fields.items():
        info = flags.get(field)
        if info is not None and not ({"choices", "type"}
                                     & info["kwargs"]):
            add("KNOB-GUARD", cli_rel, info["line"],
                f"{info['flag']} has no argparse-level validation "
                f"(neither choices= nor type=)")
        if field not in guarded:
            add("KNOB-GUARD", cli_rel, main_line,
                f"Config.{field} has no cli.main guard (programmatic "
                f"Config construction bypasses argparse choices)")
        # downstream layer
        if field in bridge:
            target = bridge[field]
            if serve_cfg_validated is not None \
                    and target not in serve_cfg_validated:
                add("KNOB-GUARD", cfg_rel, line,
                    f"Config.{field} maps to ServeConfig.{target}, "
                    f"which ServeConfig.__post_init__ never validates")
        elif field in EXTRA_BRIDGES:
            cls_name, attr = EXTRA_BRIDGES[field]
            if attr is None:
                if not _ctor_raises(trees, cls_name):
                    add("KNOB-GUARD", cfg_rel, line,
                        f"Config.{field}: {cls_name} constructor has "
                        f"no guard (expected a raising check)")
            else:
                validated = _post_init_validated(trees, cls_name)
                if validated is not None and attr not in validated:
                    add("KNOB-GUARD", cfg_rel, line,
                        f"Config.{field} maps to {cls_name}.{attr}, "
                        f"which its __post_init__ never validates")
        else:
            add("KNOB-GUARD", cfg_rel, line,
                f"Config.{field} reaches neither ServeConfig."
                f"from_config nor the EXTRA_BRIDGES table — no "
                f"downstream validation layer is checked")

    # --- dead fields ---
    consumed = _consumed_fields(trees, skip_files={cli_rel, cfg_rel})
    for field, line in serve_fields.items():
        if field not in bridge and field not in consumed:
            add("KNOB-DEAD", cfg_rel, line,
                f"Config.{field} is never consumed (not in "
                f"ServeConfig.from_config, never read outside "
                f"cli/config)")
    return findings
