"""graft-lint: project-specific static analysis over the package AST.

Mechanizes the cross-cutting contracts every PR has hand-enforced
since PR 1 (stdlib ``ast`` only — no new dependencies):

- ``knob_bridge``   — every ``--serve-*`` CLI flag bridges to a Config
                      field and is validated at argparse, ``cli.main``,
                      AND its downstream consumer (ServeConfig /
                      WorkloadSpec / router); no dead knobs.
- ``jit_stability`` — the zero-steady-state-recompile contract's static
                      half: no Python-value branching on traced args
                      inside jit/shard_map-reachable functions, no
                      jit construction in loop bodies, no dispatch
                      shapes built from raw (non-pow2-bucketed)
                      request lengths.
- ``host_sync``     — no implicit device->host syncs (``int()`` /
                      ``float()`` / ``bool()`` / ``.item()`` /
                      ``np.asarray`` on jitted-call results) in the
                      serving hot loop, except sites allowlisted with
                      ``# graft-lint: sync-ok(<reason>)``.
- ``locks``         — the ``_GUARDED_BY`` declaration convention: every
                      access to a guarded attribute is lexically inside
                      ``with self._lock`` (the PR 7 sticky-map race
                      class, caught at lint time).
- ``names``         — pyflakes-style undefined-name / unused-import
                      sweep over the whole package.

Run it: ``python -m mpi_tensorflow_tpu.analysis`` (see
``analysis/runner.py`` and docs/ANALYSIS.md).  ``scripts/t1_guard.sh``
runs it as a pre-flight before the tier-1 suite.
"""

from mpi_tensorflow_tpu.analysis.core import Finding  # noqa: F401
