"""host-sync pass: no accidental device->host syncs in the hot loop.

Every ``int()`` / ``float()`` / ``bool()`` / ``.item()`` /
``np.asarray()`` on the result of a jitted call blocks the host on the
device stream.  The serving hot loop (``serving/iteration.py``, the
engine's step path, the router's tick path) budgets its syncs — one
bulk ``np.asarray`` per dispatch — and anything beyond that is latency
the continuous-batching design exists to avoid.

The pass taints names bound from calls through jit-built attributes
(``self._decode_fn = jax.jit(...)`` and friends) and flags host
conversions applied to tainted values inside the hot namespace.
Intended syncs carry ``# graft-lint: sync-ok(<reason>)`` on the line
or the line above; ``.item()`` is flagged unconditionally (the
per-element sync pattern has no place in the hot loop).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from mpi_tensorflow_tpu.analysis import core

PASS_IDS = ("HOST-SYNC",)

JIT_CTORS = {"jax.jit", "jit", "pjit", "jax.pjit"}
#: hot namespace: path suffix -> function names (None = whole file)
HOT: Dict[str, Optional[Set[str]]] = {
    "serving/iteration.py": None,
    "serving/engine.py": {"step", "_advance_prefill", "_step_verify",
                          "_ensure_private", "_track_occupancy"},
    "serving/router.py": {"route", "load_score", "_tick", "_route_due",
                          "_observe_fleet"},
}
HOST_CASTS = {"int", "float", "bool"}
HOST_COPIES = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


def _device_attrs(tree: ast.Module) -> Set[str]:
    """Attribute names assigned from ``jax.jit(...)`` anywhere in the
    module (``self._decode_fn = jax.jit(self._decode_impl, ...)``)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and core.dotted_name(node.value.func) in JIT_CTORS:
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    out.add(t.attr)
    return out


def _hot_functions(rel: str, tree: ast.Module):
    for suffix, names in HOT.items():
        if rel.endswith(suffix):
            for fn in core.iter_functions(tree):
                if names is None or fn.name in names:
                    yield fn
            return


class _FnChecker:
    """Statement-ordered taint walk of one hot function."""

    def __init__(self, rel: str, src: str, device_attrs: Set[str],
                 findings: List[core.Finding]):
        self.rel = rel
        self.src = src
        self.device_attrs = device_attrs
        self.findings = findings
        self.tainted: Set[str] = set()

    # -- taint helpers --

    def _is_device_call(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in self.device_attrs)

    def _is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Subscript):
            return self._is_tainted(node.value)
        return False

    def _flag(self, node: ast.AST, what: str) -> None:
        if core.allowlist_reason(self.src, node.lineno, "sync"):
            return
        self.findings.append(core.Finding(
            self.rel, node.lineno, "HOST-SYNC",
            f"{what} forces a device->host sync in the hot loop "
            f"(batch it, hoist it, or annotate "
            f"`# graft-lint: sync-ok(<reason>)`)"))

    # -- expression scan (uses) --

    def check_expr(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = core.dotted_name(sub.func)
            if name in HOST_CASTS and sub.args \
                    and self._is_tainted(sub.args[0]):
                self._flag(sub, f"{name}() on a jitted-call result")
            elif name in HOST_COPIES and sub.args \
                    and self._is_tainted(sub.args[0]):
                self._flag(sub, f"{name}() on a jitted-call result")
            elif isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "item" and not sub.args:
                self._flag(sub, ".item()")

    # -- statement walk (flow order: check uses, then bind) --

    def run(self, fn: ast.AST) -> None:
        self.visit_body(fn.body)

    def visit_body(self, body) -> None:
        for stmt in body:
            self.visit_stmt(stmt)

    def _bind(self, targets, value) -> None:
        names = []
        for t in targets:
            if isinstance(t, ast.Name):
                names.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                names.extend(e.id for e in t.elts
                             if isinstance(e, ast.Name))
        if self._is_device_call(value):
            self.tainted |= set(names)
        else:
            self.tainted -= set(names)

    def visit_stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, ast.Assign):
            self.check_expr(stmt.value)
            self._bind(stmt.targets, stmt.value)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.check_expr(stmt.value)
                self._bind([stmt.target], stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            self.check_expr(stmt.value)
        elif isinstance(stmt, (ast.Expr, ast.Return)):
            if getattr(stmt, "value", None) is not None:
                self.check_expr(stmt.value)
        elif isinstance(stmt, (ast.If, ast.While)):
            self.check_expr(stmt.test)
            self.visit_body(stmt.body)
            self.visit_body(stmt.orelse)
        elif isinstance(stmt, ast.For):
            self.check_expr(stmt.iter)
            self.visit_body(stmt.body)
            self.visit_body(stmt.orelse)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                self.check_expr(item.context_expr)
            self.visit_body(stmt.body)
        elif isinstance(stmt, ast.Try):
            self.visit_body(stmt.body)
            for handler in stmt.handlers:
                self.visit_body(handler.body)
            self.visit_body(stmt.orelse)
            self.visit_body(stmt.finalbody)
        elif isinstance(stmt, (ast.Raise, ast.Assert)):
            for val in (getattr(stmt, "exc", None),
                        getattr(stmt, "test", None),
                        getattr(stmt, "msg", None)):
                if val is not None:
                    self.check_expr(val)
        # nested defs start a fresh scope; hot-ness is per named
        # function, so nested bodies are skipped here


def run(sources: Dict[str, str]) -> List[core.Finding]:
    findings: List[core.Finding] = []
    trees = core.parse_sources(sources)
    for rel, tree in trees.items():
        device_attrs = _device_attrs(tree)
        for fn in _hot_functions(rel, tree):
            checker = _FnChecker(rel, sources[rel], device_attrs,
                                 findings)
            checker.run(fn)
    return findings
