"""Runner: collect sources, run every pass, apply the baseline ratchet.

``python -m mpi_tensorflow_tpu.analysis`` runs all five passes over
the package (plus ``bench.py``) and prints one line per finding::

    mpi_tensorflow_tpu/serving/router.py:419: LOCK-HELD self.fleet_...

Exit status:

- 0 — no findings beyond the baseline;
- 1 — new findings (or a stale baseline entry count exceeded);
- 2 — usage / IO error.

The baseline (``analysis/baseline.json``) maps ``"PASS-ID:file"`` to a
suppressed count.  It is a RATCHET: the runner fails if the current
count for any key exceeds the baselined count, and
``--update-baseline`` refuses to write a baseline with any count
higher than the existing one.  Counts only go down; the shipped
baseline is empty because every real finding was fixed or annotated
in the PR that introduced the checker.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys
from typing import Dict, List

from mpi_tensorflow_tpu.analysis import (core, host_sync, jit_stability,
                                         knob_bridge, locks, names)

PASSES = (knob_bridge, jit_stability, host_sync, locks, names)

_DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                 "baseline.json")


def run_all(sources: Dict[str, str]) -> List[core.Finding]:
    findings: List[core.Finding] = []
    for mod in PASSES:
        findings.extend(mod.run(sources))
    findings.sort(key=lambda f: (f.file, f.line, f.pass_id, f.message))
    return findings


def counts_by_key(findings: List[core.Finding]) -> Dict[str, int]:
    out: Dict[str, int] = collections.Counter()
    for f in findings:
        out[f.baseline_key] += 1
    return dict(out)


def load_baseline(path: str) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        raw = json.load(fh)
    return {str(k): int(v) for k, v in raw.items()}


def compare(current: Dict[str, int],
            baseline: Dict[str, int]) -> Dict[str, int]:
    """Keys whose current count exceeds the baselined count (the
    failures), mapped to the excess."""
    over: Dict[str, int] = {}
    for key, n in current.items():
        allowed = baseline.get(key, 0)
        if n > allowed:
            over[key] = n - allowed
    return over


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_tensorflow_tpu.analysis",
        description="graft-lint: AST invariant checker for the repo's "
                    "hand-enforced contracts")
    parser.add_argument("--root", default=None,
                        help="repo root to scan (default: auto-detected "
                             "from the package location)")
    parser.add_argument("--baseline", default=_DEFAULT_BASELINE,
                        help="baseline suppression file")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current "
                             "findings (ratchet: refuses any count "
                             "increase)")
    args = parser.parse_args(argv)

    root = args.root or core.repo_root()
    sources = core.load_sources(root)
    if not sources:
        print(f"graft-lint: no Python sources under {root}",
              file=sys.stderr)
        return 2
    findings = run_all(sources)
    current = counts_by_key(findings)
    try:
        baseline = load_baseline(args.baseline)
    except (ValueError, OSError) as exc:
        print(f"graft-lint: bad baseline {args.baseline}: {exc}",
              file=sys.stderr)
        return 2

    if args.update_baseline:
        grew = {k: (baseline.get(k, 0), n) for k, n in current.items()
                if n > baseline.get(k, 0) and baseline}
        if grew:
            for key, (old, new) in sorted(grew.items()):
                print(f"graft-lint: ratchet: {key} would grow "
                      f"{old} -> {new}; fix or annotate instead of "
                      f"baselining", file=sys.stderr)
            return 1
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump(current, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"graft-lint: baseline written "
              f"({sum(current.values())} suppressed findings)")
        return 0

    over = compare(current, baseline)
    shown = 0
    budget = dict(baseline)
    for f in findings:
        if budget.get(f.baseline_key, 0) > 0:
            budget[f.baseline_key] -= 1     # suppressed by baseline
            continue
        print(f.format())
        shown += 1

    tighten = {k: v for k, v in baseline.items()
               if current.get(k, 0) < v}
    for key in sorted(tighten):
        print(f"graft-lint: baseline for {key} is stale "
              f"({current.get(key, 0)} < {tighten[key]}); run "
              f"--update-baseline to ratchet down", file=sys.stderr)

    if over:
        print(f"graft-lint: {shown} new finding(s) "
              f"({len(findings)} total, "
              f"{len(findings) - shown} baselined)", file=sys.stderr)
        return 1
    print(f"graft-lint: clean ({len(findings)} baselined finding(s))"
          if findings else "graft-lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
