"""``python -m mpi_tensorflow_tpu.analysis`` entry point."""

import sys

from mpi_tensorflow_tpu.analysis.runner import main

sys.exit(main())
