"""lock-discipline pass: ``_GUARDED_BY`` declarations, enforced.

PR 7 shipped a real KeyError race: the router's sticky-map read,
health check, and LRU touch spanned two lock holds, so a concurrent
trim could evict the key between them.  The fix was "do it under ONE
lock hold" — a convention this pass turns into a checked contract.

A class declares its threading discipline in one class attribute::

    class ReplicaRouter:
        _GUARDED_BY = {"_lock": ("_sticky", "_session_live", ...)}

and the pass proves, lexically, that EVERY read or write of
``self.<guarded attr>`` anywhere in the class sits inside a
``with self._lock`` block.  Escapes:

- ``__init__`` (no concurrent access before construction returns);
- methods named ``*_locked`` (the caller-holds-the-lock convention —
  their call sites are checked instead, since those sit in lock-held
  ``with`` blocks);
- ``# graft-lint: lock-ok(<reason>)`` on the line or the line above,
  for provably single-threaded phases (cold init, post-join
  aggregation).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from mpi_tensorflow_tpu.analysis import core

PASS_IDS = ("LOCK-HELD",)


def _guarded_map(cls: ast.ClassDef) -> Dict[str, Set[str]]:
    """Parse the ``_GUARDED_BY`` literal: lock attr -> guarded attrs."""
    for node in cls.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if not any(isinstance(t, ast.Name) and t.id == "_GUARDED_BY"
                   for t in targets):
            continue
        try:
            raw = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            return {}
        return {lock: set(attrs) for lock, attrs in raw.items()}
    return {}


def _under_lock(node: ast.AST, lock: str,
                parents: Dict[ast.AST, ast.AST],
                stop: ast.AST) -> bool:
    """Is ``node`` lexically inside ``with self.<lock>`` (climbing no
    higher than ``stop``, the class body)?"""
    cur = parents.get(node)
    while cur is not None and cur is not stop:
        if isinstance(cur, ast.With):
            for item in cur.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Attribute) and ctx.attr == lock \
                        and isinstance(ctx.value, ast.Name) \
                        and ctx.value.id == "self":
                    return True
        cur = parents.get(cur)
    return False


def _enclosing_method(node: ast.AST, parents,
                      cls: ast.ClassDef) -> Optional[ast.FunctionDef]:
    """The class-level method containing ``node`` (not nested defs)."""
    method = None
    cur = parents.get(node)
    while cur is not None and cur is not cls:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and parents.get(cur) is cls:
            method = cur
        cur = parents.get(cur)
    return method


def run(sources: Dict[str, str]) -> List[core.Finding]:
    findings: List[core.Finding] = []
    trees = core.parse_sources(sources)
    for rel, tree in trees.items():
        src = sources[rel]
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded = _guarded_map(cls)
            if not guarded:
                continue
            for lock, attrs in guarded.items():
                for node in ast.walk(cls):
                    if not (isinstance(node, ast.Attribute)
                            and node.attr in attrs
                            and isinstance(node.value, ast.Name)
                            and node.value.id == "self"):
                        continue
                    method = _enclosing_method(node, parents, cls)
                    if method is not None \
                            and (method.name == "__init__"
                                 or method.name.endswith("_locked")):
                        continue
                    if _under_lock(node, lock, parents, cls):
                        continue
                    if core.allowlist_reason(src, node.lineno, "lock"):
                        continue
                    where = method.name if method is not None \
                        else cls.name
                    findings.append(core.Finding(
                        rel, node.lineno, "LOCK-HELD",
                        f"self.{node.attr} accessed in {where} outside "
                        f"`with self.{lock}` (declared _GUARDED_BY; "
                        f"the PR 7 sticky-map race class)"))
    return findings
