"""mpi_tensorflow_tpu — a TPU-native data-parallel training framework.

A brand-new JAX/XLA re-design of the capabilities of
``youzhenfei1995/mpi-Tensorflow`` (an mpi4py + TensorFlow-v1 synchronous
MNIST trainer, reference ``mpipy.py``):

- ``data``      — in-repo IDX parsing, dataset pipelines, per-host sharding
                  (replaces the reference's external ``convolutional`` helpers
                  and root-0 ``MPI.Scatter``, mpipy.py:12, 236-241).
- ``parallel``  — device mesh, XLA collectives, sharding rules, ring attention
                  (replaces ``MPI.COMM_WORLD`` and mpi4py collectives,
                  mpipy.py:5, 208-210).
- ``models``    — the reference CNN (mpipy.py:33-68, 155-167) plus the
                  scale-out model families from BASELINE.json (ResNet, BERT).
- ``train``     — jit-compiled train step with in-graph gradient ``psum``,
                  host loop, evaluation, checkpointing (replaces
                  ``Cnn.run_process`` / ``bcast_parameters``, mpipy.py:76-153).
- ``ops``       — Pallas TPU kernels for hot ops.
- ``utils``     — console trace in the reference's format, timing harness.

The public surface mirrors what a user of the reference needs: build a model,
get sharded data, run the training loop, read the 50-step error trace.
"""

__version__ = "0.1.0"

from mpi_tensorflow_tpu.config import Config  # noqa: F401

# older jaxlibs spell shard_map / axis_size differently; one shim at
# package import keeps every call site on the modern jax surface
from mpi_tensorflow_tpu.utils import jaxcompat as _jaxcompat

_jaxcompat.install()
del _jaxcompat
