from mpi_tensorflow_tpu.cli import main

raise SystemExit(main())
