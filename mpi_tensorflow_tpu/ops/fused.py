"""Pallas TPU kernels for the reduction-fusion ops, with honest benchmarks.

The reference gets its fused elementwise+reduction kernels from the TF C++
runtime (SURVEY.md §2 E2).  On TPU the equivalent roles are:

- ``layer_norm``             single-pass mean/var/normalize/affine;
- ``online_logsumexp``       one read of logits with the running max and
                             exp-sum carried in VMEM scratch
                             (flash-attention's softmax trick);
- ``softmax_cross_entropy``  logsumexp kernel + gold-logit gather; the
                             (N, V) softmax matrix is never materialized.

**Measured verdict (TPU v5 lite, BERT-base shapes, in-graph loop timing):
XLA's own fusion wins.**  logsumexp over (4096, 30522): XLA 2.23 ms vs the
best Pallas config 3.16 ms; layer_norm over (4096, 768): parity.  XLA's
two-pass reduction fusion already runs near HBM bandwidth, so the
single-pass trick buys nothing a hand kernel can collect — consistent with
the rule that Pallas pays only where the compiler *cannot* fuse (the O(S^2)
flash-attention materialization, ops/flash_attention.py, 19x) rather than
where it merely *might* do better.  The model paths therefore keep the XLA
implementations; these kernels stay as verified building blocks for larger
hand-written pipelines (where fusing the neighbor op into a Pallas kernel
avoids an HBM round-trip XLA cannot see across a custom-call boundary).

Backward passes recompute from the saved inputs (flash_attention.py's
strategy): layer_norm grads via the closed-form JAX reference, CE grads as
``softmax - onehot`` — both fuse into single XLA passes.

All kernels take ``interpret=`` so the equivalence tests run on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_BIG = -1e30


# ---------------------------------------------------------------------------
# layer norm
# ---------------------------------------------------------------------------

def layer_norm_reference(x, scale, bias, eps: float = 1e-12):
    """Two-pass JAX reference (matches models/bert.py:_layernorm)."""
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * scale + bias


def _ln_kernel(x_ref, s_ref, b_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)                 # (BN, F)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * lax.rsqrt(var + eps) * s_ref[0] + b_ref[0]
    o_ref[...] = y.astype(o_ref.dtype)


def _ln_forward(x, scale, bias, eps: float, block_rows: int, interpret: bool):
    orig_shape = x.shape
    f = x.shape[-1]
    x2 = x.reshape(-1, f)
    n = x2.shape[0]
    pad = (-n) % block_rows
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        grid=(x2.shape[0] // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, f), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, f), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, f), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, f), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x2, scale.astype(jnp.float32).reshape(1, f),
      bias.astype(jnp.float32).reshape(1, f))
    if pad:
        out = out[:n]
    return out.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def layer_norm(x, scale, bias, eps: float = 1e-12, block_rows: int = 128,
               interpret: bool = False):
    """Fused single-pass LayerNorm over the last axis."""
    return _ln_forward(x, scale, bias, eps, block_rows, interpret)


def _ln_fwd(x, scale, bias, eps, block_rows, interpret):
    return layer_norm(x, scale, bias, eps, block_rows, interpret), \
        (x, scale, bias)


def _ln_bwd(eps, block_rows, interpret, res, g):
    x, scale, bias = res
    # the Pallas forward emits x.dtype, so the incoming cotangent is x.dtype;
    # the f32 scale/bias would otherwise promote the reference closure's
    # output (and the cotangent jax.vjp expects) to float32
    _, vjp = jax.vjp(
        lambda x, s, b: layer_norm_reference(x, s, b, eps).astype(g.dtype),
        x, scale, bias)
    return vjp(g)


layer_norm.defvjp(_ln_fwd, _ln_bwd)


# ---------------------------------------------------------------------------
# online logsumexp + fused softmax cross-entropy
# ---------------------------------------------------------------------------

def _lse_kernel(x_ref, o_ref, m_scr, l_scr):
    ki = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)

    s = x_ref[...].astype(jnp.float32)                  # (BN, BV)
    m_prev = m_scr[:, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    l_new = l_scr[:, 0:1] * jnp.exp(m_prev - m_new) + \
        jnp.sum(jnp.exp(s - m_new), axis=-1, keepdims=True)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _emit():
        o_ref[...] = jnp.broadcast_to(
            m_scr[:, 0:1] + jnp.log(l_scr[:, 0:1]), o_ref.shape)


def online_logsumexp(x, *, block_rows: int = 128, block_v: int = 512,
                     interpret: bool = False):
    """Single-pass logsumexp over the last axis of ``x`` (any leading dims).

    Carries the running max and exp-sum in VMEM scratch across vocab
    blocks, so HBM sees each logit exactly once.
    """
    orig_lead = x.shape[:-1]
    v = x.shape[-1]
    x2 = x.reshape(-1, v)
    n = x2.shape[0]
    pad_n = (-n) % block_rows
    bv = min(block_v, v)
    pad_v = (-v) % bv
    if pad_n or pad_v:
        x2 = jnp.pad(x2, ((0, pad_n), (0, pad_v)),
                     constant_values=NEG_BIG)
    grid = (x2.shape[0] // block_rows, x2.shape[1] // bv)
    out = pl.pallas_call(
        _lse_kernel,
        out_shape=jax.ShapeDtypeStruct((x2.shape[0], 128), jnp.float32),
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, bv), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((block_rows, 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((block_rows, 128), jnp.float32),
            pltpu.VMEM((block_rows, 128), jnp.float32),
        ],
        interpret=interpret,
    )(x2)
    lse = out[:n, 0]
    return lse.reshape(orig_lead)


def _ce_reference(logits, labels):
    """Per-position CE, the JAX reference (models/bert.py loss formula)."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return logz - gold


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_cross_entropy(logits, labels, block_v: int = 512,
                          interpret: bool = False):
    """Fused sparse softmax cross-entropy: per-position loss, softmax never
    materialized.  ``logits``: (..., V) float, ``labels``: (...) int."""
    lse = online_logsumexp(logits, block_v=block_v, interpret=interpret)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return lse - gold.astype(jnp.float32)


def _ce_fwd(logits, labels, block_v, interpret):
    out = softmax_cross_entropy(logits, labels, block_v, interpret)
    # save lse (cheap, (N,)) so the backward is one fused elementwise pass
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    lse = out + gold.astype(jnp.float32)
    return out, (logits, labels, lse)


def _ce_bwd(block_v, interpret, res, g):
    logits, labels, lse = res
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    grad = (p - onehot) * g[..., None]
    return grad.astype(logits.dtype), None


softmax_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
