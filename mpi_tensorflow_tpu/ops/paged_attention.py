"""Paged KV-cache device ops: block-table gather/scatter + attention.

The serving path (serving/) stores K/V in a fixed pool of
``(num_blocks, heads, block_size, head_dim)`` blocks instead of one
contiguous ``(B, H, max_len, D)`` buffer per request batch
(models/gpt.init_cache).  Each live sequence owns an ordered list of
pool blocks (its block table); block ``j`` of a sequence holds absolute
positions ``[j*block_size, (j+1)*block_size)``, so a gather of the table
reconstructs the contiguous layout and the attention math can stay
IDENTICAL to the contiguous decode path — the token-parity guarantee
(tests/test_serving.py) rests on that: same einsum contraction order,
same fp32 masked softmax (``masked_softmax_attention``, the ONE
implementation both paths call), with padding lanes exactly zeroed
(``exp(finfo.min - max)`` underflows to 0.0, and 0-weighted V lanes add
exact 0.0 terms).

The pool layout is head-major so a single block is ``(H, block_size,
D)`` — the orientation the fused Pallas kernel
(ops/paged_attention_kernel) streams blockwise with no in-kernel
transpose.

Block 0 is the NULL block: never allocated to a sequence, it absorbs
scatter writes from masked-out lanes (padded prefill tail, inactive
decode slots) so those lanes need no branching — garbage lands in
scratch, reads of it are masked by the causal visibility test.

``attend`` is THE dispatcher behind the paged-attention seam: the
``--serve-kernel`` knob (CLI -> Config -> ServeConfig -> engine)
resolves through ``resolve_kernel`` to either

- ``pallas`` — the fused kernel, reading pool blocks in place through
  the block table with an fp32 online softmax (TPU; ``interpret=True``
  on CPU for tests), or
- ``xla``    — this module's gather + dense masked softmax, the
  always-available exact fallback (TPU-lowerable, CPU-exact).

Tensor parallelism (serving/tp): every op here treats H as a PURE
BATCH dimension — ``write_kv`` scatters per-head rows independently,
``gather_kv``/``attend`` contract only within a head — so under a
head-sharded pool each shard runs these ops unchanged over its local
``H/tp`` heads with the SAME replicated block table (a block id
addresses the same slot of every shard's pool).  Nothing in this
module is tp-aware; the cross-shard reduction lives in the model's
row-parallel projections, not in attention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NULL_BLOCK = 0


def masked_softmax_attention(q, k, v, vis, dt, scale=None):
    """THE fp32 masked-softmax attention shared by the contiguous decode
    path (models/gpt.forward_with_cache) and the paged path
    (``paged_attention``) — one implementation, so the greedy
    token-parity guarantee between them holds by construction.

    q:    (B, H, S, D) queries
    k, v: (B, H, L, D) position-ordered keys/values
    vis:  bool, broadcastable to (B, S, L) — True where the key lane is
          visible to the query row
    dt:   compute dtype for the probability @ V contraction

    Cast to fp32 BEFORE the scale, scale folded into the masked select,
    softmax in fp32, probabilities cast back to ``dt``.  Masked lanes
    score ``finfo(f32).min`` so their softmax weight underflows to
    exact 0.0.
    """
    scale = q.shape[-1] ** -0.5 if scale is None else scale
    s = jnp.einsum("bhsd,bhld->bhsl", q, k).astype(jnp.float32)
    s = jnp.where(vis, s * scale, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    return jnp.einsum("bhsl,bhld->bhsd", p, v)


def write_kv(pool, kv, block_table, positions, valid):
    """Scatter per-token K or V vectors into the block pool.

    pool:        (num_blocks, H, block_size, D)
    kv:          (B, H, S, D)  — new keys or values, head-major like the
                 qkv projection emits
    block_table: (B, NB) int32 — pool block ids, position order
    positions:   (B, S) int32 — absolute position of each token
    valid:       (B, S) bool — False lanes scatter into the null block

    Returns the updated pool.  Lanes of distinct sequences never collide
    (the allocator hands each block to one sequence); invalid lanes all
    land in block 0, whose contents are never read unmasked.
    """
    bs = pool.shape[2]
    nb = block_table.shape[1]
    blk_idx = jnp.clip(positions // bs, 0, nb - 1)
    blk = jnp.take_along_axis(block_table, blk_idx, axis=1)      # (B, S)
    blk = jnp.where(valid, blk, NULL_BLOCK)
    off = positions % bs
    vals = jnp.transpose(kv, (0, 2, 1, 3))                       # (B, S, H, D)
    # two advanced indices around the head slice: the broadcast (B, S)
    # index dims lead, so this writes pool[blk[b,s], h, off[b,s], :]
    return pool.at[blk, :, off].set(vals.astype(pool.dtype))


def quantize_kv(kv):
    """Symmetric absmax int8 quantization, one scale per (B, H, S) row.

    kv: (B, H, S, D) fp K or V vectors.  Returns ``(codes, scales)``:
    codes (B, H, S, D) int8, scales (B, H, S) fp32 with
    ``scale = max|row| / 127`` (0.0 for an all-zero row).

    The scale granularity is deliberately PER TOKEN ROW, not per whole
    block: a row's codes depend only on its own fp values, never on
    which other tokens share the block or on how many tokens the write
    dispatch carried.  That makes quantization GRANULARITY-INDEPENDENT —
    chunked prefill, single-token decode, speculative verify, and
    journal replay all produce bit-identical pool bytes for the same
    token stream (the determinism contract the int8 composition tests
    pin) — and gives the exact elementwise bound
    ``|dequant - x| <= max|row| / 127 / 2 * 2 = amax/127`` (half a
    quantization step from round-half-even, bounded by one step).

    Rounding is ``jnp.round`` (round-half-even, deterministic across
    backends); stochastic rounding would break replay byte-identity.
    """
    x = kv.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)                          # (B, H, S)
    scale = amax / 127.0
    safe = jnp.where(scale > 0.0, scale, 1.0)[..., None]
    codes = jnp.clip(jnp.round(x / safe), -127, 127).astype(jnp.int8)
    return codes, scale


def write_kv_quant(pool, pool_scale, kv, block_table, positions, valid):
    """``write_kv`` for the int8 pool: quantize the incoming rows
    (``quantize_kv``) and scatter codes AND scales through the same
    block/offset indexing.

    pool:        (num_blocks, H, block_size, D) int8 codes
    pool_scale:  (num_blocks, H, block_size) fp32 row scales
    kv/block_table/positions/valid: as ``write_kv``

    Returns ``(pool, pool_scale)`` updated.  Each write dispatch
    computes fresh scales for exactly the rows it writes — invalid
    lanes land codes and scales in the null block, never read unmasked.
    """
    bs = pool.shape[2]
    nb = block_table.shape[1]
    blk_idx = jnp.clip(positions // bs, 0, nb - 1)
    blk = jnp.take_along_axis(block_table, blk_idx, axis=1)      # (B, S)
    blk = jnp.where(valid, blk, NULL_BLOCK)
    off = positions % bs
    codes, scale = quantize_kv(kv)
    vals = jnp.transpose(codes, (0, 2, 1, 3))                    # (B, S, H, D)
    sv = jnp.transpose(scale, (0, 2, 1))                         # (B, S, H)
    return (pool.at[blk, :, off].set(vals),
            pool_scale.at[blk, :, off].set(sv))


def dequantize_kv(codes, scale, dt):
    """THE int8->fp dequantization, shared verbatim (in math) by the
    XLA gather path below and the Pallas kernel's in-register step
    (ops/paged_attention_kernel) so the two lowerings stay in lockstep:
    ``(codes.astype(f32) * scale).astype(dt)``, scale broadcast over the
    trailing D axis."""
    return (codes.astype(jnp.float32) * scale[..., None]).astype(dt)


def gather_kv(pool, block_table):
    """Reassemble a (B, H, L, D) contiguous view from the pool.

    L = NB * block_size; entry ``l`` holds the sequence's absolute
    position ``l`` (block tables are position-ordered), so the causal
    visibility test against absolute query positions carries over
    unchanged from the contiguous path.
    """
    g = pool[block_table]                        # (B, NB, H, bs, D)
    B, NB, H, bs, D = g.shape
    return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(B, H, NB * bs, D)


def paged_attention(q, ck, cv, q_positions, dt):
    """Masked causal attention over a gathered paged cache.

    q:           (B, H, S, D) query block (S=1 decode, S=chunk prefill)
    ck, cv:      (B, H, L, D) gathered keys/values (gather_kv)
    q_positions: (B, S) absolute positions of the queries
    dt:          compute dtype for the probability @ V contraction

    The math IS models/gpt.forward_with_cache's attention
    (``masked_softmax_attention``): the greedy token-parity test pins
    this path to the contiguous one bit-for-bit on CPU.

    MIXED-ROW CONTRACT: visibility is evaluated PER ROW against that
    row's own ``q_positions`` — nothing couples rows, so one batch may
    freely mix phases (decode rows querying a single position beside
    prefill rows querying a chunk at their own offsets, the
    --serve-mixed-batch fused dispatch).  Each row attends to exactly
    the prefix its positions admit, identical to what a single-phase
    dispatch would give it; tests/test_mixed_batch.py pins the fused
    and unfused paths token-identical in fp32 and int8.
    """
    L = ck.shape[2]
    col = jnp.arange(L)
    # (B, S, L): key position <= query position, per row
    vis = col[None, None, :] <= q_positions[:, :, None]
    return masked_softmax_attention(q, ck, cv, vis[:, None], dt)


def attend(q, k_pool, v_pool, block_table, lengths, dt, *,
           kernel: str = "xla", k_scale=None, v_scale=None):
    """THE paged-attention dispatch seam: one entry point, two lowering
    strategies, identical greedy tokens (tests/test_paged_kernel.py).

    q:           (B, H, S, D) queries at positions [lengths[b],
                 lengths[b] + S) — their K/V already scattered into the
                 pools (write_kv runs first)
    k/v_pool:    (num_blocks, H, block_size, D)
    block_table: (B, NB) int32
    lengths:     (B,) int32 cache entries already present per row
    kernel:      "xla" (gather + dense masked softmax) or "pallas"
                 (fused blockwise online softmax; interpret mode off
                 TPU).  Callers resolve "auto" BEFORE tracing via
                 ``resolve_kernel`` — this runs under jit, where the
                 choice must be static.
    k/v_scale:   (num_blocks, H, block_size) fp32 row scales when the
                 pools hold int8 codes (--serve-kv-dtype int8); both or
                 neither.  Dequantization happens INSIDE the consume
                 path — in-register in the kernel, elementwise on the
                 gathered view here — so no fp pool ever materializes.

    MIXED-ROW CONTRACT: ``lengths`` is per-row and the causal mask is
    built per row from it (``pos = lengths[:, None] + arange(S)``), so
    rows of ONE dispatch may sit at different phases — a decode row
    (one real lane) beside prefill rows carrying chunks at their own
    offsets, as the --serve-mixed-batch fused step packs them.  Rows
    with fewer than S real lanes are the CALLER'S job to mask: slack
    lanes must be marked invalid upstream so write_kv lands them in
    the null block, and their attention output is garbage to be
    discarded on host.  Both lowerings honor this identically (the
    Pallas path masks by the same per-row positions), pinned in fp32
    and int8 by tests/test_mixed_batch.py.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("int8 pools need both k_scale and v_scale")
    if kernel == "pallas":
        from mpi_tensorflow_tpu.ops import paged_attention_kernel as pk

        interpret = jax.default_backend() != "tpu"
        fused = (pk.paged_decode_attention if q.shape[2] == 1
                 else pk.paged_prefill_attention)
        return fused(q, k_pool, v_pool, block_table, lengths,
                     interpret=interpret, k_scale=k_scale,
                     v_scale=v_scale)
    if kernel != "xla":
        raise ValueError(
            f"unresolved paged-attention kernel {kernel!r}: callers "
            f"resolve 'auto' host-side via resolve_kernel before tracing")
    S = q.shape[2]
    pos = lengths[:, None] + jnp.arange(S, dtype=jnp.int32)
    if k_scale is not None:
        # dequantize the gathered blocks elementwise, in lockstep with
        # the kernel's in-register step (dequantize_kv is the shared
        # contract), BEFORE the unchanged transpose/reshape + softmax
        ck = _gather_kv_dequant(k_pool, k_scale, block_table, q.dtype)
        cv = _gather_kv_dequant(v_pool, v_scale, block_table, q.dtype)
    else:
        ck = gather_kv(k_pool, block_table)
        cv = gather_kv(v_pool, block_table)
    return paged_attention(q, ck, cv, pos, dt)


def _gather_kv_dequant(pool, pool_scale, block_table, dt):
    """``gather_kv`` over an int8 pool: gather codes and scales through
    the same table, dequantize, reassemble the (B, H, L, D) view."""
    g = pool[block_table]                        # (B, NB, H, bs, D) int8
    gs = pool_scale[block_table]                 # (B, NB, H, bs) f32
    g = dequantize_kv(g, gs, dt)
    B, NB, H, bs, D = g.shape
    return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(B, H, NB * bs, D)


def resolve_kernel(choice: str, cfg, block_size: int,
                   prefill_chunk: int = 64,
                   kv_dtype: str = "fp32") -> str:
    """Resolve the ``--serve-kernel`` knob to a static lowering choice.

    - "xla"    -> "xla"     (always available, exact)
    - "pallas" -> "pallas"  (forced; interpret mode off TPU — the test
                             configuration)
    - "auto"   -> "pallas" on TPU when the compile probe
                  (paged_attention_kernel.kernel_supported) passes for
                  this model geometry, else "xla".  Off TPU, "auto"
                  stays on XLA: the interpreter is a correctness
                  vehicle, not a serving path.

    Host-side, once per engine: the resolved literal is baked into the
    jitted decode/prefill steps, so kernel choice can never add dispatch
    shapes or recompiles.
    """
    if choice in ("xla", "pallas"):
        return choice
    if choice != "auto":
        raise ValueError(
            f"serve kernel must be auto|xla|pallas, got {choice!r}")
    if jax.default_backend() != "tpu":
        return "xla"
    from mpi_tensorflow_tpu.ops import paged_attention_kernel as pk

    ok = pk.kernel_supported(jnp.dtype(cfg.dtype).name, cfg.heads,
                             cfg.head_dim, block_size, prefill_chunk,
                             kv_dtype)
    return "pallas" if ok else "xla"
