"""Paged KV-cache device ops: block-table gather/scatter + attention.

The serving path (serving/) stores K/V in a fixed pool of
``(num_blocks, heads, block_size, head_dim)`` blocks instead of one
contiguous ``(B, H, max_len, D)`` buffer per request batch
(models/gpt.init_cache).  Each live sequence owns an ordered list of
pool blocks (its block table); block ``j`` of a sequence holds absolute
positions ``[j*block_size, (j+1)*block_size)``, so a gather of the table
reconstructs the contiguous layout and the attention math can stay
IDENTICAL to the contiguous decode path — the token-parity guarantee
(tests/test_serving.py) rests on that: same einsum contraction order,
same fp32 masked softmax (``masked_softmax_attention``, the ONE
implementation both paths call), with padding lanes exactly zeroed
(``exp(finfo.min - max)`` underflows to 0.0, and 0-weighted V lanes add
exact 0.0 terms).

The pool layout is head-major so a single block is ``(H, block_size,
D)`` — the orientation the fused Pallas kernel
(ops/paged_attention_kernel) streams blockwise with no in-kernel
transpose.

Block 0 is the NULL block: never allocated to a sequence, it absorbs
scatter writes from masked-out lanes (padded prefill tail, inactive
decode slots) so those lanes need no branching — garbage lands in
scratch, reads of it are masked by the causal visibility test.

``attend`` is THE dispatcher behind the paged-attention seam: the
``--serve-kernel`` knob (CLI -> Config -> ServeConfig -> engine)
resolves through ``resolve_kernel`` to either

- ``pallas`` — the fused kernel, reading pool blocks in place through
  the block table with an fp32 online softmax (TPU; ``interpret=True``
  on CPU for tests), or
- ``xla``    — this module's gather + dense masked softmax, the
  always-available exact fallback (TPU-lowerable, CPU-exact).

Tensor parallelism (serving/tp): every op here treats H as a PURE
BATCH dimension — ``write_kv`` scatters per-head rows independently,
``gather_kv``/``attend`` contract only within a head — so under a
head-sharded pool each shard runs these ops unchanged over its local
``H/tp`` heads with the SAME replicated block table (a block id
addresses the same slot of every shard's pool).  Nothing in this
module is tp-aware; the cross-shard reduction lives in the model's
row-parallel projections, not in attention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NULL_BLOCK = 0


def masked_softmax_attention(q, k, v, vis, dt, scale=None):
    """THE fp32 masked-softmax attention shared by the contiguous decode
    path (models/gpt.forward_with_cache) and the paged path
    (``paged_attention``) — one implementation, so the greedy
    token-parity guarantee between them holds by construction.

    q:    (B, H, S, D) queries
    k, v: (B, H, L, D) position-ordered keys/values
    vis:  bool, broadcastable to (B, S, L) — True where the key lane is
          visible to the query row
    dt:   compute dtype for the probability @ V contraction

    Cast to fp32 BEFORE the scale, scale folded into the masked select,
    softmax in fp32, probabilities cast back to ``dt``.  Masked lanes
    score ``finfo(f32).min`` so their softmax weight underflows to
    exact 0.0.
    """
    scale = q.shape[-1] ** -0.5 if scale is None else scale
    s = jnp.einsum("bhsd,bhld->bhsl", q, k).astype(jnp.float32)
    s = jnp.where(vis, s * scale, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    return jnp.einsum("bhsl,bhld->bhsd", p, v)


def write_kv(pool, kv, block_table, positions, valid):
    """Scatter per-token K or V vectors into the block pool.

    pool:        (num_blocks, H, block_size, D)
    kv:          (B, H, S, D)  — new keys or values, head-major like the
                 qkv projection emits
    block_table: (B, NB) int32 — pool block ids, position order
    positions:   (B, S) int32 — absolute position of each token
    valid:       (B, S) bool — False lanes scatter into the null block

    Returns the updated pool.  Lanes of distinct sequences never collide
    (the allocator hands each block to one sequence); invalid lanes all
    land in block 0, whose contents are never read unmasked.
    """
    bs = pool.shape[2]
    nb = block_table.shape[1]
    blk_idx = jnp.clip(positions // bs, 0, nb - 1)
    blk = jnp.take_along_axis(block_table, blk_idx, axis=1)      # (B, S)
    blk = jnp.where(valid, blk, NULL_BLOCK)
    off = positions % bs
    vals = jnp.transpose(kv, (0, 2, 1, 3))                       # (B, S, H, D)
    # two advanced indices around the head slice: the broadcast (B, S)
    # index dims lead, so this writes pool[blk[b,s], h, off[b,s], :]
    return pool.at[blk, :, off].set(vals.astype(pool.dtype))


def quantize_kv(kv):
    """Symmetric absmax int8 quantization, one scale per (B, H, S) row.

    kv: (B, H, S, D) fp K or V vectors.  Returns ``(codes, scales)``:
    codes (B, H, S, D) int8, scales (B, H, S) fp32 with
    ``scale = max|row| / 127`` (0.0 for an all-zero row).

    The scale granularity is deliberately PER TOKEN ROW, not per whole
    block: a row's codes depend only on its own fp values, never on
    which other tokens share the block or on how many tokens the write
    dispatch carried.  That makes quantization GRANULARITY-INDEPENDENT —
    chunked prefill, single-token decode, speculative verify, and
    journal replay all produce bit-identical pool bytes for the same
    token stream (the determinism contract the int8 composition tests
    pin) — and gives the exact elementwise bound
    ``|dequant - x| <= max|row| / 127 / 2 * 2 = amax/127`` (half a
    quantization step from round-half-even, bounded by one step).

    Rounding is ``jnp.round`` (round-half-even, deterministic across
    backends); stochastic rounding would break replay byte-identity.
    """
    x = kv.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)                          # (B, H, S)
    scale = amax / 127.0
    safe = jnp.where(scale > 0.0, scale, 1.0)[..., None]
    codes = jnp.clip(jnp.round(x / safe), -127, 127).astype(jnp.int8)
    return codes, scale


def write_kv_quant(pool, pool_scale, kv, block_table, positions, valid):
    """``write_kv`` for the int8 pool: quantize the incoming rows
    (``quantize_kv``) and scatter codes AND scales through the same
    block/offset indexing.

    pool:        (num_blocks, H, block_size, D) int8 codes
    pool_scale:  (num_blocks, H, block_size) fp32 row scales
    kv/block_table/positions/valid: as ``write_kv``

    Returns ``(pool, pool_scale)`` updated.  Each write dispatch
    computes fresh scales for exactly the rows it writes — invalid
    lanes land codes and scales in the null block, never read unmasked.
    """
    bs = pool.shape[2]
    nb = block_table.shape[1]
    blk_idx = jnp.clip(positions // bs, 0, nb - 1)
    blk = jnp.take_along_axis(block_table, blk_idx, axis=1)      # (B, S)
    blk = jnp.where(valid, blk, NULL_BLOCK)
    off = positions % bs
    codes, scale = quantize_kv(kv)
    vals = jnp.transpose(codes, (0, 2, 1, 3))                    # (B, S, H, D)
    sv = jnp.transpose(scale, (0, 2, 1))                         # (B, S, H)
    return (pool.at[blk, :, off].set(vals),
            pool_scale.at[blk, :, off].set(sv))


def dequantize_kv(codes, scale, dt):
    """THE int8->fp dequantization, shared verbatim (in math) by the
    XLA gather path below and the Pallas kernel's in-register step
    (ops/paged_attention_kernel) so the two lowerings stay in lockstep:
    ``(codes.astype(f32) * scale).astype(dt)``, scale broadcast over the
    trailing D axis."""
    return (codes.astype(jnp.float32) * scale[..., None]).astype(dt)


def pack_int4(codes):
    """Pack int4 codes (values in [-7, 7]) two-per-byte along D.

    codes: (..., D) integer codes.  Split-half layout: byte ``i`` holds
    code ``i`` in its low nibble and code ``i + D/2`` in its high
    nibble, so pack/unpack are two cheap vector ops (mask/shift +
    concat) with no interleaving shuffle — the layout the Pallas
    kernel's in-register unpack mirrors exactly.  Returns (..., D//2)
    uint8.
    """
    D = codes.shape[-1]
    lo = codes[..., :D // 2] & 0xF
    hi = codes[..., D // 2:] & 0xF
    return (lo | (hi << 4)).astype(jnp.uint8)


def unpack_int4(packed):
    """Invert ``pack_int4``: (..., D//2) uint8 -> (..., D) int32 codes
    in [-7, 7] (nibbles sign-extend: values > 7 are negatives)."""
    c = packed.astype(jnp.int32)
    lo = c & 0xF
    hi = (c >> 4) & 0xF
    codes = jnp.concatenate([lo, hi], axis=-1)
    return codes - jnp.where(codes > 7, 16, 0)


def quantize_kv_int4(kv, group: int):
    """Symmetric absmax int4 quantization with per-GROUP scales along D
    (the KIVI recipe, arXiv:2402.02750: sub-8-bit KV needs finer scale
    granularity than a whole row).

    kv: (B, H, S, D) fp K or V vectors.  ``group`` is the --serve-kv-
    group knob; the effective group is ``min(group, D)`` (so the
    default 32 stays valid on tiny test heads) and must divide D.
    Returns ``(packed, scales)``: packed (B, H, S, D//2) uint8
    (pack_int4 layout), scales (B, H, S, D // g_eff) fp32 with
    ``scale = max|group| / 7`` (0.0 for an all-zero group).

    Like ``quantize_kv``, the scale granularity never crosses a token
    row: a group's codes depend only on its OWN fp values, so int4
    stays write-GRANULARITY-INDEPENDENT — chunked prefill, one-token
    decode, speculative verify, and journal replay all land
    bit-identical pool bytes for the same token stream.  Rounding is
    ``jnp.round`` (round-half-even, deterministic across backends).
    """
    x = kv.astype(jnp.float32)
    D = x.shape[-1]
    g = min(group, D)
    xg = x.reshape(x.shape[:-1] + (D // g, g))
    amax = jnp.max(jnp.abs(xg), axis=-1)              # (B, H, S, G)
    scale = amax / 7.0
    safe = jnp.where(scale > 0.0, scale, 1.0)[..., None]
    codes = jnp.clip(jnp.round(xg / safe), -7, 7).astype(jnp.int32)
    return pack_int4(codes.reshape(x.shape)), scale


def dequantize_kv_int4(packed, scale, dt):
    """THE int4->fp dequantization (XLA gather path and the Pallas
    kernel's in-register step share this math): unpack the nibbles,
    multiply each D-group by its fp32 scale, cast to ``dt``.

    packed: (..., D//2) uint8, scale: (..., G) fp32 where G divides D.
    """
    codes = unpack_int4(packed)                       # (..., D) int32
    D = codes.shape[-1]
    G = scale.shape[-1]
    x = codes.reshape(codes.shape[:-1] + (G, D // G)).astype(jnp.float32)
    x = x * scale[..., None]
    return x.reshape(codes.shape).astype(dt)


def write_kv_quant_int4(pool, pool_scale, kv, block_table, positions,
                        valid):
    """``write_kv`` for the int4 pool: group-quantize the incoming rows
    (``quantize_kv_int4``) and scatter packed codes AND group scales
    through the same block/offset indexing.

    pool:        (num_blocks, H, block_size, D//2) uint8 packed codes
    pool_scale:  (num_blocks, H, block_size, G) fp32 group scales
    kv/block_table/positions/valid: as ``write_kv``

    The group size is implied by the pool geometry (``g = D // G``), so
    the write path can never disagree with ``init_pools`` about it.
    Returns ``(pool, pool_scale)`` updated.
    """
    bs = pool.shape[2]
    nb = block_table.shape[1]
    D = pool.shape[-1] * 2
    G = pool_scale.shape[-1]
    blk_idx = jnp.clip(positions // bs, 0, nb - 1)
    blk = jnp.take_along_axis(block_table, blk_idx, axis=1)      # (B, S)
    blk = jnp.where(valid, blk, NULL_BLOCK)
    off = positions % bs
    packed, scale = quantize_kv_int4(kv, D // G)
    vals = jnp.transpose(packed, (0, 2, 1, 3))                   # (B, S, H, D/2)
    sv = jnp.transpose(scale, (0, 2, 1, 3))                      # (B, S, H, G)
    return (pool.at[blk, :, off].set(vals),
            pool_scale.at[blk, :, off].set(sv))


def gather_kv(pool, block_table):
    """Reassemble a (B, H, L, D) contiguous view from the pool.

    L = NB * block_size; entry ``l`` holds the sequence's absolute
    position ``l`` (block tables are position-ordered), so the causal
    visibility test against absolute query positions carries over
    unchanged from the contiguous path.
    """
    g = pool[block_table]                        # (B, NB, H, bs, D)
    B, NB, H, bs, D = g.shape
    return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(B, H, NB * bs, D)


def paged_attention(q, ck, cv, q_positions, dt):
    """Masked causal attention over a gathered paged cache.

    q:           (B, H, S, D) query block (S=1 decode, S=chunk prefill)
    ck, cv:      (B, H, L, D) gathered keys/values (gather_kv)
    q_positions: (B, S) absolute positions of the queries
    dt:          compute dtype for the probability @ V contraction

    The math IS models/gpt.forward_with_cache's attention
    (``masked_softmax_attention``): the greedy token-parity test pins
    this path to the contiguous one bit-for-bit on CPU.

    MIXED-ROW CONTRACT: visibility is evaluated PER ROW against that
    row's own ``q_positions`` — nothing couples rows, so one batch may
    freely mix phases (decode rows querying a single position beside
    prefill rows querying a chunk at their own offsets, the
    --serve-mixed-batch fused dispatch).  Each row attends to exactly
    the prefix its positions admit, identical to what a single-phase
    dispatch would give it; tests/test_mixed_batch.py pins the fused
    and unfused paths token-identical in fp32 and int8.
    """
    L = ck.shape[2]
    col = jnp.arange(L)
    # (B, S, L): key position <= query position, per row
    vis = col[None, None, :] <= q_positions[:, :, None]
    return masked_softmax_attention(q, ck, cv, vis[:, None], dt)


def paged_attention_self_residual(q, ck, cv, q_positions, dt, k_new,
                                  v_new, scale=None):
    """``paged_attention`` with the KIVI fp-residual SELF lane: each
    query row's own key/value — the most recent token it can see — is
    taken from the in-register fp projections (``k_new``/``v_new``)
    instead of the quantized pool, folded into the SAME fp32 masked
    softmax so the lockstep with the kernel lowering holds.

    q, ck, cv, q_positions, dt: as ``paged_attention`` (ck/cv are the
    DEQUANTIZED gathered view of the int4 pool).
    k_new, v_new: (B, H, S, D) fp K/V of exactly the query tokens, the
    same tensors ``write_kv_quant_int4`` just scattered.  Query row
    ``s`` attends to its own position through these (exact fp score and
    value) and to every earlier position through the pool.

    Why the self lane only: by the time row ``s`` is a PAST lane of some
    later query, any fp window must have been re-derived from pool bytes
    to keep writes granularity-independent — but its own step still has
    the exact fp vectors in registers for free.  Each token is queried
    exactly once with them (prefix-cached positions are never
    re-queried), so the residual is dispatch-shape-invariant: chunked
    prefill, decode, and speculative verify score identically.

    The softmax denominator INCLUDES the self lane (it is the row's
    ``s == q_position`` column, overridden before scale+mask); rows
    whose position lies beyond the gathered view (q_pos >= L, a
    can't-happen guard) simply get no override.
    """
    scale = q.shape[-1] ** -0.5 if scale is None else scale
    L = ck.shape[2]
    col = jnp.arange(L)
    vis = col[None, None, :] <= q_positions[:, :, None]          # (B, S, L)
    self_m = (col[None, None, :] ==
              q_positions[:, :, None])[:, None]                  # (B, 1, S, L)
    s = jnp.einsum("bhsd,bhld->bhsl", q, ck).astype(jnp.float32)
    s_self = jnp.einsum("bhsd,bhsd->bhs", q, k_new).astype(jnp.float32)
    s = jnp.where(self_m, s_self[..., None], s)
    s = jnp.where(vis[:, None], s * scale, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1)
    p_self = jnp.sum(jnp.where(self_m, p, 0.0), axis=-1)         # (B, H, S)
    p_main = jnp.where(self_m, 0.0, p).astype(dt)
    return (jnp.einsum("bhsl,bhld->bhsd", p_main, cv)
            + p_self[..., None].astype(dt) * v_new.astype(dt))


def attend(q, k_pool, v_pool, block_table, lengths, dt, *,
           kernel: str = "xla", k_scale=None, v_scale=None,
           k_new=None, v_new=None):
    """THE paged-attention dispatch seam: one entry point, two lowering
    strategies, identical greedy tokens (tests/test_paged_kernel.py).

    q:           (B, H, S, D) queries at positions [lengths[b],
                 lengths[b] + S) — their K/V already scattered into the
                 pools (write_kv runs first)
    k/v_pool:    (num_blocks, H, block_size, D)
    block_table: (B, NB) int32
    lengths:     (B,) int32 cache entries already present per row
    kernel:      "xla" (gather + dense masked softmax) or "pallas"
                 (fused blockwise online softmax; interpret mode off
                 TPU).  Callers resolve "auto" BEFORE tracing via
                 ``resolve_kernel`` — this runs under jit, where the
                 choice must be static.
    k/v_scale:   fp32 scales when the pools hold quantized codes; both
                 or neither.  3-d ``(num_blocks, H, block_size)`` row
                 scales mean int8 codes (--serve-kv-dtype int8); 4-d
                 ``(num_blocks, H, block_size, G)`` group scales mean
                 int4 nibble-packed codes (--serve-kv-dtype int4) —
                 the scale RANK is the dtype discriminator, so no new
                 pool leaf key is needed and CoW/TP/partial-copy stay
                 generic.  Dequantization happens INSIDE the consume
                 path — in-register in the kernel, elementwise on the
                 gathered view here — so no fp pool ever materializes.
    k/v_new:     (B, H, S, D) fp K/V of the query tokens themselves
                 (the tensors the int4 write just quantized away) —
                 enables the fp-residual self lane
                 (``paged_attention_self_residual``).  int4 pools only;
                 both or neither.

    MIXED-ROW CONTRACT: ``lengths`` is per-row and the causal mask is
    built per row from it (``pos = lengths[:, None] + arange(S)``), so
    rows of ONE dispatch may sit at different phases — a decode row
    (one real lane) beside prefill rows carrying chunks at their own
    offsets, as the --serve-mixed-batch fused step packs them.  Rows
    with fewer than S real lanes are the CALLER'S job to mask: slack
    lanes must be marked invalid upstream so write_kv lands them in
    the null block, and their attention output is garbage to be
    discarded on host.  Both lowerings honor this identically (the
    Pallas path masks by the same per-row positions), pinned in fp32
    and int8 by tests/test_mixed_batch.py.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("quantized pools need both k_scale and v_scale")
    if (k_new is None) != (v_new is None):
        raise ValueError("fp residual needs both k_new and v_new")
    if k_new is not None and (k_scale is None or k_scale.ndim != 4):
        raise ValueError(
            "fp-residual k_new/v_new only apply to int4 (group-scaled) "
            "pools")
    if kernel == "pallas":
        from mpi_tensorflow_tpu.ops import paged_attention_kernel as pk

        interpret = jax.default_backend() != "tpu"
        fused = (pk.paged_decode_attention if q.shape[2] == 1
                 else pk.paged_prefill_attention)
        return fused(q, k_pool, v_pool, block_table, lengths,
                     interpret=interpret, k_scale=k_scale,
                     v_scale=v_scale, k_new=k_new, v_new=v_new)
    if kernel != "xla":
        raise ValueError(
            f"unresolved paged-attention kernel {kernel!r}: callers "
            f"resolve 'auto' host-side via resolve_kernel before tracing")
    S = q.shape[2]
    pos = lengths[:, None] + jnp.arange(S, dtype=jnp.int32)
    if k_scale is not None:
        # dequantize the gathered blocks elementwise, in lockstep with
        # the kernel's in-register step (dequantize_kv /
        # dequantize_kv_int4 are the shared contracts), BEFORE the
        # unchanged transpose/reshape + softmax
        ck = _gather_kv_dequant(k_pool, k_scale, block_table, q.dtype)
        cv = _gather_kv_dequant(v_pool, v_scale, block_table, q.dtype)
    else:
        ck = gather_kv(k_pool, block_table)
        cv = gather_kv(v_pool, block_table)
    if k_new is not None:
        return paged_attention_self_residual(q, ck, cv, pos, dt,
                                             k_new, v_new)
    return paged_attention(q, ck, cv, pos, dt)


def _gather_kv_dequant(pool, pool_scale, block_table, dt):
    """``gather_kv`` over a quantized pool: gather codes and scales
    through the same table, dequantize (int8 row scales or int4 group
    scales, discriminated by scale rank), reassemble the (B, H, L, D)
    view."""
    g = pool[block_table]                        # (B, NB, H, bs, D|D/2)
    gs = pool_scale[block_table]                 # (B, NB, H, bs[, G])
    if pool_scale.ndim == 4:
        g = dequantize_kv_int4(g, gs, dt)        # unpacks D/2 -> D
    else:
        g = dequantize_kv(g, gs, dt)
    B, NB, H, bs, D = g.shape
    return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(B, H, NB * bs, D)


def resolve_kernel(choice: str, cfg, block_size: int,
                   prefill_chunk: int = 64,
                   kv_dtype: str = "fp32",
                   kv_group: int = 32) -> str:
    """Resolve the ``--serve-kernel`` knob to a static lowering choice.

    - "xla"    -> "xla"     (always available, exact)
    - "pallas" -> "pallas"  (forced; interpret mode off TPU — the test
                             configuration)
    - "auto"   -> "pallas" on TPU when the compile probe
                  (paged_attention_kernel.kernel_supported) passes for
                  this model geometry, else "xla".  Off TPU, "auto"
                  stays on XLA: the interpreter is a correctness
                  vehicle, not a serving path.

    Host-side, once per engine: the resolved literal is baked into the
    jitted decode/prefill steps, so kernel choice can never add dispatch
    shapes or recompiles.
    """
    if choice in ("xla", "pallas"):
        return choice
    if choice != "auto":
        raise ValueError(
            f"serve kernel must be auto|xla|pallas, got {choice!r}")
    if jax.default_backend() != "tpu":
        return "xla"
    from mpi_tensorflow_tpu.ops import paged_attention_kernel as pk

    ok = pk.kernel_supported(jnp.dtype(cfg.dtype).name, cfg.heads,
                             cfg.head_dim, block_size, prefill_chunk,
                             kv_dtype, kv_group)
    return "pallas" if ok else "xla"
