"""Paged KV-cache device ops: block-table gather/scatter + attention.

The serving path (serving/) stores K/V in a fixed pool of
``(num_blocks, block_size, heads, head_dim)`` blocks instead of one
contiguous ``(B, H, max_len, D)`` buffer per request batch
(models/gpt.init_cache).  Each live sequence owns an ordered list of
pool blocks (its block table); block ``j`` of a sequence holds absolute
positions ``[j*block_size, (j+1)*block_size)``, so a gather of the table
reconstructs the contiguous layout and the attention math can stay
IDENTICAL to the contiguous decode path — the token-parity guarantee
(tests/test_serving.py) rests on that: same einsum contraction order,
same fp32 masked softmax, with padding lanes exactly zeroed
(``exp(finfo.min - max)`` underflows to 0.0, and 0-weighted V lanes add
exact 0.0 terms).

Block 0 is the NULL block: never allocated to a sequence, it absorbs
scatter writes from masked-out lanes (padded prefill tail, inactive
decode slots) so those lanes need no branching — garbage lands in
scratch, reads of it are masked by the causal visibility test.

All ops are plain XLA gather/scatter + einsum (TPU-lowerable, CPU-exact
for tests); a Pallas kernel can slot in behind ``paged_attention``
without touching callers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NULL_BLOCK = 0


def write_kv(pool, kv, block_table, positions, valid):
    """Scatter per-token K or V vectors into the block pool.

    pool:        (num_blocks, block_size, H, D)
    kv:          (B, H, S, D)  — new keys or values, head-major like the
                 qkv projection emits
    block_table: (B, NB) int32 — pool block ids, position order
    positions:   (B, S) int32 — absolute position of each token
    valid:       (B, S) bool — False lanes scatter into the null block

    Returns the updated pool.  Lanes of distinct sequences never collide
    (the allocator hands each block to one sequence); invalid lanes all
    land in block 0, whose contents are never read unmasked.
    """
    bs = pool.shape[1]
    nb = block_table.shape[1]
    blk_idx = jnp.clip(positions // bs, 0, nb - 1)
    blk = jnp.take_along_axis(block_table, blk_idx, axis=1)      # (B, S)
    blk = jnp.where(valid, blk, NULL_BLOCK)
    off = positions % bs
    vals = jnp.transpose(kv, (0, 2, 1, 3))                       # (B, S, H, D)
    return pool.at[blk, off].set(vals.astype(pool.dtype))


def gather_kv(pool, block_table):
    """Reassemble a (B, H, L, D) contiguous view from the pool.

    L = NB * block_size; entry ``l`` holds the sequence's absolute
    position ``l`` (block tables are position-ordered), so the causal
    visibility test against absolute query positions carries over
    unchanged from the contiguous path.
    """
    g = pool[block_table]                        # (B, NB, bs, H, D)
    B, NB, bs, H, D = g.shape
    return jnp.transpose(g.reshape(B, NB * bs, H, D), (0, 2, 1, 3))


def paged_attention(q, ck, cv, q_positions, dt):
    """Masked causal attention over a gathered paged cache.

    q:           (B, H, S, D) query block (S=1 decode, S=chunk prefill)
    ck, cv:      (B, H, L, D) gathered keys/values (gather_kv)
    q_positions: (B, S) absolute positions of the queries
    dt:          compute dtype for the probability @ V contraction

    Math kept in LOCKSTEP with models/gpt.forward_with_cache (cast to
    fp32 BEFORE the scale, scale folded into the masked select, softmax
    in fp32, probabilities cast back to ``dt``): the greedy token-parity
    test pins this path to the contiguous one bit-for-bit on CPU.
    """
    L = ck.shape[2]
    scale = q.shape[-1] ** -0.5
    col = jnp.arange(L)
    # (B, S, L): key position <= query position, per row
    vis = col[None, None, :] <= q_positions[:, :, None]
    s = jnp.einsum("bhsd,bhld->bhsl", q, ck).astype(jnp.float32)
    s = jnp.where(vis[:, None], s * scale, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    return jnp.einsum("bhsl,bhld->bhsd", p, cv)
