"""Reusable NN building blocks (NHWC, MXU-friendly).

The reference leans on the TF runtime's fused kernels (conv/pool/matmul via
``tf.nn.*``, mpipy.py:155-167).  Here the same roles are covered by XLA
primitives that tile directly onto the TPU MXU, shared across model families.
BatchNorm follows the standard training/inference split with running
statistics carried in the framework's ``model_state`` pytree.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def conv2d(x, w, stride: int = 1, padding: str = "SAME"):
    """NHWC/HWIO conv, stride symmetric."""
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def max_pool(x, window: int = 2, stride: int = 2, padding: str = "SAME"):
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding=padding,
    )


def global_avg_pool(x):
    """(N, H, W, C) -> (N, C)."""
    return jnp.mean(x, axis=(1, 2))


def bn_init(channels: int) -> dict:
    """Trainable BN params: scale (gamma) and offset (beta)."""
    return {"scale": jnp.ones((channels,)), "offset": jnp.zeros((channels,))}


def bn_state_init(channels: int) -> dict:
    """Running statistics, tracked in model_state (not trained)."""
    return {"mean": jnp.zeros((channels,)), "var": jnp.ones((channels,))}


def batch_norm(x, params: dict, state: dict, *, train: bool,
               momentum: float = 0.9, eps: float = 1e-5):
    """BatchNorm over (N, H, W) with running-stat EMA update.

    Returns ``(y, new_state)``.  In data-parallel training each shard
    normalizes with its per-shard batch statistics (standard DP BatchNorm);
    the train step averages the updated running stats across shards so the
    replicated state stays in sync.
    """
    # statistics and the normalization arithmetic run in float32 (variance
    # in bf16 loses too many mantissa bits — mixed-precision BN
    # convention), but the OUTPUT returns in the caller's compute dtype:
    # materializing fp32 activations under a bf16 policy would double the
    # HBM traffic of every BN in the network (the fp32 math here fuses
    # into the surrounding kernel; the bf16 store is what hits memory)
    in_dtype = x.dtype
    x = x.astype(jnp.float32)
    axes = tuple(range(x.ndim - 1))
    if train:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        new_state = {
            "mean": momentum * state["mean"] + (1 - momentum) * mean,
            "var": momentum * state["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = state["mean"], state["var"]
        new_state = state
    inv = lax.rsqrt(var + eps) * params["scale"]
    y = (x - mean) * inv + params["offset"]
    return y.astype(in_dtype), new_state
