"""Chunked tied-decoder softmax cross-entropy for MLM heads.

Role: the loss of the reference is a mean softmax-CE over class logits
(``/root/reference/mpipy.py:55-56``); BERT-MLM scales that to a 30k-class
vocabulary, where the naive formulation materializes a (B, S, V) fp32 logits
tensor (~1 GB at the bench shape 64x128x30522) that is written to HBM in the
forward pass and re-read three times (logsumexp, label gather, backward).
That HBM round-trip — not FLOPs — dominates the head's cost on TPU.

This op never materializes the full logits: an online-logsumexp
``lax.scan`` walks the tied decoder matrix in vocab chunks, keeping only a
(B, S) running (max, sumexp) pair in fp32, and the gold logit comes from a
direct gather of the label embedding rows.  The scan body is rematerialized
(``jax.checkpoint``) so the backward pass recomputes each chunk's logits
instead of saving them — peak live memory for the head is one
(B, S, chunk) tile.  Gradients flow through the scan by autodiff and are
mathematically the standard softmax-CE gradients.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG_BIG = -1e30   # bias for padded vocab rows: exp() underflows to exactly 0


def tied_softmax_ce(t, emb, out_b, labels, *, chunk: int = 2048,
                    dtype=None):
    """Per-position cross entropy of ``logits = t @ emb.T + out_b``.

    t:      (B, S, E) transformed hidden states (compute dtype, e.g. bf16)
    emb:    (V, E)    tied decoder matrix (the token embedding)
    out_b:  (V,)      output bias
    labels: (B, S)    int gold token ids
    Returns (B, S) fp32 ``logsumexp(logits) - logits[labels]`` without ever
    materializing an (..., V) array.  ``chunk`` is the vocab tile width.
    """
    B, S, E = t.shape
    V = emb.shape[0]
    dt = dtype or t.dtype
    nc = -(-V // chunk)
    vp = nc * chunk

    t = t.astype(dt)
    emb_c = jnp.pad(emb, ((0, vp - V), (0, 0))).astype(dt) \
        .reshape(nc, chunk, E)
    bias_c = jnp.pad(out_b.astype(jnp.float32), (0, vp - V),
                     constant_values=_NEG_BIG).reshape(nc, chunk)

    @jax.checkpoint
    def body(carry, xs):
        m, s = carry
        ec, bc = xs
        # one (B, S, chunk) logits tile; matmul in the compute dtype (MXU),
        # reduction bookkeeping in fp32
        lg = jnp.einsum("bse,ce->bsc", t, ec).astype(jnp.float32) + bc
        m_new = jnp.maximum(m, jnp.max(lg, axis=-1))
        s = s * jnp.exp(m - m_new) \
            + jnp.sum(jnp.exp(lg - m_new[..., None]), axis=-1)
        return (m_new, s), None

    init = (jnp.full((B, S), -jnp.inf, jnp.float32),
            jnp.zeros((B, S), jnp.float32))
    (m, s), _ = lax.scan(body, init, (emb_c, bias_c))
    logz = m + jnp.log(s)

    # gold logit: gather the label rows and contract — (B, S, E) transient,
    # same order of magnitude as the activations themselves
    gold = jnp.einsum("bse,bse->bs", t, emb[labels].astype(dt)) \
        .astype(jnp.float32) + out_b[labels].astype(jnp.float32)
    return logz - gold


def masked_mean_ce(ce, mask):
    """Mean CE over masked positions (mask: (B, S) bool/float)."""
    w = mask.astype(jnp.float32)
    return jnp.sum(ce * w) / jnp.maximum(jnp.sum(w), 1.0)


def gather_masked_rows(h, labels, mask, capacity: int):
    """Pack each row's masked positions into a fixed-width buffer.

    MLM computes loss only at masked positions (~15% of tokens), yet the
    naive head pays the vocab decoder at every position.  This packs row
    ``b``'s masked positions, first-come, into ``packed[b, :capacity]`` so
    the head transform + decoder run on ``capacity/S`` of the tokens — the
    TPU-shaped equivalent of BERT's ``max_predictions_per_seq``.  Working
    per row keeps the batch dim intact, so data-parallel sharding needs no
    cross-shard communication.  Positions beyond ``capacity`` get weight 0
    (choose ``capacity`` above the mask rate's tail and none are dropped).

    h: (B, S, E), labels/mask: (B, S).  Returns ``(packed_h (B, P, E),
    packed_labels (B, P), weights (B, P) fp32)``.
    """
    B, S, _ = h.shape
    pos = jnp.cumsum(mask.astype(jnp.int32), axis=1) - 1      # nth masked
    keep = mask & (pos < capacity)
    slot = jnp.where(keep, pos, capacity)                     # overflow col
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    cols = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    idx = jnp.zeros((B, capacity + 1), jnp.int32) \
        .at[rows, slot].set(cols)[:, :capacity]               # source col
    w = jnp.zeros((B, capacity + 1), jnp.bool_) \
        .at[rows, slot].set(keep)[:, :capacity]
    packed = jnp.take_along_axis(h, idx[..., None], axis=1)
    plabels = jnp.take_along_axis(labels, idx, axis=1)
    return packed, plabels, w.astype(jnp.float32)
