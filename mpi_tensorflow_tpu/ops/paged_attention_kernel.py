"""Fused Pallas paged-attention kernel — decode + chunked prefill.

The XLA paged path (ops/paged_attention.gather_kv + paged_attention)
materializes the whole padded contiguous KV view — two pool-sized
copies per layer per decode token, then a dense masked softmax over the
full bucketed table width.  This kernel reads the pool **blocks in
place** through the block table with an fp32 online softmax (the
PagedAttention / Flash-Decoding recipe, PAPERS.md): per grid step one
``(H, block_size, D)`` K block and V block stream HBM->VMEM, scores and
the running (m, l, acc) statistics stay in VMEM scratch, and the
``(B, H, NB*block_size, D)`` gathered view never exists.

Grid: ``(batch-slot, kv-block)``, kv-block innermost.  The block table
rides in as a **scalar-prefetch** operand, so each step's BlockSpec
index map picks the pool block to DMA (``bt[b, j]``) before the kernel
body runs — the Pallas pipeline turns the host-side block table into
device-side streamed reads with no gather materialization.

Early-out: a sequence of length ``len_b`` only has
``nlive = ceil((len_b + S) / block_size)`` live blocks.  Steps with
``j >= nlive`` clamp their index map to the last live block — Pallas
skips the DMA when the block index repeats — and ``pl.when`` skips the
compute, so per-token cost tracks **live tokens**, not the padded NB
bucket.

Masking contract (kept in LOCKSTEP with ops/paged_attention.
paged_attention — the parity suite in tests/test_paged_kernel.py pins
it): a key lane at absolute position ``col = j*block_size + offset`` is
visible iff ``col <= q_position``; invisible lanes score
``finfo(f32).min`` so their softmax weight underflows to exact 0.0.
Null-block (block 0) lanes and bucket-slack rows need no special
branch: null blocks only back table entries past a row's allocation,
whose positions the visibility test already rejects, and slack rows
(all-null table, length 0) produce garbage the engine discards —
exactly as on the XLA path.

Pool layout is head-major — ``(num_blocks, H, block_size, D)`` — so a
fetched block is ``(H, block_size, D)`` and both matmuls batch over H
with no in-kernel transpose (the official TPU paged-attention kernels
use the same orientation).

``kernel_supported()`` gates the TPU path behind a real compile probe
(toolchain regressions degrade to the XLA gather path);
``interpret=True`` runs the same kernel on CPU for the tier-1 parity
suite.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# stats rows are lane-broadcast to the f32 tile width, mirroring
# ops/flash_attention's LSE_LANES treatment of per-row statistics
STAT_LANES = 128


def _nlive(length, S: int, bs: int, NB: int):
    """Live block count for a row: lanes up to ``length + S`` hold real
    cache entries (the step's own tokens were scattered in by write_kv
    before attention), everything past them is null-block padding."""
    return jnp.clip((length + S + bs - 1) // bs, 1, NB)


def _dequant_int4_block(codes, scales, dt):
    """In-register int4 dequant of one fetched pool block — the exact
    ops/paged_attention.dequantize_kv_int4 contract (unpack split-half
    nibbles, sign-extend, scale per D-group).

    codes:  (H, bs, D//2) uint8 packed, scales: (H, bs, G) fp32.
    Returns (H, bs, D) in ``dt``.
    """
    c = codes.astype(jnp.int32)
    lo = c & 0xF
    hi = (c >> 4) & 0xF
    full = jnp.concatenate([lo, hi], axis=-1)          # (H, bs, D)
    full = full - jnp.where(full > 7, 16, 0)
    H, bs, D = full.shape
    G = scales.shape[-1]
    x = full.reshape(H, bs, G, D // G).astype(jnp.float32)
    x = x * scales[..., None]
    return x.reshape(H, bs, D).astype(dt)


def _paged_kernel(*refs, scale: float, block_size: int,
                  mode: str = "fp32", residual: bool = False):
    """One (batch-slot, kv-block) grid step of the online softmax.

    q_ref:  (1, H, S, D)   — the row's whole query block (revisited)
    k_ref:  (1, H, bs, D)  — pool block ``bt[b, min(j, nlive-1)]``
    v_ref:  (1, H, bs, D)
    o_ref:  (1, H, S, D)   — written once, at the last LIVE block
    scratch: acc (H, S, D) f32, m/l (H, S, STAT_LANES) f32

    ``mode`` selects the pool storage format the step consumes:

    - "int8" (--serve-kv-dtype int8): k/v_ref hold int8 codes and two
      extra refs ride between them — ks_ref/vs_ref, the ``(1, H, bs)``
      fp32 row scales of the SAME pool block (their BlockSpec shares
      the kv index map, so code block and scale block can never skew).
      The codes dequantize IN REGISTER right here — ``(codes.astype(f32)
      * scale).astype(q.dtype)``, the exact ops/paged_attention.
      dequantize_kv contract the XLA gather path applies elementwise —
      before the unchanged fp32 matmul/softmax; no fp pool ever
      materializes.
    - "int4" (--serve-kv-dtype int4): k/v_ref hold ``(1, H, bs, D//2)``
      nibble-packed uint8 codes, ks/vs_ref the ``(1, H, bs, G)`` fp32
      GROUP scales; ``_dequant_int4_block`` unpacks + dequantizes in
      register (the dequantize_kv_int4 contract).

    ``residual`` (int4 only) adds the KIVI fp-residual self lane: two
    more refs kn_ref/vn_ref — ``(1, H, S, D)`` fp K/V of exactly the
    query tokens (q_map-indexed, revisited each step).  Where a score
    column IS the query row's own position (``col == qpos``), the int4
    score is overridden with the exact fp dot product ``q · kn`` BEFORE
    scale+mask, and that column's probability weights ``vn`` instead of
    the dequantized pool V — the in-kernel mirror of
    ops/paged_attention.paged_attention_self_residual, so both
    lowerings agree within tolerance.  The self column lives in exactly
    one live grid step; the denominator (l) keeps its weight.
    """
    if mode == "int4" and residual:
        (bt_ref, len_ref, q_ref, kn_ref, vn_ref, k_ref, ks_ref, v_ref,
         vs_ref, o_ref, acc, m_scr, l_scr) = refs
    elif mode in ("int8", "int4"):
        (bt_ref, len_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref,
         acc, m_scr, l_scr) = refs
    else:
        (bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
         acc, m_scr, l_scr) = refs
    b = pl.program_id(0)
    j = pl.program_id(1)
    NB = pl.num_programs(1)
    H, S, D = q_ref.shape[1:]
    bs = block_size
    nlive = _nlive(len_ref[b], S, bs, NB)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, jnp.finfo(jnp.float32).min)
        l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(j < nlive)
    def _step():
        q = q_ref[0]                                   # (H, S, D)
        k = k_ref[0]                                   # (H, bs, D)
        v = v_ref[0]
        if mode == "int8":
            k = (k.astype(jnp.float32)
                 * ks_ref[0][..., None]).astype(q.dtype)
            v = (v.astype(jnp.float32)
                 * vs_ref[0][..., None]).astype(q.dtype)
        elif mode == "int4":
            k = _dequant_int4_block(k, ks_ref[0], q.dtype)
            v = _dequant_int4_block(v, vs_ref[0], q.dtype)
        s = lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)        # (H, S, bs)
        # visibility: key position <= query position, exactly the XLA
        # path's mask (q positions are lengths[b] + [0, S))
        col = j * bs + lax.broadcasted_iota(jnp.int32, (S, bs), 1)
        qpos = len_ref[b] + lax.broadcasted_iota(jnp.int32, (S, bs), 0)
        if residual:
            # fp self lane: exact q·k_new score for each row's own
            # column, overriding the int4 score BEFORE scale+mask
            self_m = col == qpos                       # (S, bs)
            kn = kn_ref[0]                             # (H, S, D)
            s_self = jnp.sum(q.astype(jnp.float32)
                             * kn.astype(jnp.float32), axis=-1)  # (H, S)
            s = jnp.where(self_m[None], s_self[:, :, None], s)
        s = jnp.where((col <= qpos)[None], s * scale,
                      jnp.finfo(jnp.float32).min)
        m_prev = m_scr[:, :, 0:1]                      # (H, S, 1)
        l_prev = l_scr[:, :, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                         # (H, S, bs)
        corr = jnp.exp(m_prev - m_new)                 # (H, S, 1)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        if residual:
            # the self column's weight multiplies the fp v_new row, not
            # the dequantized pool row; l keeps the full p sum
            p_main = jnp.where(self_m[None], 0.0, p)
            p_self = jnp.sum(jnp.where(self_m[None], p, 0.0),
                             axis=-1)                  # (H, S)
            vn = vn_ref[0]                             # (H, S, D)
            acc[:] = acc[:] * corr + lax.dot_general(
                p_main.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32) \
                + p_self[..., None] * vn.astype(jnp.float32)
        else:
            acc[:] = acc[:] * corr + lax.dot_general(
                p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)    # (H, S, D)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nlive - 1)
    def _emit():
        l = l_scr[:, :, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / l_safe).astype(o_ref.dtype)


def _paged_call(q, k_pool, v_pool, block_table, lengths, *,
                scale: float, interpret: bool,
                k_scale=None, v_scale=None, k_new=None, v_new=None):
    B, H, S, D = q.shape
    NB = block_table.shape[1]
    bs = k_pool.shape[2]
    if k_scale is None:
        mode = "fp32"
    elif k_scale.ndim == 4:
        mode = "int4"                    # group scales (.., bs, G)
    else:
        mode = "int8"                    # row scales (.., bs)
    residual = k_new is not None
    Dk = k_pool.shape[-1]                # D (fp/int8) or D//2 (int4)

    def kv_map(b, j, bt, lens):
        # clamp dead steps to the last live block: the repeated index
        # makes the Pallas pipeline skip the refetch, so padded table
        # width costs no HBM traffic
        jl = jnp.minimum(j, _nlive(lens[b], S, bs, NB) - 1)
        return (bt[b, jl], 0, 0, 0)

    def ks_map(b, j, bt, lens):
        # the scale sibling of kv_map: same clamped block id, 3-D block
        jl = jnp.minimum(j, _nlive(lens[b], S, bs, NB) - 1)
        return (bt[b, jl], 0, 0)

    def gs_map(b, j, bt, lens):
        # int4 group-scale sibling: same clamped block id, 4-D block
        jl = jnp.minimum(j, _nlive(lens[b], S, bs, NB) - 1)
        return (bt[b, jl], 0, 0, 0)

    def q_map(b, j, bt, lens):
        return (b, 0, 0, 0)

    if mode == "int8":
        # scales ride as regular streamed inputs indexed by the SAME
        # (clamped) block id as their code block — each grid step DMAs
        # the (1, H, bs) scale rows next to the (1, H, bs, D) codes
        in_specs = [
            pl.BlockSpec((1, H, S, D), q_map),
            pl.BlockSpec((1, H, bs, D), kv_map),
            pl.BlockSpec((1, H, bs), ks_map),
            pl.BlockSpec((1, H, bs, D), kv_map),
            pl.BlockSpec((1, H, bs), ks_map),
        ]
        operands = (q, k_pool, k_scale, v_pool, v_scale)
    elif mode == "int4":
        G = k_scale.shape[-1]
        in_specs = [pl.BlockSpec((1, H, S, D), q_map)]
        operands = [q]
        if residual:
            # fp residual K/V of the query tokens: q_map-indexed, so
            # every grid step revisits the row's own (1, H, S, D) block
            in_specs += [pl.BlockSpec((1, H, S, D), q_map),
                         pl.BlockSpec((1, H, S, D), q_map)]
            operands += [k_new, v_new]
        in_specs += [
            pl.BlockSpec((1, H, bs, Dk), kv_map),
            pl.BlockSpec((1, H, bs, G), gs_map),
            pl.BlockSpec((1, H, bs, Dk), kv_map),
            pl.BlockSpec((1, H, bs, G), gs_map),
        ]
        operands += [k_pool, k_scale, v_pool, v_scale]
        operands = tuple(operands)
    else:
        in_specs = [
            pl.BlockSpec((1, H, S, D), q_map),
            pl.BlockSpec((1, H, bs, D), kv_map),
            pl.BlockSpec((1, H, bs, D), kv_map),
        ]
        operands = (q, k_pool, v_pool)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, NB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, S, D), q_map),
        scratch_shapes=[
            pltpu.VMEM((H, S, D), jnp.float32),
            pltpu.VMEM((H, S, STAT_LANES), jnp.float32),
            pltpu.VMEM((H, S, STAT_LANES), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, scale=scale, block_size=bs,
                          mode=mode, residual=residual),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        interpret=interpret,
    )(block_table.astype(jnp.int32), lengths.astype(jnp.int32),
      *operands)


def paged_attention_kernel(q, k_pool, v_pool, block_table, lengths, *,
                           scale=None, interpret: bool = False,
                           k_scale=None, v_scale=None,
                           k_new=None, v_new=None):
    """Fused paged attention over pool blocks — no gathered view.

    q:           (B, H, S, D) queries; S=1 decode, S=chunk prefill
    k_pool:      (num_blocks, H, block_size, D) key pool (head-major,
                 ops/paged_attention.write_kv layout)
    v_pool:      idem, values
    block_table: (B, NB) int32 pool block ids, position order; entries
                 past a row's allocation must be the null block (0)
    lengths:     (B,) int32 cache entries already present per row; the
                 queries occupy absolute positions
                 [lengths[b], lengths[b] + S) and their K/V must already
                 be scattered into the pool (write_kv runs first)
    k/v_scale:   fp32 scales when the pools hold quantized codes (both
                 or neither): 3-d ``(num_blocks, H, block_size)`` row
                 scales = int8 codes; 4-d ``(num_blocks, H, block_size,
                 G)`` group scales = int4 nibble-packed codes (the
                 scale RANK discriminates, mirroring attend).  The
                 kernel streams them beside the code blocks and
                 dequantizes in register (see _paged_kernel)
    k/v_new:     (B, H, S, D) fp K/V of the query tokens (int4 only,
                 both or neither) — enables the fp-residual self lane

    Returns (B, H, S, D) in q.dtype.  Numerically this is the online-
    softmax evaluation of ops/paged_attention.paged_attention over the
    gathered view — token-parity on the greedy decode path is pinned by
    tests/test_paged_kernel.py.

    MIXED-ROW CONTRACT (lockstep with ops/paged_attention.attend): the
    grid is (batch row, kv block) and every visibility test uses that
    row's own ``lengths[b]``, so one dispatch may mix decode rows
    (one real lane) with prefill rows carrying chunks at different
    offsets — the --serve-mixed-batch fused step.  Slack lanes past a
    row's real count are the caller's to mask upstream (their K/V
    scatters to the null block); their output lanes are discarded on
    host.  tests/test_mixed_batch.py pins kernel-vs-XLA agreement on
    mixed batches in fp32 and int8.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("quantized pools need both k_scale and v_scale")
    if (k_new is None) != (v_new is None):
        raise ValueError("fp residual needs both k_new and v_new")
    if k_new is not None and (k_scale is None or k_scale.ndim != 4):
        raise ValueError(
            "fp-residual k_new/v_new only apply to int4 (group-scaled) "
            "pools")
    scale = q.shape[-1] ** -0.5 if scale is None else scale
    return _paged_call(q, k_pool, v_pool, block_table, lengths,
                       scale=scale, interpret=interpret,
                       k_scale=k_scale, v_scale=v_scale,
                       k_new=k_new, v_new=v_new)


def paged_decode_attention(q, k_pool, v_pool, block_table, lengths, *,
                           scale=None, interpret: bool = False,
                           k_scale=None, v_scale=None,
                           k_new=None, v_new=None):
    """Single-token decode specialization (S must be 1) — the serving
    hot path.  Thin wrapper so call sites (and probes) name the phase
    they are on; the grid/kernel body is shared with chunked prefill."""
    if q.shape[2] != 1:
        raise ValueError(f"decode takes one query token per row, got "
                         f"S={q.shape[2]} (use paged_prefill_attention)")
    return paged_attention_kernel(q, k_pool, v_pool, block_table,
                                  lengths, scale=scale,
                                  interpret=interpret,
                                  k_scale=k_scale, v_scale=v_scale,
                                  k_new=k_new, v_new=v_new)


def paged_prefill_attention(q, k_pool, v_pool, block_table, lengths, *,
                            scale=None, interpret: bool = False,
                            k_scale=None, v_scale=None,
                            k_new=None, v_new=None):
    """Chunked-prefill variant: S = chunk queries per row at positions
    [lengths[b], lengths[b] + S), causal within the chunk and over the
    cache via the same visibility test (col <= q position)."""
    return paged_attention_kernel(q, k_pool, v_pool, block_table,
                                  lengths, scale=scale,
                                  interpret=interpret,
                                  k_scale=k_scale, v_scale=v_scale,
                                  k_new=k_new, v_new=v_new)


@functools.lru_cache(maxsize=16)
def kernel_supported(dtype_name: str = "bfloat16", heads: int = 12,
                     head_dim: int = 64, block_size: int = 16,
                     prefill_chunk: int = 64,
                     kv_dtype: str = "fp32",
                     kv_group: int = 32) -> bool:
    """One-time probe per geometry: do the decode AND prefill kernels
    compile for this backend's Mosaic?  The serving dispatcher gates
    ``--serve-kernel auto`` on this (passing the dtype/heads/head_dim/
    block_size/prefill_chunk it will actually run) so a toolchain
    regression degrades to the XLA gather path instead of killing the
    engine.  The probe compiles decode (S=1) plus EVERY pow2 prefill
    bucket up to ``prefill_chunk`` — the exact S set the engine
    dispatches (engine._bucket), since S changes the kernel's tile
    shapes.  (Grid extents B/NB vary per dispatch too, but only as grid
    bounds and scalar-table width, not tile shapes — the fixed B=8/NB=4
    probe stands in for them.)  Mirrors
    ops/flash_attention.kernel_supported, including the operator kill
    switch: ``MPI_TF_TPU_DISABLE_PAGED_KERNEL=1`` force-disables the
    kernel (also the control arm for kernel A/B benches).  Checked
    inside the cached body, so it must be set before first use."""
    import os as _os
    import sys as _sys

    try:
        if _os.environ.get("MPI_TF_TPU_DISABLE_PAGED_KERNEL", "") \
                not in ("", "0"):
            print("[paged_attention_kernel] disabled via "
                  "MPI_TF_TPU_DISABLE_PAGED_KERNEL", file=_sys.stderr)
            return False
        if jax.devices()[0].platform != "tpu":
            return False
        dt = jnp.dtype(dtype_name)
        B, NB, bs = 8, 4, block_size
        # quantized modes swap the pool storage for codes + scale
        # siblings; Mosaic's sub-fp tiling rules differ from fp, so the
        # probe must compile the exact variant the engine will dispatch
        # — for int4 that is nibble-packed uint8 codes + 4-d group
        # scales + the fp-residual k_new/v_new operands
        if kv_dtype == "int4":
            g = min(kv_group, head_dim)
            pool = jnp.zeros((1 + B * NB, heads, bs, head_dim // 2),
                             jnp.uint8)
            scales = jnp.zeros((1 + B * NB, heads, bs, head_dim // g),
                               jnp.float32)
        elif kv_dtype == "int8":
            pool = jnp.zeros((1 + B * NB, heads, bs, head_dim), jnp.int8)
            scales = jnp.zeros((1 + B * NB, heads, bs), jnp.float32)
        else:
            pool = jnp.zeros((1 + B * NB, heads, bs, head_dim), dt)
            scales = None
        bt = jnp.arange(1, 1 + B * NB, dtype=jnp.int32).reshape(B, NB)
        lens = jnp.full((B,), bs, jnp.int32)
        chunks = []                       # 1 (decode) + pow2 buckets
        S = 1
        while S <= prefill_chunk:
            chunks.append(S)
            S *= 2
        for S in chunks:
            q = jnp.zeros((B, heads, S, head_dim), dt)
            kn = (jnp.zeros((B, heads, S, head_dim), dt)
                  if kv_dtype == "int4" else None)
            # graft-lint: jit-ok(compile probe: runs once at kernel resolve, not per step)
            jax.jit(functools.partial(
                paged_attention_kernel,
                k_scale=scales, v_scale=scales,
                k_new=kn, v_new=kn)).lower(
                q, pool, pool, bt, lens).compile()
        return True
    except Exception as e:   # noqa: BLE001 — any compile failure disables
        print(f"[paged_attention_kernel] Pallas probe failed for "
              f"{dtype_name} (H={heads}, D={head_dim}, bs={block_size}); "
              f"falling back to the XLA gather path ({e!r})",
              file=_sys.stderr)
        return False
