"""Flash attention — Pallas TPU kernels for the transformer hot op.

The reference delegates its hot ops to the TF runtime's fused C++ kernels
(SURVEY.md §2 E2); here the attention inner loop is hand-written Pallas:
Q/K/V stream HBM->VMEM in blocks, scores and the online softmax stay in
VMEM scratch, and the (S, S) score matrix is never materialized in HBM —
O(S) memory instead of O(S^2), with the matmuls on the MXU.

Forward AND backward are kernels (round 1 shipped only the forward):

- ``_flash_fwd_kernel``   online-softmax forward, also emitting the
                          per-row logsumexp needed by the backward;
- ``_flash_dq_kernel``    dq, streaming over kv blocks;
- ``_flash_dkdv_kernel``  dk and dv, streaming over q blocks.

Both backward kernels work in the transposed (block_k, block_q) score
orientation so the per-row statistics (lse, delta = rowsum(do*o)) enter as
(1, block_q) row vectors — broadcasts instead of sublane/lane relayouts —
and dq comes out of a dot_general contraction over the k dimension without
materializing a transpose.

Sequence lengths that are not multiples of the block size are padded and
masked (``s_valid``), so the kernels apply to any shape; ``interpret=True``
runs the same kernels on CPU for tests.

``blockwise_attention`` (pure-JAX online-softmax scan) remains as the
portable fallback; ``dense_attention`` (parallel/ring.py) is the reference
implementation.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_BIG = -1e30

# Per-row statistics (logsumexp, delta) cannot leave/enter kernels as flat
# (1, block_q) rows: Mosaic requires a block's sublane dim to be divisible
# by 8 or equal to the array dim, which a 1-row block over a (B*H, S) array
# violates whenever B*H > 1 (the round-2 probe shape hid exactly this).
# The forward therefore EMITS lse lane-broadcast as (B*H, S, LSE_LANES)
# and the backward CONSUMES it sublane-broadcast as (B*H, LSE_SUBLANES, S)
# — the latter orientation puts q-position on lanes, so the transposed
# (block_k, block_q) backward kernels read a native (1, block_q) row.
LSE_LANES = 128      # official TPU flash kernel uses MIN_BLOCK_SIZE lanes
LSE_SUBLANES = 8     # f32 sublane tile


# ---------------------------------------------------------------------------
# pure-JAX blockwise online softmax (portable fallback)
# ---------------------------------------------------------------------------

def blockwise_attention(q, k, v, *, causal: bool = False,
                        scale: Optional[float] = None, block_k: int = 128):
    """O(S * block_k) memory attention via lax.scan.  q,k,v: (B, H, S, D)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    S = k.shape[2]
    block_k = min(block_k, S)
    nk = -(-S // block_k)
    pad = nk * block_k - S
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = kp.reshape(*k.shape[:2], nk, block_k, k.shape[-1])
    vb = vp.reshape(*v.shape[:2], nk, block_k, v.shape[-1])
    qpos = jnp.arange(q.shape[2])[:, None]

    def body(carry, blk):
        o, m, l = carry
        kblk, vblk, i = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kblk,
                       preferred_element_type=jnp.float32) * scale
        kpos = i * block_k + jnp.arange(block_k)[None, :]
        invalid = kpos >= S
        if causal:
            invalid = invalid | (kpos > qpos)
        s = jnp.where(invalid, NEG_BIG, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vblk.dtype), vblk,
            preferred_element_type=jnp.float32)
        return (o, m_new, l), None

    o0 = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    m0 = jnp.full(q.shape[:3], NEG_BIG, jnp.float32)
    l0 = jnp.zeros(q.shape[:3], jnp.float32)
    kb_t = jnp.moveaxis(kb, 2, 0)
    vb_t = jnp.moveaxis(vb, 2, 0)
    (o, m, l), _ = lax.scan(body, (o0, m0, l0),
                            (kb_t, vb_t, jnp.arange(nk)))
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas forward kernel (emits out + logsumexp)
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr,
                      l_scr, *, scale: float, causal: bool, block_q: int,
                      block_k: int, s_valid: int, s_pad: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    last_k = nk - 1
    if causal:
        last_k = jnp.minimum(((qi + 1) * block_q - 1) // block_k, nk - 1)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(ki <= last_k)
    def _step():
        q = q_ref[0]                                   # (BQ, D)
        k = k_ref[0]                                   # (BK, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (BQ, BK)
        # s_valid/s_pad are static: skip mask construction entirely on the
        # hot aligned non-causal path
        if causal or s_valid < s_pad:
            kpos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            invalid = kpos >= s_valid
            if causal:
                qpos = qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                invalid = invalid | (kpos > qpos)
            s = jnp.where(invalid, NEG_BIG, s)
        m_prev = m_scr[:, 0:1]                         # (BQ, 1)
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                         # (BQ, BK)
        corr = jnp.exp(m_prev - m_new)                 # (BQ, 1)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == last_k)
    def _emit():
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / l_safe).astype(o_ref.dtype)
        # lse leaves in a lane-broadcast (block_q, LSE_LANES) tile: Mosaic
        # rejects blocks whose sublane dim is 1 over a larger array dim, so
        # a flat (1, block_q) row per program cannot be written from here
        lse_ref[0] = jnp.broadcast_to(m + jnp.log(l_safe),
                                      lse_ref.shape[1:])


def _flash_forward(q, k, v, *, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool, s_valid: int):
    """Padded inputs (S multiple of blocks) -> (out, lse)."""
    B, H, S, D = q.shape
    Dv = v.shape[-1]
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, Dv)
    grid = (B * H, S // block_q, S // block_k)

    kernel = functools.partial(_flash_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               s_valid=s_valid, s_pad=S)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((B * H, S, Dv), q.dtype),
                   jax.ShapeDtypeStruct((B * H, S, LSE_LANES), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, Dv), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, Dv), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, LSE_LANES), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, Dv), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, S, Dv), lse[:, :, 0].reshape(B, H, S)


# ---------------------------------------------------------------------------
# Pallas backward kernels
# ---------------------------------------------------------------------------

def _scores_t(k, q, v, do, lse_row, dsum_row, *, scale, causal, s_valid,
              s_pad, qi, ki, block_q, block_k):
    """Shared backward math in the transposed (BK, BQ) orientation:
    returns (p_t, ds_t).  Masks are built only when statically needed."""
    s_t = jax.lax.dot_general(
        k, q, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # (BK, BQ)
    invalid = None
    if causal or s_valid < s_pad:
        kpos = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 0)
        qpos = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 1)
        if s_valid < s_pad:
            # padded q columns MUST be masked here: their lse is NEG_BIG,
            # so the exp would overflow to inf and 0*inf = NaN would
            # poison dk/dv
            invalid = (kpos >= s_valid) | (qpos >= s_valid)
        if causal:
            c = kpos > qpos
            invalid = c if invalid is None else (invalid | c)
    p_t = jnp.exp(s_t - lse_row)                           # (BK, BQ)
    if invalid is not None:
        p_t = jnp.where(invalid, 0.0, p_t)
    dp_t = jax.lax.dot_general(
        v, do, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # (BK, BQ)
    ds_t = p_t * (dp_t - dsum_row) * scale
    return p_t, ds_t


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
                     dq_ref, acc, *, scale: float, causal: bool,
                     block_q: int, block_k: int, s_valid: int,
                     s_pad: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    last_k = nk - 1
    if causal:
        last_k = jnp.minimum(((qi + 1) * block_q - 1) // block_k, nk - 1)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    @pl.when(ki <= last_k)
    def _step():
        _, ds_t = _scores_t(
            k_ref[0], q_ref[0], v_ref[0], do_ref[0],
            lse_ref[0, 0:1], dsum_ref[0, 0:1], scale=scale, causal=causal,
            s_valid=s_valid, s_pad=s_pad, qi=qi, ki=ki,
            block_q=block_q, block_k=block_k)
        # dq_block = ds^T @ k == contract ds_t's BK dim with k's BK dim
        acc[:] += jax.lax.dot_general(
            ds_t, k_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (BQ, D)

    @pl.when(ki == last_k)
    def _emit():
        dq_ref[0] = acc[:].astype(dq_ref.dtype)


def _flash_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
                       dk_ref, dv_ref, acc_dk, acc_dv, *, scale: float,
                       causal: bool, block_q: int, block_k: int,
                       s_valid: int, s_pad: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    first_q = 0
    if causal:
        first_q = (ki * block_k) // block_q   # earlier q blocks are masked

    @pl.when(qi == 0)
    def _init():
        acc_dk[:] = jnp.zeros_like(acc_dk)
        acc_dv[:] = jnp.zeros_like(acc_dv)

    @pl.when(qi >= first_q)
    def _step():
        do = do_ref[0]
        p_t, ds_t = _scores_t(
            k_ref[0], q_ref[0], v_ref[0], do, lse_ref[0, 0:1],
            dsum_ref[0, 0:1],
            scale=scale, causal=causal, s_valid=s_valid, s_pad=s_pad,
            qi=qi, ki=ki, block_q=block_q, block_k=block_k)
        acc_dv[:] += jax.lax.dot_general(
            p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (BK, Dv)
        acc_dk[:] += jax.lax.dot_general(
            ds_t, q_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (BK, D)

    @pl.when(qi == nq - 1)
    def _emit():
        dk_ref[0] = acc_dk[:].astype(dk_ref.dtype)
        dv_ref[0] = acc_dv[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, do, *, causal: bool, scale: float,
                    block_q: int, block_k: int, interpret: bool,
                    s_valid: int):
    B, H, S, D = q.shape
    Dv = v.shape[-1]
    dsum = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                   axis=-1)                                # (B, H, S)
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, Dv)
    dof = do.reshape(B * H, S, Dv)
    # sublane-broadcast the per-row stats (see LSE_SUBLANES note up top);
    # XLA fuses the broadcast into the feeding computation
    lsef = jnp.broadcast_to(lse.reshape(B * H, 1, S),
                            (B * H, LSE_SUBLANES, S))
    dsumf = jnp.broadcast_to(dsum.reshape(B * H, 1, S),
                             (B * H, LSE_SUBLANES, S))

    row_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),              # q
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),              # k
        pl.BlockSpec((1, block_k, Dv), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),              # v
        pl.BlockSpec((1, block_q, Dv), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),              # do
        pl.BlockSpec((1, LSE_SUBLANES, block_q), lambda b, i, j: (b, 0, i),
                     memory_space=pltpu.VMEM),              # lse
        pl.BlockSpec((1, LSE_SUBLANES, block_q), lambda b, i, j: (b, 0, i),
                     memory_space=pltpu.VMEM),              # dsum
    ]
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          s_valid=s_valid, s_pad=S),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        grid=(B * H, S // block_q, S // block_k),
        in_specs=row_specs,
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, dsumf)

    col_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0),
                     memory_space=pltpu.VMEM),              # q (by q step)
        pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0),
                     memory_space=pltpu.VMEM),              # k (by k block)
        pl.BlockSpec((1, block_k, Dv), lambda b, j, i: (b, j, 0),
                     memory_space=pltpu.VMEM),              # v
        pl.BlockSpec((1, block_q, Dv), lambda b, j, i: (b, i, 0),
                     memory_space=pltpu.VMEM),              # do
        pl.BlockSpec((1, LSE_SUBLANES, block_q), lambda b, j, i: (b, 0, i),
                     memory_space=pltpu.VMEM),              # lse
        pl.BlockSpec((1, LSE_SUBLANES, block_q), lambda b, j, i: (b, 0, i),
                     memory_space=pltpu.VMEM),              # dsum
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkdv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          s_valid=s_valid, s_pad=S),
        out_shape=(jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
                   jax.ShapeDtypeStruct((B * H, S, Dv), v.dtype)),
        grid=(B * H, S // block_k, S // block_q),
        in_specs=col_specs,
        out_specs=(
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, Dv), lambda b, j, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, Dv), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, dsumf)
    return (dq.reshape(B, H, S, D), dk.reshape(B, H, S, D),
            dv.reshape(B, H, S, Dv))


# ---------------------------------------------------------------------------
# public entry: padding + custom VJP (Pallas forward AND backward)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=8)
def kernel_supported(dtype_name: str = "bfloat16",
                     causal: bool = False) -> bool:
    """One-time probe per (dtype, causal): do the fwd+bwd kernels compile
    for this backend's Mosaic?  Model code gates on this (passing the dtype
    and mask mode it will actually run) so a toolchain regression degrades
    to the XLA attention paths instead of killing the training step.  The
    probe shape fixes B*H=8 / S=256 / D=64: B*H > 1 exercises the
    batch-blocked (1, ...) specs real Mosaic constrains (a (1,1,S,D) probe
    green-lit round 2's kernels while every real model shape failed), and
    S=256 makes the grid multi-block in both q and k.

    ``MPI_TF_TPU_DISABLE_FLASH=1`` force-disables the kernels (operator
    kill switch; also the control arm for flash-vs-XLA A/B benches).
    Checked inside the cached body, so it must be set before first use."""
    import os as _os

    import jax as _jax

    try:
        if _os.environ.get("MPI_TF_TPU_DISABLE_FLASH", "") not in ("", "0"):
            import sys as _sys

            print("[flash_attention] disabled via MPI_TF_TPU_DISABLE_FLASH",
                  file=_sys.stderr)
            return False
        if _jax.devices()[0].platform != "tpu":
            return False
        q = jnp.zeros((2, 4, 256, 64), jnp.dtype(dtype_name))

        def f(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal).astype(jnp.float32))

        _jax.jit(_jax.grad(f, argnums=(0, 1, 2))).lower(q, q, q).compile()
        return True
    except Exception as e:   # noqa: BLE001 — any compile failure disables
        import sys as _sys

        print(f"[flash_attention] Pallas kernel probe failed for "
              f"{dtype_name} (causal={causal}); falling back to XLA "
              f"attention ({e!r})", file=_sys.stderr)
        return False


def _padded_len(S: int, block_q: int, block_k: int) -> int:
    """Pad to the lcm so BOTH grid dims divide evenly (padding to just
    the max would silently drop trailing blocks of the other size)."""
    blk = math.lcm(block_q, block_k)
    return -(-S // blk) * blk


def _pad_seq(x, S_pad):
    S = x.shape[2]
    if S == S_pad:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, S_pad - S), (0, 0)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Flash attention for any S (padded/masked to the block size).
    q,k,v: (B, H, S, D)."""
    out, _ = _fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret):
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    S = q.shape[2]
    S_pad = _padded_len(S, block_q, block_k)
    out_p, lse = _flash_forward(
        _pad_seq(q, S_pad), _pad_seq(k, S_pad), _pad_seq(v, S_pad),
        causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret, s_valid=S)
    return out_p[:, :, :S], lse


def _fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse_padded = _fwd_impl(q, k, v, causal, scale, block_q, block_k,
                                interpret)
    return out, (q, k, v, out, lse_padded)


def _bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse_padded = res   # lse keeps the padded length
    scale_ = scale if scale is not None else q.shape[-1] ** -0.5
    S = q.shape[2]
    S_pad = _padded_len(S, block_q, block_k)
    dq, dk, dv = _flash_backward(
        _pad_seq(q, S_pad), _pad_seq(k, S_pad), _pad_seq(v, S_pad),
        _pad_seq(out, S_pad), lse_padded, _pad_seq(g, S_pad),
        causal=causal, scale=scale_, block_q=block_q, block_k=block_k,
        interpret=interpret, s_valid=S)
    return dq[:, :, :S], dk[:, :, :S], dv[:, :, :S]


flash_attention.defvjp(_fwd, _bwd)
