"""Flash attention — Pallas TPU kernel for the transformer hot op.

The reference delegates its hot ops to the TF runtime's fused C++ kernels
(SURVEY.md §2 E2); here the attention inner loop is a hand-written Pallas
kernel: Q/K/V stream HBM->VMEM in blocks, scores and the online softmax stay
in VMEM scratch, and the (S, S) score matrix is never materialized in HBM —
O(S) memory instead of O(S^2), with the two matmuls on the MXU.

Three layers, all numerically equivalent (tests assert so):
- ``flash_attention``     public entry: Pallas forward + custom-VJP backward
                          (backward recomputes via the blockwise JAX path —
                          standard flash recomputation strategy);
- ``blockwise_attention`` pure-JAX online-softmax scan: memory-efficient,
                          differentiable, runs anywhere (CPU fallback and
                          the backward's recompute);
- ``dense_attention``     reference implementation (parallel/ring.py).

Grid layout: ``(batch*heads, q_blocks, kv_blocks)`` — the kv dimension is
innermost and TPU grids execute sequentially per core, so the VMEM scratch
accumulators persist across kv steps (init at kv==0, emit at the last block).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_BIG = -1e30


# ---------------------------------------------------------------------------
# pure-JAX blockwise online softmax (fallback + backward recompute)
# ---------------------------------------------------------------------------

def blockwise_attention(q, k, v, *, causal: bool = False,
                        scale: Optional[float] = None, block_k: int = 128):
    """O(S * block_k) memory attention via lax.scan.  q,k,v: (B, H, S, D)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    S = k.shape[2]
    block_k = min(block_k, S)
    nk = -(-S // block_k)
    pad = nk * block_k - S
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = kp.reshape(*k.shape[:2], nk, block_k, k.shape[-1])
    vb = vp.reshape(*v.shape[:2], nk, block_k, v.shape[-1])
    qpos = jnp.arange(q.shape[2])[:, None]

    def body(carry, blk):
        o, m, l = carry
        kblk, vblk, i = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kblk,
                       preferred_element_type=jnp.float32) * scale
        kpos = i * block_k + jnp.arange(block_k)[None, :]
        invalid = kpos >= S
        if causal:
            invalid = invalid | (kpos > qpos)
        s = jnp.where(invalid, NEG_BIG, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vblk.dtype), vblk,
            preferred_element_type=jnp.float32)
        return (o, m_new, l), None

    o0 = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    m0 = jnp.full(q.shape[:3], NEG_BIG, jnp.float32)
    l0 = jnp.zeros(q.shape[:3], jnp.float32)
    kb_t = jnp.moveaxis(kb, 2, 0)
    vb_t = jnp.moveaxis(vb, 2, 0)
    (o, m, l), _ = lax.scan(body, (o0, m0, l0),
                            (kb_t, vb_t, jnp.arange(nk)))
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc, m_scr, l_scr, *,
                  scale: float, causal: bool, block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    last_k = nk - 1
    if causal:
        # last kv block this q block needs (blocks past the diagonal skip)
        last_k = jnp.minimum(((qi + 1) * block_q - 1) // block_k, nk - 1)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(ki <= last_k)
    def _step():
        q = q_ref[0]                                   # (BQ, D)
        k = k_ref[0]                                   # (BK, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (BQ, BK)
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos > qpos, NEG_BIG, s)
        m_prev = m_scr[:, 0:1]                         # (BQ, 1)
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                         # (BQ, BK)
        corr = jnp.exp(m_prev - m_new)                 # (BQ, 1)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == last_k)
    def _emit():
        l = l_scr[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool):
    B, H, S, D = q.shape
    Dv = v.shape[-1]
    assert S % block_q == 0 and S % block_k == 0, (
        f"seq len {S} must be divisible by block sizes ({block_q},{block_k})")
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, Dv)
    grid = (B * H, S // block_q, S // block_k)

    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, S, Dv), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, Dv), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, Dv), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((block_q, Dv), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, S, Dv)


# ---------------------------------------------------------------------------
# public entry with custom VJP (flash forward, blockwise-recompute backward)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_forward(q, k, v, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret)


def _fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(q, k, v, causal=causal,
                                            scale=scale, block_k=block_k),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
