"""The host training loop — ``Cnn.run_process`` rebuilt (mpipy.py:76-93).

Semantics preserved from the reference:
- per-shard steps: ``epochs * local_train_size // batch_size`` (mpipy.py:79);
- sequential wraparound batching per shard, no shuffling (mpipy.py:80-82) —
  the global batch each step is the concatenation of every shard's 64-row
  window, exactly the rows the N MPI ranks would each slice;
- LR decay_steps = local train size (mpipy.py:62);
- the 50-step console trace, one line per shard (mpipy.py:87-90);
- parameter sync on the trace cadence in ``avg50`` mode (mpipy.py:91).

Deliberate divergences (documented in SURVEY.md §7):
- evaluation runs on the trace cadence, OFF the timed path — the reference
  evaluates the full test shard EVERY step (mpipy.py:86), an accidental cost
  excluded by BASELINE.md's measurement rule;
- ``psum`` mode replaces the reference's rank-0-only periodic averaging with
  per-step gradient allreduce (true synchronous SGD).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_tensorflow_tpu.config import Config
from mpi_tensorflow_tpu.data import mnist
from mpi_tensorflow_tpu.data.idx import error_rate
from mpi_tensorflow_tpu.models import cnn as cnn_lib
from mpi_tensorflow_tpu.parallel import mesh as meshlib
from mpi_tensorflow_tpu.train import evaluation, step as step_lib
from mpi_tensorflow_tpu.utils import logging as logs
from mpi_tensorflow_tpu.utils.profiling import StepTimer


@dataclasses.dataclass
class TrainResult:
    state: Any
    history: list          # [(step, global_test_error), ...]
    final_test_error: float
    images_per_sec: float
    step_time_seconds: float
    num_devices: int
    num_steps: int


def build_model(config: Config):
    dt = config.compute_dtype
    if config.model == "mnist_cnn":
        return cnn_lib.MnistCnn(
            image_size=config.image_size,
            num_channels=config.num_channels,
            num_classes=config.num_classes,
            dropout_rate=config.dropout_rate,
            compute_dtype=dt,
        )
    if config.model in ("resnet20", "resnet50"):
        from mpi_tensorflow_tpu.models import resnet

        return resnet.build(config.model, num_classes=config.num_classes,
                            compute_dtype=dt, remat=config.remat)
    if config.model == "vit":
        import dataclasses as dc

        from mpi_tensorflow_tpu.models import vit

        # channels follow the dataset (MNIST is single-channel); patch
        # size follows the input geometry: 28 -> 7px patches (4x4 grid),
        # 32 -> 4px (8x8 grid), else 16px (224 -> 14x14 grid)
        ch = 1 if config.dataset == "mnist" else 3
        patch = {28: 7, 32: 4}.get(config.image_size, 16)
        vcfg = dc.replace(vit.VIT_TINY_CIFAR,
                          image_size=config.image_size, patch=patch,
                          channels=ch, num_classes=config.num_classes,
                          dtype=dt, remat=config.remat)
        return vit.VisionTransformer(vcfg)
    if config.model == "bert_base":
        import dataclasses as dc

        from mpi_tensorflow_tpu.models import bert

        return bert.BertMlm(dc.replace(bert.BERT_BASE, dtype=dt))
    raise ValueError(f"unknown model {config.model!r}")


def load_dataset(config: Config, num_shards: int) -> mnist.Splits:
    """Dataset dispatch (the reference supports exactly one dataset,
    downloaded at mpipy.py:203-206; scale-out sets come from BASELINE.json)."""
    if config.dataset == "mnist":
        mnist.ensure_downloaded(config.data_dir)
        return mnist.load_splits(config.data_dir, num_shards=num_shards)
    if config.dataset == "cifar10":
        from mpi_tensorflow_tpu.data import cifar

        return cifar.load_splits(config.data_dir)
    if config.dataset == "imagenet_synthetic":
        from mpi_tensorflow_tpu.data import imagenet

        return imagenet.load_splits(config.data_dir)
    raise ValueError(f"unknown dataset {config.dataset!r} for the image loop")


def train(config: Config, model=None, splits: Optional[mnist.Splits] = None,
          mesh=None, verbose: bool = True) -> TrainResult:
    """End-to-end data-parallel training (the ``main()`` + ``Cnn`` path of
    the reference, mpipy.py:201-244, minus MPI)."""
    mesh = mesh if mesh is not None else meshlib.make_mesh(config.mesh_shape)
    ndev = meshlib.data_axis_size(mesh)
    model = model if model is not None else build_model(config)
    if splits is None:
        splits = load_dataset(config, ndev)
    b = config.batch_size

    # per-shard contiguous layout: shard i <- rows [i*localN, (i+1)*localN)
    local_n = splits.train_labels.shape[0] // ndev
    if local_n <= b:
        raise ValueError(f"local train size {local_n} must exceed batch {b}")
    tr_d = splits.train_data[:local_n * ndev].reshape(
        ndev, local_n, *splits.train_data.shape[1:])
    tr_l = splits.train_labels[:local_n * ndev].reshape(ndev, local_n)
    num_steps = config.epochs * local_n // b          # mpipy.py:79
    global_b = b * ndev

    state = step_lib.init_state(model, jax.random.key(config.seed))
    if config.sync == "psum":
        train_step = step_lib.make_train_step(model, config, mesh,
                                              decay_steps=local_n)
        eval_step = step_lib.make_eval_step(model, config, mesh)
    elif config.sync == "avg50":
        if config.grad_accum > 1:
            raise ValueError(
                "grad_accum applies to the psum (sync-SGD) and transformer "
                "paths; the avg50 fidelity mode reproduces the reference's "
                "per-rank batch-64 stepping, where microbatching has no "
                "counterpart")
        train_step = step_lib.make_local_train_step(model, config, mesh,
                                                    decay_steps=local_n)
        avg_step = step_lib.make_average_step(mesh)
        eval_step = step_lib.make_stacked_eval_step(model, config, mesh)
        state = step_lib.stack_state(state, ndev)
    else:
        raise ValueError(f"unknown sync mode {config.sync!r}")

    from mpi_tensorflow_tpu.train.ckpt_hooks import CheckpointHooks

    hooks = CheckpointHooks(config.checkpoint_dir, verbose=verbose)
    from mpi_tensorflow_tpu.utils import metrics_writer

    mw = metrics_writer.for_process(config.metrics_dir,
                                    meshlib.process_index())
    start_step = 0
    if config.resume:
        state, start_step = hooks.resume(state)

    batch_sharding = NamedSharding(mesh, P("data"))
    rng = config.make_train_key(config.seed + 1)
    timer = StepTimer(warmup_steps=1)
    history = []
    if verbose:
        logs.session_start(meshlib.process_index())

    fused = max(1, int(config.fused_steps or 1)) if config.sync == "psum" else 1
    eval_multi = None
    if fused > 1:
        eval_multi = step_lib.make_multi_eval_step(model, config, mesh)

    def run_eval(s, data=None):
        data = splits.test_data if data is None else data
        if eval_multi is not None:
            return evaluation.eval_in_batches_fused(
                lambda w: eval_multi(s.params, s.model_state, w),
                data, global_b)
        predict = lambda b: eval_step(s.params, s.model_state, b)
        return evaluation.eval_in_batches(predict, data, global_b)

    # validation-based early stopping: the reference scatters val shards and
    # never reads them (mpipy.py:236-241); patience > 0 puts them to work
    es_patience = int(getattr(config, "early_stop_patience", 0) or 0)
    es_usable = es_patience > 0 and splits.val_labels.shape[0] >= global_b
    if es_patience > 0 and not es_usable and verbose:
        print(f"[early-stop] DISABLED: validation split "
              f"({splits.val_labels.shape[0]} rows) is smaller than the "
              f"global batch ({global_b}) — --early-stop-patience ignored")
    es_best, es_bad, stop_early = [float("inf")], [0], [False]

    def check_early_stop(s) -> bool:
        if not es_usable:
            return False
        preds = run_eval(s, splits.val_data)
        val_err = error_rate(preds, splits.val_labels)
        if verbose:
            logs.val_trace(meshlib.process_index(), val_err)
        if val_err < es_best[0] - 1e-12:
            es_best[0], es_bad[0] = val_err, 0
            return False
        es_bad[0] += 1
        if es_bad[0] >= es_patience:
            if verbose:
                print(f"[early-stop] validation error has not improved for "
                      f"{es_patience} trace points (best {es_best[0]:.2f}%)")
            return True
        return False

    pending = 0
    if fused > 1:
        # +1: trace points land on completed step t with t % log_every == 0,
        # so the first window is log_every+1 steps; the fixed K plus the
        # n_valid mask keeps every window on ONE compiled shape
        fused_k = fused + 1
        multi_step = step_lib.make_multi_train_step(
            model, config, mesh, decay_steps=local_n, masked=True)
        fused_sharding = NamedSharding(mesh, P(None, "data"))

    def slice_step(t):
        # single window of width 1 — the wraparound-offset semantics live
        # in data/prefetch.assemble_window only (one place per language)
        from mpi_tensorflow_tpu.data import prefetch

        bs, ls = prefetch.assemble_window(tr_d, tr_l, t, 1, 1, b)
        return bs[0], ls[0]

    def window_schedule():
        """(starts, widths): fixed-K windows ending exactly on the 50-step
        trace cadence, so the eval/avg/checkpoint schedule matches the
        per-step loop."""
        L = config.log_every
        starts, widths = [], []
        t = start_step
        while t < num_steps:
            # next step index at which the per-step loop would trace
            T = min(((max(t, 1) + L - 1) // L) * L, num_steps - 1)
            w = min(T - t + 1, fused_k)
            starts.append(t)
            widths.append(w)
            t += w
        return starts, widths

    def run_steps_fused():
        """One device dispatch per window of steps (lax.scan inside,
        train/step.py make_multi_train_step): same step semantics, none of
        the per-step dispatch latency.  Window assembly (a strided gather)
        runs ahead on a background worker — native C++ when available
        (data/prefetch.py) — overlapping the device's previous window."""
        nonlocal state, pending
        from mpi_tensorflow_tpu.data import prefetch

        L = config.log_every
        starts, widths = window_schedule()
        pf = None
        if config.prefetch != "off":
            force = None if config.prefetch == "auto" else config.prefetch
            pf = prefetch.make_prefetcher(tr_d, tr_l, starts, widths,
                                          fused_k, b, force=force)
        try:
            for t0, w in zip(starts, widths):
                if pf is not None:
                    bs, ls, _ = pf.next()
                else:
                    bs, ls = prefetch.assemble_window(tr_d, tr_l, t0, w,
                                                      fused_k, b)
                bdev = jax.device_put(bs, fused_sharding)
                ldev = jax.device_put(ls, fused_sharding)
                state, _ = multi_step(state, bdev, ldev, rng, w)
                pending += w
                t_done = t0 + w - 1

                if hooks.stop_now(t_done):
                    hooks.preempt_save(state, t_done)
                    break

                if (t_done % L == 0 and t_done > 0) \
                        or t_done == num_steps - 1:
                    trace_point(t_done)
                    if stop_early[0]:
                        break
                    if t_done != num_steps - 1 and hooks.stop_agreed(t_done):
                        hooks.preempt_save(state, t_done,
                                           already_queued=True)
                        break
        finally:
            if pf is not None:
                pf.close()

    def trace_point(t):
        nonlocal state, pending
        jax.block_until_ready(state)                   # close the timed span
        timer.stop(pending)
        pending = 0
        preds = run_eval(state)
        global_err = error_rate(preds, splits.test_labels)
        history.append((t, global_err))
        mw.scalar("eval/test_error_pct", global_err, t)
        if verbose:
            # one line per shard, the reference's per-rank trace
            for r, e in enumerate(evaluation.shard_error_rates(
                    preds, splits.test_labels, ndev)):
                logs.step_trace(r, t, e)
        if config.sync == "avg50" and t != num_steps - 1:  # mpipy.py:91
            state = avg_step(state)
        if t != num_steps - 1:   # a verdict at the final step is dead work
            stop_early[0] = check_early_stop(state)
        # async: snapshot now (cheap), write on the worker thread — the
        # train loop does not block on disk at trace points
        hooks.save_async(state, t)
        timer.start()

    def run_steps():
        nonlocal state, pending
        for t in range(start_step, num_steps):
            batch, labels = slice_step(t)
            batch = jax.device_put(batch, batch_sharding)
            labels = jax.device_put(labels, batch_sharding)
            state, metrics = train_step(state, batch, labels, rng)
            pending += 1

            if hooks.stop_now(t):
                hooks.preempt_save(state, t)
                break

            if (t > 0 and t % config.log_every == 0) or t == num_steps - 1:
                trace_point(t)
                if stop_early[0]:
                    break
                if t != num_steps - 1 and hooks.stop_agreed(t):
                    hooks.preempt_save(state, t, already_queued=True)
                    break

    timer.start()
    try:
        if fused > 1:
            run_steps_fused()
        else:
            run_steps()
        ips_t = timer.images_per_sec(global_b)
        if ips_t == ips_t:   # skip the NaN of a run with no timed span
            mw.scalar("perf/images_per_sec", ips_t, num_steps)
    finally:
        hooks.close()   # every queued checkpoint is on disk before return
        mw.close()      # flush TB events even on an exceptional exit
    final_err = history[-1][1] if history else float("nan")
    ips = timer.images_per_sec(global_b)
    if verbose:
        logs.timing_summary(ips, timer.mean_step_seconds * 1e3, ndev)
    return TrainResult(
        state=state, history=history, final_test_error=final_err,
        images_per_sec=ips, step_time_seconds=timer.mean_step_seconds,
        num_devices=ndev, num_steps=num_steps,
    )
