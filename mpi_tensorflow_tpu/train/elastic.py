"""Elastic recovery: restart training from the latest checkpoint after a
transient failure.

The reference has no failure handling — a failed download raises an
undefined ``DownloadError`` NameError (mpipy.py:196-198) and any rank
death kills the MPI job with all progress lost (SURVEY.md §5 failure
row).  The TPU-native recovery story has three layers:

1. **Graceful preemption** (train/preemption.py + ckpt_hooks.py): SIGTERM
   -> multi-host-agreed stop -> durable checkpoint -> clean exit.
2. **Crash durability** (train/checkpoint.py): trace-cadence async saves
   mean at most ``log_every`` steps are lost to a hard kill; the sharded
   format's meta.json commit marker makes torn writes invisible to
   ``latest_step``.
3. **Restart supervision** (this module): ``run_with_recovery`` re-invokes
   the training entry point after a *transient* failure (device loss,
   distributed-init hiccup, preemption eviction), resuming from the latest
   committpoint.  Mesh-shape changes across restarts are supported by
   ``restore_sharded`` (a job evicted from 8 chips can resume on 4).

The supervisor deliberately re-raises on non-transient errors (ValueError
etc. — a config bug restarted forever is a worse failure mode) and bounds
restart count.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable, Optional, Tuple

# error types that MAY indicate transient infrastructure failure; jax
# surfaces device loss / RPC failures as RuntimeError
# (jaxlib.xla_extension.XlaRuntimeError subclasses it) — is_transient()
# additionally inspects the message so deterministic RuntimeErrors
# (compile OOM, shape bugs) fail fast instead of being retried
TRANSIENT_ERRORS: Tuple[type, ...] = (RuntimeError, OSError, ConnectionError)

_TRANSIENT_MARKERS = ("device_lost", "device lost", "unavailable",
                      "aborted", "preempt", "connection", "socket",
                      "deadline", "heartbeat", "simulated")
_PERMANENT_MARKERS = ("resource_exhausted", "out of memory", "oom",
                      "invalid_argument", "unimplemented", "failed_precond")

# canonical absl/gRPC status codes, the stable contract PJRT errors carry
# ("UNAVAILABLE: socket closed ...") — classified FIRST, before any
# free-text matching, so a reworded message body cannot flip the verdict.
# UNKNOWN is deliberately in neither set: it is gRPC's catch-all for
# arbitrary server-side exceptions (often a peer's deterministic bug), so
# it falls through to the substring heuristics instead of force-retrying
_TRANSIENT_CODES = frozenset({"UNAVAILABLE", "ABORTED", "DEADLINE_EXCEEDED",
                              "CANCELLED"})
_PERMANENT_CODES = frozenset({"RESOURCE_EXHAUSTED", "INVALID_ARGUMENT",
                              "UNIMPLEMENTED", "FAILED_PRECONDITION",
                              "NOT_FOUND", "ALREADY_EXISTS", "OUT_OF_RANGE",
                              "PERMISSION_DENIED", "UNAUTHENTICATED"})


def _status_code(e: BaseException) -> Optional[str]:
    """Leading canonical status code of a PJRT/RPC error message, if any."""
    head = str(e).split(":", 1)[0].strip().upper().replace(" ", "_")
    if head in _TRANSIENT_CODES or head in _PERMANENT_CODES:
        return head
    return None


def is_transient(e: BaseException) -> bool:
    """Worth retrying?  Classified by exception TYPE first (OS/connection
    errors), then by the canonical status code PJRT errors carry, and only
    then by message substrings — so the free-text fallback cannot override
    a structured verdict, and a reworded device-loss message still retries
    as long as its status code survives."""
    if isinstance(e, (OSError, ConnectionError)):
        return True
    code = _status_code(e)
    if code is not None:
        return code in _TRANSIENT_CODES
    msg = str(e).lower()
    if any(m in msg for m in _PERMANENT_MARKERS):
        return False
    return any(m in msg for m in _TRANSIENT_MARKERS)


def run_with_recovery(train_fn: Callable[[], Any], *,
                      max_restarts: int = 3,
                      backoff_seconds: float = 5.0,
                      transient: Iterable[type] = TRANSIENT_ERRORS,
                      is_transient_fn: Callable[[BaseException],
                                                bool] = is_transient,
                      on_restart: Optional[Callable[[int, BaseException],
                                                    None]] = None) -> Any:
    """Run ``train_fn`` (a zero-arg closure over a --resume-enabled config),
    restarting it after transient failures.

    ``train_fn`` must be idempotent-from-checkpoint: constructed so each
    invocation resumes from the latest committed checkpoint (the loops'
    ``config.resume`` path).  ``on_restart(attempt, error)`` is the hook
    for runtime re-initialization before the retry.  Non-transient
    exceptions propagate immediately; the restart budget re-raises the
    ORIGINAL exception (no type laundering).
    """
    transient = tuple(transient)
    attempt = 0
    while True:
        try:
            return train_fn()
        except transient as e:
            if not is_transient_fn(e):
                raise
            attempt += 1
            if attempt > max_restarts:
                print(f"[elastic] giving up after {max_restarts} restarts")
                raise
            print(f"[elastic] transient failure ({e!r}); restart "
                  f"{attempt}/{max_restarts} in {backoff_seconds:.0f}s")
            if on_restart is not None:
                on_restart(attempt, e)
            time.sleep(backoff_seconds)
