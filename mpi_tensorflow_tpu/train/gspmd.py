"""GSPMD train step: multi-axis (DP x TP x SP) training for transformers.

The explicit ``shard_map`` step in ``train/step.py`` reproduces the
reference's data-parallel semantics with a hand-placed allreduce.  For the
transformer families the idiomatic TPU path is compiler-side partitioning:
parameters are *placed* per the logical sharding rules
(parallel/sharding_rules.py), activations are constrained inside the model,
and XLA GSPMD inserts every collective (gradient allreduce over ``data``,
row-parallel psums over ``model``) — except ring attention, which is
inherently manual and runs as an inner ``shard_map`` over ``seq``
(parallel/ring.py).

One jitted, donated-buffer function is the full training step on any mesh
shape from a single chip to a pod slice.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi_tensorflow_tpu.models import base
from mpi_tensorflow_tpu.parallel import fsdp as fsdp_lib
from mpi_tensorflow_tpu.parallel import mesh as meshlib
from mpi_tensorflow_tpu.parallel import sharding_rules as rules_lib


class GspmdState(NamedTuple):
    params: Any
    opt: Any
    model_state: Any
    step: jnp.ndarray


class MasterOpt(NamedTuple):
    """Mixed-precision optimizer state: fp32 master weights + the inner
    optimizer's state (which lives on the masters)."""
    master: Any
    inner: Any


def init_gspmd_state(model, tx: optax.GradientTransformation, rng,
                     mesh: Mesh, rules: Optional[dict] = None,
                     param_dtype=None) -> GspmdState:
    """Initialize and *place* the train state: params go to their mesh
    shards; optimizer moments inherit the param shardings (zeros_like
    preserves sharding).

    ``param_dtype`` (e.g. ``jnp.bfloat16``) stores the *live* parameters in
    that dtype — halving weight HBM traffic per matmul — while the
    optimizer keeps fp32 master copies and applies updates to them
    (``MasterOpt``).  When the model's COMPUTE dtype is bf16 this leaves
    compute numerics unchanged (the model casts weights to bf16 at use
    either way); pairing bf16 params with fp32 compute changes what the
    matmuls see and is rejected by bench.py's flag validation.
    """
    params = model.init(rng)
    params = rules_lib.shard_tree(params, model.logical_axes(), mesh, rules)
    mstate = base.init_model_state(model)
    if param_dtype is None:
        opt = tx.init(params)
        return GspmdState(params, opt, mstate, jnp.zeros((), jnp.int32))
    master = params   # fp32, placed
    live = jax.tree.map(
        lambda x: x.astype(param_dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    opt = MasterOpt(master=master, inner=tx.init(master))
    return GspmdState(live, opt, mstate, jnp.zeros((), jnp.int32))


def _place_replicated(tree: Any, mesh: Mesh) -> Any:
    """Pin any leaf without an explicit mesh placement to full replication
    (optimizer step counters, model state, the step scalar)."""
    rep = meshlib.replicated(mesh)

    def place(x):
        if isinstance(getattr(x, "sharding", None), NamedSharding):
            return x
        return jax.device_put(jnp.asarray(x), rep)

    return jax.tree.map(place, tree)


def init_fsdp_state(model, tx: optax.GradientTransformation, rng,
                    mesh: Mesh, rules: Optional[dict] = None,
                    axis: str = "data",
                    min_size: int = fsdp_lib.DEFAULT_MIN_SIZE) -> GspmdState:
    """ZeRO/FSDP initialization: parameters — and therefore the optimizer
    moments created from them — live sharded along ``axis``.  TP axes from
    the model's logical rules are kept; FSDP claims a second dimension
    (parallel/fsdp.py)."""
    params = model.init(rng)
    logical = model.logical_axes() if hasattr(model, "logical_axes") else None
    specs = fsdp_lib.fsdp_tree_specs(params, mesh, logical, rules,
                                     axis=axis, min_size=min_size)
    params = fsdp_lib.shard_params(params, mesh, specs)
    opt = _place_replicated(tx.init(params), mesh)
    mstate = _place_replicated(base.init_model_state(model), mesh)
    step = jax.device_put(jnp.zeros((), jnp.int32), meshlib.replicated(mesh))
    return GspmdState(params, opt, mstate, step)


def init_zero1_state(model, tx: optax.GradientTransformation, rng,
                     mesh: Mesh, rules: Optional[dict] = None,
                     axis: str = "data",
                     min_size: int = fsdp_lib.DEFAULT_MIN_SIZE) -> GspmdState:
    """ZeRO-1 initialization: parameters keep their rule-table placement
    (pipe-sharded stages, TP axes, data-replicated weights) — so the
    manual pipeline schedules' shard_map in_specs still hold — while the
    optimizer moments are additionally sharded over ``axis``
    (parallel/fsdp.py::zero1_shard_opt).  Pass the result as
    ``state_template`` to pin the moments to their shards across steps."""
    st = init_gspmd_state(model, tx, rng, mesh, rules)
    # shard_tree leaves un-ruled leaves (layernorm scales, counters)
    # unplaced; a state used as ``state_template`` must carry an explicit
    # mesh placement on EVERY leaf or out_shardings conflicts
    params = _place_replicated(st.params, mesh)
    opt = fsdp_lib.zero1_shard_opt(_place_replicated(st.opt, mesh),
                                   mesh, axis=axis, min_size=min_size)
    mstate = _place_replicated(st.model_state, mesh)
    step = jax.device_put(st.step, meshlib.replicated(mesh))
    return GspmdState(params, opt, mstate, step)


def grad_accum_dtype(opt_state) -> Optional[Any]:
    """Accumulation dtype for scanned microbatch gradients: fp32 when the
    optimizer keeps fp32 masters (live params — and thus per-microbatch
    grads — are low precision), None (= grad dtype) otherwise."""
    return jnp.float32 if isinstance(opt_state, MasterOpt) else None


def shard_batch(tree: Any, mesh: Mesh):
    """Place host batch arrays: leading dim over ``data``, second dim over
    ``seq`` when the mesh has one (token grids are (B, S))."""
    def place(x):
        axes = [None, None]
        if mesh.shape.get("data", 1) > 1:
            axes[0] = "data"
        if x.ndim >= 2 and mesh.shape.get("seq", 1) > 1 \
                and x.shape[1] % mesh.shape["seq"] == 0:
            axes[1] = "seq"
        return jax.device_put(x, NamedSharding(mesh, P(*axes[:x.ndim])))

    return jax.tree.map(place, tree)


def make_gspmd_train_step(model, mesh: Mesh,
                          tx: optax.GradientTransformation,
                          state_template: Optional[GspmdState] = None,
                          grad_accum: int = 1):
    """Full training step: loss -> grads -> optax update, all under one jit.

    ``model.loss(params, model_state, batch, labels, rng=..., train=True)``
    supplies the objective (the MLM loss for BERT).

    ``state_template`` (an initialized, placed state) pins the output state
    back to its input shardings — required for FSDP, where the compiler
    must re-scatter parameters and moments after the update instead of
    leaving them gathered.

    ``grad_accum > 1`` splits the batch into that many microbatches and
    accumulates their mean gradient in an on-device ``lax.scan`` before the
    single optimizer update (same semantics, 1/A the activation memory).
    """
    accum = max(1, int(grad_accum))

    def step(state: GspmdState, batch, labels, rng):
        rng = jax.random.fold_in(rng, state.step)

        def lf(params, b, l, r):
            loss, ms = model.loss(params, state.model_state, b, l,
                                  rng=r, train=True)
            return loss, ms

        if accum == 1:
            (loss, ms), grads = jax.value_and_grad(lf, has_aux=True)(
                state.params, batch, labels, rng)
        else:
            def split(x):
                if x.shape[0] % accum:
                    raise ValueError(
                        f"batch dim {x.shape[0]} not divisible by "
                        f"grad_accum {accum}")
                return x.reshape(accum, x.shape[0] // accum, *x.shape[1:])

            mb = jax.tree.map(split, batch)
            ml = jax.tree.map(split, labels)

            # with bf16 live params the per-microbatch grads come out bf16;
            # accumulate in fp32 or small contributions are swallowed —
            # exactly the error mode the fp32 masters exist to avoid
            acc_dtype = grad_accum_dtype(state.opt)

            def up(g):
                if acc_dtype and jnp.issubdtype(g.dtype, jnp.floating):
                    return g.astype(acc_dtype)
                return g

            def micro(carry, xs):
                g_acc, l_acc, mstate = carry
                b, l, i = xs

                def lf_ms(params, b, l, r):
                    # thread the running model state microbatch-to-
                    # microbatch (matches the accum=1 path and the psum
                    # implementation in step.py)
                    return model.loss(params, mstate, b, l, rng=r,
                                      train=True)

                (loss, ms), g = jax.value_and_grad(lf_ms, has_aux=True)(
                    state.params, b, l, jax.random.fold_in(rng, i))
                return (jax.tree.map(lambda a, x: a + up(x), g_acc, g),
                        l_acc + loss, ms), None

            zeros = jax.tree.map(lambda x: jnp.zeros_like(up(x)),
                                 state.params)
            (grads, loss, ms), _ = lax.scan(
                micro, (zeros, jnp.zeros(()), state.model_state),
                (mb, ml, jnp.arange(accum)))
            grads = jax.tree.map(lambda x: x / accum, grads)
            loss = loss / accum

        if isinstance(state.opt, MasterOpt):
            # mixed precision: grads (param dtype) -> fp32, update the fp32
            # masters, re-emit the live params in their storage dtype
            g32 = jax.tree.map(
                lambda g: g.astype(jnp.float32)
                if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)
            updates, inner = tx.update(g32, state.opt.inner,
                                       state.opt.master)
            master = optax.apply_updates(state.opt.master, updates)
            params = jax.tree.map(
                lambda m, p: m.astype(p.dtype), master, state.params)
            return (GspmdState(params, MasterOpt(master, inner), ms,
                               state.step + 1), {"loss": loss})
        updates, opt = tx.update(grads, state.opt, state.params)
        params = optax.apply_updates(state.params, updates)
        return (GspmdState(params, opt, ms, state.step + 1),
                {"loss": loss})

    if state_template is None:
        return jax.jit(step, donate_argnums=0)
    out_shardings = (fsdp_lib.state_out_shardings(state_template),
                     {"loss": meshlib.replicated(mesh)})
    return jax.jit(step, donate_argnums=0, out_shardings=out_shardings)


def make_gspmd_multi_step(model, mesh: Mesh,
                          tx: optax.GradientTransformation,
                          state_template: Optional[GspmdState] = None,
                          grad_accum: int = 1):
    """K GSPMD train steps per dispatch via ``lax.scan`` over stacked
    batches — the transformer counterpart of train/step.py's
    ``make_multi_train_step`` (amortizes per-dispatch latency; used by the
    benchmark harness).  ``batches``/``labels`` carry a leading (K,) axis on
    every leaf.  ``state_template`` as in ``make_gspmd_train_step`` — pins
    output shardings so FSDP states stay sharded across the scan."""
    one = make_gspmd_train_step(model, mesh, tx,
                                state_template=state_template,
                                grad_accum=grad_accum)

    def multi(state: GspmdState, batches, labels, rng):
        def body(s, xs):
            b, l = xs
            return one(s, b, l, rng)

        return lax.scan(body, state, (batches, labels))

    if state_template is None:
        return jax.jit(multi, donate_argnums=0)
    out_shardings = (fsdp_lib.state_out_shardings(state_template),
                     {"loss": meshlib.replicated(mesh)})
    return jax.jit(multi, donate_argnums=0, out_shardings=out_shardings)


def make_gspmd_eval_step(model, mesh: Mesh):
    """Forward-only logits (eval mode)."""

    def fwd(state: GspmdState, tokens):
        return model.apply(state.params, tokens, train=False)

    return jax.jit(fwd)
