"""Preemption-safe training: failure detection + graceful checkpoint.

The reference has no failure handling at all — a failed download raises an
undefined ``DownloadError`` NameError (mpipy.py:196-198) and any rank death
kills the whole MPI job with all progress lost (SURVEY.md §5 failure row).
TPU pods make this concrete: preemptible slices receive SIGTERM shortly
before eviction.

``PreemptionGuard`` turns that signal into a cooperative stop: the handler
only sets a flag (async-signal-safe), the training loop polls it at step
granularity, saves a checkpoint, and exits cleanly; ``--resume`` then
continues from the saved step.  ``request_stop()`` triggers the same path
programmatically (tests, notebook interrupts, external schedulers).

The SAME guard drives the serving engine's graceful drain
(serving/engine.py ``run(..., guard=...)``): SIGTERM stops admission,
in-flight sequences finish inside the drain budget, and the run reports
per-request drained-vs-shed outcomes.  ``installed()`` is the
context-manager form both entry points use — handlers are guaranteed
uninstalled on the way out, even when the serve/train body raises.
"""

from __future__ import annotations

import contextlib
import signal
import threading
from typing import Iterable, Optional


class PreemptionGuard:
    """Cooperative stop flag wired to OS signals.

    Usage::

        guard = PreemptionGuard.install()        # SIGTERM by default
        for step in range(n):
            ...
            if guard.should_stop:
                save_checkpoint(); break
        guard.uninstall()
    """

    def __init__(self) -> None:
        self._stop = threading.Event()
        self._prev: dict = {}
        self.reason: Optional[str] = None

    # -- flag --

    @property
    def should_stop(self) -> bool:
        return self._stop.is_set()

    def request_stop(self, reason: str = "requested") -> None:
        self.reason = self.reason or reason
        self._stop.set()

    # -- signal wiring --

    def _handler(self, signum, frame) -> None:
        self.request_stop(f"signal {signal.Signals(signum).name}")

    @classmethod
    def install(cls, signals: Iterable[int] = (signal.SIGTERM,)
                ) -> "PreemptionGuard":
        """Install handlers (main thread only — signal module requirement)
        and return the guard.  Previous handlers are preserved for
        ``uninstall``."""
        guard = cls()
        for s in signals:
            guard._prev[s] = signal.signal(s, guard._handler)
        return guard

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()

    # -- context-manager form --

    @classmethod
    @contextlib.contextmanager
    def installed(cls, signals: Iterable[int] = (signal.SIGTERM,)):
        """``with PreemptionGuard.installed() as guard:`` — install the
        handlers for the block and ALWAYS restore the previous ones,
        even when the guarded body raises (a serve loop that dies with
        handlers still hijacked would turn the supervisor's next SIGTERM
        into a silent no-op)."""
        guard = cls.install(signals=signals)
        try:
            yield guard
        finally:
            guard.uninstall()
