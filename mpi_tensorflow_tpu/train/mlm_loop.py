"""Host loop for the masked-LM family (BASELINE.json config 5).

Same shape as the image loop (train/loop.py) — 50-step trace, timing with
eval off the timed path — but driven by the GSPMD multi-axis step
(train/gspmd.py) and the synthetic MLM stream (data/synthetic.py).  The
printed metric is masked-token prediction error %, the MLM analogue of the
reference's test-error trace (mpipy.py:88).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import numpy as np

from mpi_tensorflow_tpu.config import Config
from mpi_tensorflow_tpu.data import synthetic
from mpi_tensorflow_tpu.models import bert
from mpi_tensorflow_tpu.parallel import mesh as meshlib
from mpi_tensorflow_tpu.train import gspmd
from mpi_tensorflow_tpu.train import optimizer as opt_lib
from mpi_tensorflow_tpu.utils import logging as logs
from mpi_tensorflow_tpu.utils.profiling import StepTimer


@dataclasses.dataclass
class MlmResult:
    state: Any
    history: list              # [(step, masked error %)]
    final_error: float
    tokens_per_sec: float
    step_time_seconds: float
    num_devices: int
    num_steps: int


def train_mlm(config: Config, bert_cfg: Optional[bert.BertConfig] = None,
              mesh=None, seq_len: int = 128, train_n: int = 4096,
              test_n: int = 512, learning_rate: float = 1e-4,
              lr_schedule: str = "warmup_linear",
              verbose: bool = True) -> MlmResult:
    mesh = mesh if mesh is not None else meshlib.make_mesh(config.mesh_shape)
    ndev = int(np.prod(list(mesh.shape.values())))
    if bert_cfg is None:
        import dataclasses as dc

        bert_cfg = dc.replace(bert.BERT_BASE, dtype=config.compute_dtype,
                              remat=config.remat)
    wp_vocab = None
    if getattr(config, "text_file", None) and \
            getattr(config, "vocab_file", None):
        from mpi_tensorflow_tpu.data import corpus

        # real vocabulary: the model's vocab axis adopts its size, so the
        # packed/chunked head trains at the true (e.g. 30522) width
        wp_vocab = corpus.WordPieceVocab.from_file(config.vocab_file)
        bert_cfg = dataclasses.replace(bert_cfg, vocab_size=wp_vocab.size)
    if config.model == "moe_bert":
        from mpi_tensorflow_tpu.models import moe

        if mesh.shape.get("pipe", 1) > 1:
            # MoE under PP: uniform expert layers pipelined over the pipe
            # axis (the plain MoeBertMlm would silently ignore the axis).
            # Architecturally DIFFERENT from the data-mesh default — say
            # so loudly: checkpoints and convergence numbers are not
            # comparable across the two meshes.
            print("[mlm_loop] moe_bert under a pipe mesh uses "
                  "PipelinedMoeBertMlm: every layer is MoE "
                  "(every_other=False) and the load-balance aux loss is "
                  "off — a different architecture from the data-mesh "
                  "default (MoE on odd layers, aux 0.01); checkpoints/"
                  "traces are not interchangeable between the two",
                  flush=True)
            model = moe.PipelinedMoeBertMlm(
                bert_cfg, mesh=mesh, schedule=config.pp_schedule,
                virtual_stages=config.virtual_stages)
        else:
            model = moe.MoeBertMlm(bert_cfg, mesh=mesh)
    elif config.model == "gpt_base":
        from mpi_tensorflow_tpu.models import gpt

        if mesh.shape.get("pipe", 1) > 1:
            # causal LM under PP (the plain CausalLm would silently
            # ignore the pipe axis); the pipelined loss consults
            # ce_positions directly, and packing is an MLM concept
            model = gpt.PipelinedCausalLm(
                dataclasses.replace(bert_cfg, ce_positions="all"),
                mesh=mesh, schedule=config.pp_schedule,
                virtual_stages=config.virtual_stages)
        else:
            model = gpt.CausalLm(bert_cfg, mesh=mesh)
    elif config.model == "encdec_t5":
        from mpi_tensorflow_tpu.models import encdec

        if any(v > 1 for k, v in mesh.shape.items()
               if k not in ("data", "model")):
            raise ValueError(
                f"the encoder-decoder family supports data x model "
                f"(Megatron TP) meshes only this round (mesh "
                f"{dict(mesh.shape)}); drop the other axes rather than "
                f"silently ignoring them")
        model = encdec.EncDecLm(bert_cfg)
    elif mesh.shape.get("pipe", 1) > 1:
        from mpi_tensorflow_tpu.models import bert_pipeline

        model = bert_pipeline.PipelinedBertMlm(
            bert_cfg, mesh=mesh, schedule=config.pp_schedule,
            virtual_stages=config.virtual_stages)
    else:
        model = bert.BertMlm(bert_cfg, mesh=mesh)

    enc_dec = config.model == "encdec_t5"
    if enc_dec:
        if getattr(config, "text_file", None):
            raise ValueError(
                "--text-file is a single-stream input; the encoder-"
                "decoder family trains on (src, tgt) pairs (synthetic "
                "reversal task)")
        # the synthetic reversal task: tgt = BOS + reverse(src) — forces
        # the decoder through cross-attention.  tokens/targets below hold
        # src/tgt; mask is unused (every tgt position carries loss)
        tokens, targets = synthetic.seq2seq_batches(
            train_n, src_len=seq_len, tgt_len=seq_len,
            vocab_size=bert_cfg.vocab_size, seed=config.seed)
        ts_tokens, ts_targets = synthetic.seq2seq_batches(
            test_n, src_len=seq_len, tgt_len=seq_len,
            vocab_size=bert_cfg.vocab_size, seed=config.seed + 1)
    elif getattr(config, "text_file", None):
        # real text, byte-level or WordPiece per --vocab-file
        # (data/corpus.py); the trailing rows become the held-out split
        from mpi_tensorflow_tpu.data import corpus

        if getattr(model, "causal", False):
            rows = corpus.load_causal(config.text_file, seq_len=seq_len,
                                      vocab_file=wp_vocab)
            inp, tgt_all = rows, rows
            msk = np.ones(rows.shape, bool)
        else:
            inp, tgt_all, msk = corpus.load_mlm(
                config.text_file, seq_len=seq_len, seed=config.seed,
                vocab_file=wp_vocab)
        n_test = max(len(inp) // 10, 1)
        train_n, test_n = len(inp) - n_test, n_test
        tokens, targets, mask = (inp[:train_n], tgt_all[:train_n],
                                 msk[:train_n])
        ts_tokens, ts_targets, ts_mask = (inp[train_n:], tgt_all[train_n:],
                                          msk[train_n:])
    else:
        tokens, targets, mask = synthetic.mlm_batches(
            train_n, seq_len=seq_len, vocab_size=bert_cfg.vocab_size,
            seed=config.seed)
        ts_tokens, ts_targets, ts_mask = synthetic.mlm_batches(
            test_n, seq_len=seq_len, vocab_size=bert_cfg.vocab_size,
            seed=config.seed + 1)

    b = config.batch_size * mesh.shape.get("data", 1)
    num_steps = config.epochs * (train_n // b)
    if num_steps == 0:
        raise ValueError(
            f"train split ({train_n} sequences) is smaller than one global "
            f"batch ({b}); lower --batch-size or provide more data")

    # warmup-linear adamw is the transformer default (VERDICT r2 #7: the
    # reference's exponential decay, mpipy.py:60-64, serves the image
    # families; adam needs warmup to survive its early-variance phase);
    # --optimizer lamb swaps in layer-wise trust ratios for large-batch
    # scale-out
    tx = opt_lib.transformer_tx(
        learning_rate, num_steps, schedule=lr_schedule,
        optimizer=getattr(config, "optimizer", "adamw"))
    ps = getattr(config, "param_sharding", "replicated")
    key0 = jax.random.key(config.seed)
    if ps != "replicated" and mesh.shape.get("data", 1) <= 1:
        # augment_spec is a no-op without a >1 'data' axis: training
        # would proceed fully replicated while the user believes the
        # ZeRO sharding engaged
        raise ValueError(
            f"--param-sharding {ps} shards over the 'data' mesh axis, "
            f"but this mesh has none (mesh {dict(mesh.shape)}); add "
            f"data=N or drop the flag")
    if ps == "fsdp":
        if mesh.shape.get("pipe", 1) > 1:
            # FSDP re-shards the stage params themselves over 'data',
            # breaking the pipeline schedules' shard_map layout contract
            raise ValueError(
                "--param-sharding fsdp does not compose with a 'pipe' "
                "mesh axis (stage params must keep the pipeline layout);"
                " use --param-sharding zero1, which shards only the "
                "optimizer moments")
        state = gspmd.init_fsdp_state(model, tx, key0, mesh)
    elif ps == "zero1":
        state = gspmd.init_zero1_state(model, tx, key0, mesh)
    else:
        state = gspmd.init_gspmd_state(model, tx, key0, mesh)
    train_step = gspmd.make_gspmd_train_step(
        model, mesh, tx,
        state_template=state if ps != "replicated" else None,
        grad_accum=getattr(config, "grad_accum", 1))
    eval_step = gspmd.make_gspmd_eval_step(model, mesh)

    from mpi_tensorflow_tpu.train.ckpt_hooks import CheckpointHooks

    hooks = CheckpointHooks(config.checkpoint_dir, verbose=verbose)
    from mpi_tensorflow_tpu.utils import metrics_writer

    mw = metrics_writer.for_process(config.metrics_dir,
                                    meshlib.process_index())
    start_step = 0
    if config.resume:
        state, start_step = hooks.resume(state)

    rng = config.make_train_key(config.seed + 2)
    timer = StepTimer(warmup_steps=1)
    history = []
    if verbose:
        logs.session_start(meshlib.process_index())

    causal = getattr(model, "causal", False)

    def _eval_index_batches():
        """(indices, valid) pairs: full (b,)-sized row-index batches over
        the SAMPLED test window (jit needs static shapes).  The window is
        capped at 4 global batches — held-out error is a sampled estimate
        on large splits, keeping eval off the timed path cheap.  Within
        the window a partial tail wrap-pads to b rows with ``valid``
        marking how many are real, so no window row is dropped or
        double-counted."""
        n = min(test_n, 4 * b)
        for i in range(0, n, b):
            take = min(b, n - i)
            yield np.resize(np.arange(i, i + take), b), take

    def masked_error(s) -> float:
        """Held-out error %: masked-position prediction error for the MLM
        families; next-token prediction error (position t predicts t+1)
        for the causal family; teacher-forced target-side next-token
        error for the encoder-decoder family."""
        errs, tot = 0, 0
        for idx, take in _eval_index_batches():
            if enc_dec:
                pair = gspmd.shard_batch(
                    {"src": ts_tokens[idx], "tgt": ts_targets[idx]}, mesh)
                logits = np.asarray(eval_step(s, pair))
                pred = logits.argmax(-1)[:take]
                tgt_rows = np.asarray(ts_targets[idx[:take]])
                errs += int((pred[:, :-1] != tgt_rows[:, 1:]).sum())
                tot += int(np.prod(tgt_rows[:, 1:].shape))
                continue
            tok = gspmd.shard_batch(ts_tokens[idx], mesh)
            logits = np.asarray(eval_step(s, tok))
            pred = logits.argmax(-1)[:take]
            real = idx[:take]
            if causal:
                tgt = np.asarray(ts_tokens[real])
                errs += int((pred[:, :-1] != tgt[:, 1:]).sum())
                tot += int(np.prod(tgt[:, 1:].shape))
            else:
                m = ts_mask[real]
                errs += int(((pred != ts_targets[real]) & m).sum())
                tot += int(m.sum())
        return 100.0 * errs / max(tot, 1)

    pending = 0
    timer.start()
    try:
        for t in range(start_step, num_steps):
            lo = (t * b) % max(train_n - b, 1)
            if enc_dec:
                batch = gspmd.shard_batch(
                    {"src": tokens[lo:lo + b], "tgt": targets[lo:lo + b]},
                    mesh)
            else:
                batch = gspmd.shard_batch(
                    {"tokens": tokens[lo:lo + b],
                     "mask": mask[lo:lo + b]}, mesh)
            tgt = gspmd.shard_batch(targets[lo:lo + b], mesh)
            state, metrics = train_step(state, batch, tgt, rng)
            pending += 1

            if hooks.stop_now(t):
                hooks.preempt_save(state, t)
                break

            last = t == num_steps - 1
            if (t > 0 and t % config.log_every == 0) or last:
                jax.block_until_ready(state)
                timer.stop(pending)
                pending = 0
                err = masked_error(state)
                history.append((t, err))
                mw.scalar("eval/heldout_error_pct", err, t)
                mw.scalar("train/loss", float(metrics["loss"]), t)
                if verbose:
                    logs.step_trace(meshlib.process_index(), t, err)
                hooks.save_async(state, t)
                if not last and hooks.stop_agreed(t):
                    hooks.preempt_save(state, t, already_queued=True)
                    break
                timer.start()
        sec_t = timer.mean_step_seconds
        if sec_t == sec_t and sec_t > 0:
            mw.scalar("perf/tokens_per_sec", b * seq_len / sec_t, num_steps)
    finally:
        hooks.close()
        mw.close()      # flush TB events even on an exceptional exit
    final_err = history[-1][1] if history else float("nan")
    sec = timer.mean_step_seconds
    tps = b * seq_len / sec if sec == sec and sec > 0 else float("nan")
    if verbose:
        logs.timing_summary(tps, sec * 1e3, ndev)
    return MlmResult(state=state, history=history, final_error=final_err,
                     tokens_per_sec=tps, step_time_seconds=sec,
                     num_devices=ndev, num_steps=num_steps)
