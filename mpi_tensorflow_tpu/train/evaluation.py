"""Batched evaluation — the ``eval_in_batches`` equivalent (mpipy.py:169-183).

Semantics preserved:
- raises if the dataset is smaller than one batch (mpipy.py:171-172);
- full batches evaluated in sequence; the tail is handled by re-running the
  final full window and slicing the overlap (mpipy.py:179-182) — on TPU this
  also keeps every compiled shape static (no recompilation for the tail);
- predictions are softmax probabilities (mpipy.py:68).

Aggregation: the reference scatters test data, so each rank reports error on
a *different* shard (SURVEY.md §3.5).  ``shard_error_rates`` reproduces that
per-shard trace; ``error_rate`` gives the correct global number.
"""

from __future__ import annotations

import numpy as np

from mpi_tensorflow_tpu.data.idx import error_rate  # re-export  # noqa: F401


def eval_in_batches(predict_fn, data, batch_size: int) -> np.ndarray:
    """Run ``predict_fn(batch) -> probs`` over ``data`` in fixed-size
    batches, tail via overlapped final window.  Bind params/model-state into
    ``predict_fn`` before calling."""
    size = data.shape[0]
    if size < batch_size:
        raise ValueError(
            "batch size for evals larger than dataset: %d" % size)
    out = None
    for begin in range(0, size, batch_size):
        end = begin + batch_size
        if end <= size:
            preds = np.asarray(predict_fn(data[begin:end]))
        else:
            preds = np.asarray(predict_fn(data[-batch_size:]))[begin - size:]
        if out is None:
            out = np.empty((size, preds.shape[-1]), dtype=np.float32)
        out[begin:begin + preds.shape[0]] = preds
    return out


def stack_eval_windows(data, batch_size: int):
    """Assemble the eval windows ``eval_in_batches`` would run — full
    batches plus the overlapped final window for the tail (mpipy.py:179-182)
    — into one ``(K, batch_size, ...)`` array for a single scanned dispatch.

    Returns ``(windows, starts)`` where ``starts[k]`` is the dataset row the
    k-th window's predictions belong at (the tail window's overlap rows are
    simply overwritten by design, exactly like the reference's slicing)."""
    size = data.shape[0]
    if size < batch_size:
        raise ValueError(
            "batch size for evals larger than dataset: %d" % size)
    starts = list(range(0, size - batch_size + 1, batch_size))
    if starts[-1] + batch_size < size:
        starts.append(size - batch_size)   # overlapped tail window
    windows = np.stack([np.asarray(data[s:s + batch_size]) for s in starts])
    return windows, starts


def eval_in_batches_fused(predict_multi_fn, data, batch_size: int
                          ) -> np.ndarray:
    """``eval_in_batches`` semantics in ONE device dispatch:
    ``predict_multi_fn(windows) -> (K, batch_size, C)`` scans the forward
    pass over staged windows (train/step.py make_multi_eval_step).  Per-
    dispatch latency dominates batchwise eval on small models (and utterly
    dominates through a tunneled device), so the host loop of the unfused
    path becomes a single call."""
    windows, starts = stack_eval_windows(data, batch_size)
    preds = np.asarray(predict_multi_fn(windows))
    out = np.empty((data.shape[0], preds.shape[-1]), dtype=np.float32)
    for k, s in enumerate(starts):
        out[s:s + batch_size] = preds[k]
    return out


def shard_error_rates(predictions: np.ndarray, labels: np.ndarray,
                      num_shards: int) -> list[float]:
    """Per-shard error %, matching the reference's per-rank printed trace
    (each rank holds a contiguous test shard, mpipy.py:88)."""
    n = predictions.shape[0] // num_shards * num_shards
    per = n // num_shards
    return [error_rate(predictions[i * per:(i + 1) * per],
                       labels[i * per:(i + 1) * per])
            for i in range(num_shards)]
